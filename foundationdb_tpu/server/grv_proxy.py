"""GrvProxy: the read-version endpoint.

Reference: fdbserver/GrvProxyServer.actor.cpp — queueGetReadVersionRequests
(:389) buckets incoming requests by priority; transactionStarter (:702)
releases them in batches under the Ratekeeper budget; each batch confirms
TLog-epoch liveness and asks the master for the max live committed version
(getLiveCommittedVersion :527), replying with that version (sendGrvReplies
:595).  The liveness confirm is what makes the read version *causally*
consistent: a version is only handed out after the current log system
quorum has acknowledged it is still the live epoch.
"""

from __future__ import annotations

from typing import Any, List, Optional

from ..core.futures import Promise, wait_all, wait_any
from ..core.knobs import server_knobs
from ..core.scheduler import delay, now, spawn
from ..core.trace import TraceEvent
from ..rpc.endpoint import RequestStream
from .interfaces import (GetRawCommittedVersionRequest, GetReadVersionReply,
                         GetReadVersionRequest, GrvProxyInterface,
                         TLogConfirmRunningRequest, TransactionPriority)


class GrvProxy:
    def __init__(self, proxy_id: str, master: Any,
                 tlogs: Optional[List[Any]] = None,
                 ratekeeper: Optional[Any] = None) -> None:
        self.id = proxy_id
        self.master = master            # MasterInterface
        self.tlogs = tlogs or []        # [TLogInterface] for liveness confirm
        self.ratekeeper = ratekeeper    # RatekeeperInterface (optional)
        self._rate = float("inf")       # tps budget from the ratekeeper
        self._batch_rate = float("inf")  # batch-priority budget (<= _rate)
        # Per-tag throttles from the ratekeeper (reference proxy-side
        # tag throttle enforcement): tag -> tps ceiling, token budget,
        # and a held-request queue per throttled tag.
        self._tag_rates: dict = {}
        self._tag_budgets: dict = {}
        self._tag_deferred: dict = {}
        self.tag_released: dict = {}    # tag -> total released (to RK)
        # Conflict predictor (sched/predictor.py, ISSUE 12): per-proxy
        # hot-range abort-probability table fed from the ratekeeper's
        # rate-info piggyback.  Admission defers a predicted-doomed
        # request (knob-bounded delay, starvation-proof max-defer count)
        # so it reads at a fresher version instead of resolving into a
        # near-certain abort.  Inert while SCHED_PREDICTOR_ENABLED is
        # off: no deferrals, no feed folding.
        from ..sched.predictor import ConflictPredictor
        self.predictor = ConflictPredictor.from_knobs(server_knobs())
        self._sched_deferred: list = []   # (release_at, req), release order
        self.interface = GrvProxyInterface(proxy_id)
        # Priority queues: immediate > default > batch (reference
        # SystemTransactionQueue/DefaultQueue/BatchQueue).
        self.queues: List[List[GetReadVersionRequest]] = [[], [], []]
        self.transaction_budget = float("inf")
        self.batch_budget = float("inf")
        self._wait_failure_actor = None
        self.stats = {"grvs": 0, "batches": 0}
        from ..core.histogram import CounterCollection
        self.metrics = CounterCollection("GrvProxy", proxy_id)
        self.interface.role = self   # sim-side backref for status/tests
        self._wakeup: Optional[Promise] = None

    async def _queue_requests(self) -> None:
        async for req in self.interface.get_consistent_read_version.queue:
            pri = min(max(req.priority, TransactionPriority.BATCH),
                      TransactionPriority.IMMEDIATE)
            # Arrival stamp for the QueueWait latency band (reference
            # GrvProxyStats grvLatencyBands: time spent queued under the
            # ratekeeper budget, measured per request, emitted
            # periodically — no per-request TraceEvent).
            req._t_queued = now()
            self.queues[pri].append(req)
            if self._wakeup is not None:
                w, self._wakeup = self._wakeup, None
                w.send(None)

    def _drain(self, budget: float, batch_budget: float):
        """Release requests: IMMEDIATE always (and exempt from ratekeeper
        accounting, as in the reference); DEFAULT while the normal budget
        remains; BATCH only while BOTH the normal and the batch budget
        remain (reference GrvProxyServer.actor.cpp:702 — batch releases
        draw from a separate, smaller allowance, so a batch flood can
        never starve default traffic: the batch limit collapses first
        under load and default is always drained ahead of batch).
        Returns (released, charged, batch_charged) so overdrafts carry
        forward as debt per bucket."""
        out: List[GetReadVersionRequest] = []
        charged = 0
        batch_charged = 0
        q = self.queues[TransactionPriority.IMMEDIATE]
        while q:
            out.append(q.pop(0))

        def tag_blocked(req) -> bool:
            """A throttled tag with an exhausted token bucket holds the
            request in a per-tag side queue (reference: tagged GRVs wait
            out their throttle at the proxy, not in the main queue, so
            untagged traffic flows past them)."""
            for tag in getattr(req, "tags", ()) or ():
                if tag in self._tag_rates and \
                        self._tag_budgets.get(tag, 0.0) <= 0.0:
                    self._tag_deferred.setdefault(tag, []).append(req)
                    return True
            return False

        def sched_blocked(req) -> bool:
            """Predictor deferral (sched stage a): a request whose
            declared tag/tenant maps to a predicted-doomed range waits
            out a short deterministic delay in a side queue instead of
            burning a guaranteed resolve-and-abort round trip.  At most
            SCHED_MAX_DEFERRALS deferrals per request — then it is
            admitted unconditionally (starvation-proof)."""
            knobs = server_knobs()
            if not knobs.SCHED_PREDICTOR_ENABLED:
                return False
            defers = getattr(req, "_sched_defers", 0)
            if defers >= int(knobs.SCHED_MAX_DEFERRALS):
                return False
            if not self.predictor.is_doomed(
                    getattr(req, "tags", ()) or (),
                    getattr(req, "tenant_id", -1)):
                return False
            req._sched_defers = defers + 1
            self._sched_deferred.append(
                (now() + float(knobs.SCHED_ADMISSION_DELAY_S), req))
            self.metrics.counter("SchedDeferrals").add(1)
            from ..core.coverage import test_coverage
            test_coverage("GrvSchedDeferral")
            return True

        def charge_tags(req) -> None:
            # Only THROTTLED tags are tracked/reported: tags are arbitrary
            # client strings, so unconditional accounting would grow
            # per-tag state (and every rate-info payload) without bound.
            for tag in getattr(req, "tags", ()) or ():
                if tag not in self._tag_rates:
                    continue
                self.tag_released[tag] = self.tag_released.get(tag, 0) + \
                    req.transaction_count
                if tag in self._tag_budgets:
                    self._tag_budgets[tag] -= req.transaction_count

        q = self.queues[TransactionPriority.DEFAULT]
        while q and budget - charged > 0:
            req = q.pop(0)
            if tag_blocked(req) or sched_blocked(req):
                continue
            charge_tags(req)
            out.append(req)
            charged += req.transaction_count
        q = self.queues[TransactionPriority.BATCH]
        while q and budget - charged > 0 and \
                batch_budget - batch_charged > 0:
            req = q.pop(0)
            if tag_blocked(req) or sched_blocked(req):
                continue
            charge_tags(req)
            out.append(req)
            charged += req.transaction_count
            batch_charged += req.transaction_count
        return out, charged, batch_charged

    def _requeue_front(self, reqs) -> None:
        """Re-admit previously deferred requests at the FRONT of their
        priority queue, original order preserved (shared by the tag-
        throttle and predictor deferral paths — a deferred request waits
        out its hold once, never behind fresh arrivals)."""
        for req in reversed(list(reqs)):
            pri = min(max(req.priority, TransactionPriority.BATCH),
                      TransactionPriority.IMMEDIATE)
            self.queues[pri].insert(0, req)

    async def _transaction_starter(self) -> None:
        from ..core.scheduler import now
        knobs = server_knobs()
        last = now()
        # True after a drain pass released NOTHING while requests were
        # still queued (token bucket empty): the next pass then waits the
        # MAX batch interval instead of MIN.  Without this, a starved
        # queue polls at INTERVAL_MIN (1us of virtual time) until budget
        # accrues — ~500k wasted scheduler dispatches per virtual second
        # whenever the ratekeeper clamps the rate (exactly what chaos
        # runs do to it; found via the unseed digest's fold counts).
        starved = False
        while True:
            if not any(self.queues) and \
                    not any(self._tag_deferred.values()) and \
                    not self._sched_deferred:
                # Sleep until a request arrives (no virtual-time polling).
                self._wakeup = Promise()
                await self._wakeup.get_future()
                starved = False
            # Recomputed AFTER the park: new deferrals may have arrived
            # while we slept (and the park condition already consumed
            # the pre-await state).
            have_deferred = any(self._tag_deferred.values()) or \
                bool(self._sched_deferred)
            # Tag-deferred requests wait on token accrual, not on new
            # arrivals: poll at a coarse interval instead of parking.
            await delay(0.05 if have_deferred and not any(self.queues)
                        else (knobs.START_TRANSACTION_BATCH_INTERVAL_MAX
                              if starved else
                              knobs.START_TRANSACTION_BATCH_INTERVAL_MIN))
            # Token bucket: accrue budget at the ratekeeper's tps, capped
            # at one lease's worth (reference transactionStarter :702).
            t = now()
            if self._rate != float("inf"):
                self.transaction_budget = min(
                    self.transaction_budget + self._rate * (t - last),
                    self._rate)
            else:
                self.transaction_budget = float("inf")
            if self._batch_rate != float("inf"):
                self.batch_budget = min(
                    self.batch_budget + self._batch_rate * (t - last),
                    self._batch_rate)
            else:
                self.batch_budget = float("inf")
            # Per-tag token buckets accrue at the throttle tps, capped at
            # one second's worth; deferred holders re-enter their priority
            # queue once their tag has budget again.
            for tag, rate in self._tag_rates.items():
                self._tag_budgets[tag] = min(
                    self._tag_budgets.get(tag, 0.0) + rate * (t - last),
                    max(rate, 1.0))
            for tag, held in list(self._tag_deferred.items()):
                if held and (tag not in self._tag_rates or
                             self._tag_budgets.get(tag, 0.0) > 0.0):
                    self._requeue_front(held)
                    held.clear()
            # Predictor deferrals whose delay has elapsed re-enter their
            # priority queue at the FRONT (append order preserved): a
            # deferred request waits its knob-bounded delay once per
            # deferral, never behind fresh arrivals.
            if self._sched_deferred:
                due = [r for at, r in self._sched_deferred if at <= t]
                if due:
                    self._sched_deferred = [
                        (at, r) for at, r in self._sched_deferred if at > t]
                    self._requeue_front(due)
            last = t
            batch, charged, batch_charged = self._drain(
                self.transaction_budget, self.batch_budget)
            if not batch:
                starved = bool(any(self.queues))
                continue
            starved = False
            if self.transaction_budget != float("inf"):
                # Deficit carries forward (may go negative): overdraft now
                # means fewer releases later, keeping the long-run rate at
                # the ratekeeper's tps.
                self.transaction_budget -= charged
            if self.batch_budget != float("inf"):
                self.batch_budget -= batch_charged
            self.stats["batches"] += 1
            self._process.spawn(self._reply_batch(batch),
                                f"{self.id}.grvBatch")

    async def _rate_updater(self) -> None:
        """Fetch the tps budget from the ratekeeper (reference getRate
        loop :288); on ratekeeper silence the last lease keeps being used
        (and eventually recovery replaces everyone anyway)."""
        from ..core.error import FdbError
        from .ratekeeper import GetRateInfoRequest
        while True:
            try:
                reply = await RequestStream.at(
                    self.ratekeeper.get_rate_info.endpoint).get_reply(
                    GetRateInfoRequest(proxy_id=self.id,
                                       total_released=self.stats["grvs"],
                                       tag_released=dict(self.tag_released)))
                self._rate = reply.tps
                self._batch_rate = min(reply.batch_tps, reply.tps)
                heat = getattr(reply, "conflict_heat", None)
                if heat is not None:
                    # Fold the piggybacked resolver heat rows into this
                    # proxy's predictor table (sched stage a).
                    self.predictor.update(heat)
                new_tags = reply.tag_throttles or {}
                for tag in new_tags:
                    if tag not in self._tag_rates:
                        # Fresh throttle starts with an empty bucket.
                        self._tag_budgets.setdefault(tag, 0.0)
                for tag in list(self._tag_budgets):
                    if tag not in new_tags:
                        del self._tag_budgets[tag]
                # Expired throttles drop ALL their per-tag state (tags are
                # unbounded client strings; kept entries would accrete for
                # the proxy's lifetime).  Deferred holders re-enter the
                # main queues via the starter's re-injection pass.
                for tag in list(self.tag_released):
                    if tag not in new_tags:
                        del self.tag_released[tag]
                for tag in list(self._tag_deferred):
                    if tag not in new_tags and not self._tag_deferred[tag]:
                        del self._tag_deferred[tag]
                self._tag_rates = new_tags
                wait = reply.lease_duration / 2
            except FdbError:
                wait = 0.5
            await delay(wait)

    async def _reply_batch(self, batch: List[GetReadVersionRequest]) -> None:
        from ..core.error import FdbError, err
        _t0 = now()
        # Confirm log-system liveness + fetch live committed version in
        # parallel (reference getLiveCommittedVersion :527).
        confirms = [RequestStream.at(t.confirm_running.endpoint).get_reply(
            TLogConfirmRunningRequest()) for t in self.tlogs]
        version_f = RequestStream.at(
            self.master.get_live_committed_version.endpoint).get_reply(
            GetRawCommittedVersionRequest())
        try:
            # Bounded wait (reference TLOG_TIMEOUT in getLiveCommittedVersion):
            # a confirm that neither replies nor errors — its request parked
            # behind a displaced log generation — must read as epoch death,
            # not wedge this proxy's GRV plane forever.
            guard = delay(server_knobs().TLOG_CONFIRM_TIMEOUT_S)
            waits = ([wait_all(confirms)] if confirms else []) + [version_f]
            for f in waits:
                if not f.is_ready():
                    await wait_any([f, guard])
                if f.is_error():
                    raise f.error
                if not f.is_ready():
                    raise err("timed_out", "tlog liveness confirm timed out")
            vreply = version_f.get()
        except FdbError as e:
            # A failed liveness confirm means our log generation is locked
            # or dead: this proxy must DIE VISIBLY (reference: GRV proxies
            # die on tlog_failed, taking the master with them so the CC
            # recruits a fresh epoch).  Observed deadlock without this: a
            # superseded epoch keeps timing out every GRV forever while
            # its master never ends.
            TraceEvent("GrvProxyBatchFailed").detail(
                "Proxy", self.id).detail("Error", e.name).log()
            if self._wait_failure_actor is not None and \
                    not self._wait_failure_actor.is_ready():
                self._wait_failure_actor.cancel()
            return
        # Client-side GRV batching (ISSUE 14): one request may carry N
        # transactions (transaction_count); released/started accounting
        # charges the true count so the ratekeeper's smoothed-release
        # rate stays exact (identical to len(batch) when every request
        # carries count 1, i.e. with client batching off).
        n_txns = 0
        n_batched = 0
        for req in batch:
            c = max(1, int(getattr(req, "transaction_count", 1) or 1))
            n_txns += c
            if c > 1:
                n_batched += 1
        self.stats["grvs"] += n_txns
        self.metrics.counter("TxnStarted").add(n_txns)
        if n_batched:
            self.metrics.counter("ClientBatchedGrvRequests").add(n_batched)
        # Separate bands: QueueWait ends at batch formation (_t0) — time
        # spent held under the ratekeeper budget — while GRVLatency is
        # the reply path from there (liveness confirm + master version
        # fetch, ours).  Measuring the queue to reply completion would
        # make a slow master read as ratekeeper throttling.
        self.metrics.histogram("GRVLatency").record(now() - _t0)
        qw = self.metrics.histogram("QueueWait")
        for req in batch:
            t_in = getattr(req, "_t_queued", None)
            if t_in is not None:
                qw.record(max(_t0 - t_in, 0.0))
        throttles = dict(self._tag_rates) if self._tag_rates else None
        from ..core.trace import trace_batch_event
        for req in batch:
            if req.debug_id:
                # GRV hop of the cross-role commit timeline
                # (tools/commit_debug.py; reference g_traceBatch
                # "TransactionDebug" points at the GRV proxy).
                trace_batch_event("TransactionDebug", req.debug_id,
                                  "GrvProxy.reply")
            req.reply.send(GetReadVersionReply(version=vreply.version,
                                               locked=vreply.locked,
                                               tag_throttles=throttles))

    def scheduler_status(self) -> dict:
        """This proxy's slice of status cluster.scheduler: predictor
        table + deferral counters (the \xff\xff/metrics/scheduler/ and
        fdbcli `metrics` surfaces render the same document)."""
        doc = self.predictor.status()
        doc["deferrals"] = self.metrics.counter("SchedDeferrals").value
        doc["deferred_held"] = len(self._sched_deferred)
        return doc

    def run(self, process) -> None:
        self._process = process
        for s in self.interface.streams():
            process.register(s)
        process.spawn(self._queue_requests(), f"{self.id}.queue")
        process.spawn(self.metrics.emit_loop(), f"{self.id}.metrics")
        process.spawn(self._transaction_starter(), f"{self.id}.starter")
        if self.ratekeeper is not None:
            process.spawn(self._rate_updater(), f"{self.id}.rateUpdater")
        from .failure import hold_wait_failure
        self._wait_failure_actor = process.spawn(
            hold_wait_failure(self.interface.wait_failure),
            f"{self.id}.waitFailure")
        TraceEvent("GrvProxyStarted").detail("Id", self.id).log()
