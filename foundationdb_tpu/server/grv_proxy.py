"""GrvProxy: the read-version endpoint.

Reference: fdbserver/GrvProxyServer.actor.cpp — queueGetReadVersionRequests
(:389) buckets incoming requests by priority; transactionStarter (:702)
releases them in batches under the Ratekeeper budget; each batch confirms
TLog-epoch liveness and asks the master for the max live committed version
(getLiveCommittedVersion :527), replying with that version (sendGrvReplies
:595).  The liveness confirm is what makes the read version *causally*
consistent: a version is only handed out after the current log system
quorum has acknowledged it is still the live epoch.
"""

from __future__ import annotations

from typing import Any, List, Optional

from ..core.futures import Promise, wait_all
from ..core.knobs import server_knobs
from ..core.scheduler import delay, now, spawn
from ..core.trace import TraceEvent
from ..rpc.endpoint import RequestStream
from .interfaces import (GetRawCommittedVersionRequest, GetReadVersionReply,
                         GetReadVersionRequest, GrvProxyInterface,
                         TLogConfirmRunningRequest, TransactionPriority)


class GrvProxy:
    def __init__(self, proxy_id: str, master: Any,
                 tlogs: Optional[List[Any]] = None,
                 ratekeeper: Optional[Any] = None) -> None:
        self.id = proxy_id
        self.master = master            # MasterInterface
        self.tlogs = tlogs or []        # [TLogInterface] for liveness confirm
        self.ratekeeper = ratekeeper    # Ratekeeper client handle (optional)
        self.interface = GrvProxyInterface(proxy_id)
        # Priority queues: immediate > default > batch (reference
        # SystemTransactionQueue/DefaultQueue/BatchQueue).
        self.queues: List[List[GetReadVersionRequest]] = [[], [], []]
        self.transaction_budget = float("inf")
        self.stats = {"grvs": 0, "batches": 0}
        self._wakeup: Optional[Promise] = None

    async def _queue_requests(self) -> None:
        async for req in self.interface.get_consistent_read_version.queue:
            pri = min(max(req.priority, TransactionPriority.BATCH),
                      TransactionPriority.IMMEDIATE)
            self.queues[pri].append(req)
            if self._wakeup is not None:
                w, self._wakeup = self._wakeup, None
                w.send(None)

    def _drain(self, budget: float) -> List[GetReadVersionRequest]:
        out: List[GetReadVersionRequest] = []
        for pri in (TransactionPriority.IMMEDIATE,
                    TransactionPriority.DEFAULT, TransactionPriority.BATCH):
            q = self.queues[pri]
            while q and (budget > 0 or pri == TransactionPriority.IMMEDIATE):
                req = q.pop(0)
                out.append(req)
                budget -= req.transaction_count
        return out

    async def _transaction_starter(self) -> None:
        knobs = server_knobs()
        while True:
            if not any(self.queues):
                # Sleep until a request arrives (no virtual-time polling).
                self._wakeup = Promise()
                await self._wakeup.get_future()
            await delay(knobs.START_TRANSACTION_BATCH_INTERVAL_MIN)
            if self.ratekeeper is not None:
                self.transaction_budget = self.ratekeeper.current_budget(
                    self.id)
            batch = self._drain(self.transaction_budget)
            if not batch:
                continue
            self.stats["batches"] += 1
            spawn(self._reply_batch(batch), f"{self.id}.grvBatch")

    async def _reply_batch(self, batch: List[GetReadVersionRequest]) -> None:
        # Confirm log-system liveness + fetch live committed version in
        # parallel (reference getLiveCommittedVersion :527).
        confirms = [RequestStream.at(t.confirm_running.endpoint).get_reply(
            TLogConfirmRunningRequest()) for t in self.tlogs]
        version_f = RequestStream.at(
            self.master.get_live_committed_version.endpoint).get_reply(
            GetRawCommittedVersionRequest())
        if confirms:
            await wait_all(confirms)
        vreply = await version_f
        self.stats["grvs"] += len(batch)
        if self.ratekeeper is not None:
            self.ratekeeper.report_released(self.id, len(batch))
        for req in batch:
            req.reply.send(GetReadVersionReply(version=vreply.version,
                                               locked=vreply.locked))

    def run(self, process) -> None:
        for s in self.interface.streams():
            process.register(s)
        process.spawn(self._queue_requests(), f"{self.id}.queue")
        process.spawn(self._transaction_starter(), f"{self.id}.starter")
        from .failure import hold_wait_failure
        process.spawn(hold_wait_failure(self.interface.wait_failure),
                      f"{self.id}.waitFailure")
        TraceEvent("GrvProxyStarted").detail("Id", self.id).log()
