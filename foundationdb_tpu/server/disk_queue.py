"""DiskQueue: a durable, checksummed log of records with recovery scan.

Reference: fdbserver/DiskQueue.actor.cpp (+ IDiskQueue.h) — the durable
ring buffer under the TLog and the memory storage engine's WAL: records
are appended with checksums, commit() makes the prefix durable (fsync),
pop() trims acknowledged prefixes, and recovery scans forward validating
checksums, stopping at the first torn/corrupt record — so exactly a
durable PREFIX of pushed records survives a power loss.

Record framing (little-endian): MAGIC:2 | seq:8 | popped:8 | len:4 | crc:4
| payload.  `popped` persists the trim frontier piggybacked on appends
(the reference stores it in page headers).
"""

from __future__ import annotations

import struct
import zlib
from typing import List, Optional, Tuple

from ..core.trace import Severity, TraceEvent
from .sim_fs import SimFile

_MAGIC = 0xFDB1
_HDR = struct.Struct("<HQQII")


class DiskQueue:
    def __init__(self, file: SimFile) -> None:
        self.file = file
        self.next_seq = 1
        self.popped_seq = 0          # records <= this are logically gone
        self._write_offset = 0
        self._pending: List[bytes] = []
        # seq -> (payload offset, payload length): random access for
        # spill-by-reference readers (the TLog serves peeks of spilled
        # tags straight from the queue file; reference TLogServer spill
        # reads via IDiskQueue::read).  Entries drop at pop().
        self._index: dict = {}
        self._pending_offset = 0

    # -- write path ----------------------------------------------------------
    def push(self, payload: bytes) -> int:
        """Append one record (buffered until commit); returns its seq."""
        seq = self.next_seq
        self.next_seq += 1
        crc = zlib.crc32(payload)
        frame = _HDR.pack(_MAGIC, seq, self.popped_seq,
                          len(payload), crc) + payload
        self._index[seq] = (self._write_offset + self._pending_offset +
                            _HDR.size, len(payload))
        self._pending_offset += len(frame)
        self._pending.append(frame)
        return seq

    async def read_payload(self, seq: int) -> Optional[bytes]:
        """Read one DURABLE record's payload by seq (spilled-tag peeks).
        None if unknown or already popped."""
        loc = self._index.get(seq)
        if loc is None or seq <= self.popped_seq:
            return None
        offset, length = loc
        if offset + length > self._write_offset:
            return None            # not yet committed to the file
        return await self.file.read(offset, length)

    async def commit(self) -> None:
        """Write buffered records and fsync (reference group commit)."""
        if self._pending:
            blob = b"".join(self._pending)
            self._pending = []
            self._pending_offset = 0
            await self.file.write(self._write_offset, blob)
            self._write_offset += len(blob)
        await self.file.sync()

    def pop(self, up_to_seq: int) -> None:
        """Trim records <= seq (durably recorded with the NEXT append, as
        in the reference's lazy page-header update)."""
        if up_to_seq > self.popped_seq:
            self.popped_seq = up_to_seq
            for seq in [s for s in self._index if s <= up_to_seq]:
                del self._index[seq]

    # -- recovery (reference recovery scan) ----------------------------------
    async def recover(self) -> List[Tuple[int, bytes]]:
        """Scan from the start; return surviving un-popped records in order.
        Stops at the first invalid/torn record: everything before it was
        durable, everything after never fully reached disk."""
        size = self.file.size()
        offset = 0
        records: List[Tuple[int, bytes]] = []
        max_popped = 0
        last_seq = 0
        while offset + _HDR.size <= size:
            hdr = await self.file.read(offset, _HDR.size)
            magic, seq, popped, length, crc = _HDR.unpack(hdr)
            if magic != _MAGIC or seq != last_seq + 1:
                break
            if offset + _HDR.size + length > size:
                break                      # torn tail
            payload = await self.file.read(offset + _HDR.size, length)
            if zlib.crc32(payload) != crc:
                break                      # corrupt tail
            records.append((seq, payload))
            self._index[seq] = (offset + _HDR.size, length)
            max_popped = max(max_popped, popped)
            last_seq = seq
            offset += _HDR.size + length
        self.next_seq = last_seq + 1
        self.popped_seq = max_popped
        for seq in [s for s in self._index if s <= max_popped]:
            del self._index[seq]
        self._write_offset = offset
        # Anything beyond the valid prefix is garbage from a torn write:
        # discard it so future appends are consistent.
        await self.file.truncate(offset)
        await self.file.sync()
        out = [(s, p) for s, p in records if s > max_popped]
        TraceEvent("DiskQueueRecovered").detail(
            "File", self.file.name).detail("Records", len(out)).detail(
            "NextSeq", self.next_seq).detail("Popped", max_popped).log()
        return out
