"""DiskQueue: a durable, checksummed log of records with recovery scan.

Reference: fdbserver/DiskQueue.actor.cpp (+ IDiskQueue.h) — the durable
ring buffer under the TLog and the memory storage engine's WAL: records
are appended with checksums, commit() makes the prefix durable (fsync),
pop() trims acknowledged prefixes, and recovery scans forward validating
checksums, stopping at the first torn/corrupt record — so exactly a
durable PREFIX of pushed records survives a power loss.

Record framing (little-endian): MAGIC:2 | seq:8 | popped:8 | len:4 | crc:4
| payload.  `popped` persists the trim frontier piggybacked on appends
(the reference stores it in page headers).  The crc spans the header
fields AND the payload, so bit-rot anywhere in a frame — including the
trim frontier — fails validation.
"""

from __future__ import annotations

import struct
import zlib
from typing import List, Optional, Tuple

from ..core.coverage import test_coverage
from ..core.error import err
from ..core.trace import Severity, TraceEvent
from .sim_fs import SimFile

_MAGIC = 0xFDB1
_HDR = struct.Struct("<HQQII")
# The CRC covers the header fields AND the payload (reference DiskQueue
# page checksums span the whole page): a bit flipped in `popped` or
# `seq` must be as detectable as one in the payload — the trim frontier
# rides in headers, and silently corrupting it drops records.
_HDR_CRC = struct.Struct("<HQQI")


def _frame_crc(seq: int, popped: int, payload: bytes) -> int:
    return zlib.crc32(payload, zlib.crc32(
        _HDR_CRC.pack(_MAGIC, seq, popped, len(payload))))


class DiskQueue:
    def __init__(self, file: SimFile) -> None:
        self.file = file
        self.next_seq = 1
        self.popped_seq = 0          # records <= this are logically gone
        self._write_offset = 0
        self._pending: List[bytes] = []
        # seq -> (payload offset, payload length): random access for
        # spill-by-reference readers (the TLog serves peeks of spilled
        # tags straight from the queue file; reference TLogServer spill
        # reads via IDiskQueue::read).  Entries drop at pop().
        self._index: dict = {}
        self._pending_offset = 0

    # -- write path ----------------------------------------------------------
    def push(self, payload: bytes) -> int:
        """Append one record (buffered until commit); returns its seq."""
        seq = self.next_seq
        self.next_seq += 1
        crc = _frame_crc(seq, self.popped_seq, payload)
        frame = _HDR.pack(_MAGIC, seq, self.popped_seq,
                          len(payload), crc) + payload
        self._index[seq] = (self._write_offset + self._pending_offset +
                            _HDR.size, len(payload))
        self._pending_offset += len(frame)
        self._pending.append(frame)
        return seq

    async def read_payload(self, seq: int) -> Optional[bytes]:
        """Read one DURABLE record's payload by seq (spilled-tag peeks).
        None if unknown or already popped.

        The frame's CRC is re-verified on EVERY live read, not just at
        recovery: post-sync bit-rot (sim_fs DiskFaultProfile) can land in
        a record long after its durability was acked, and a spilled-tag
        peek is the first reader to touch it again.  Corruption raises
        io_error — the TLog converts that to process death (never serve
        corrupt data; reference checksum failure is process-fatal)."""
        loc = self._index.get(seq)
        if loc is None or seq <= self.popped_seq:
            return None
        offset, length = loc
        if offset + length > self._write_offset:
            return None            # not yet committed to the file
        hdr = await self.file.read(offset - _HDR.size, _HDR.size)
        payload = await self.file.read(offset, length)
        magic, hseq, popped, hlen, crc = _HDR.unpack(hdr)
        if magic != _MAGIC or hseq != seq or hlen != length or \
                _frame_crc(hseq, popped, payload) != crc:
            test_coverage("DiskQueueCrcCaught")
            TraceEvent("DiskQueueCorruptRecord", Severity.Error).detail(
                "File", self.file.name).detail("Seq", seq).detail(
                "Offset", offset).log()
            raise err("io_error",
                      f"disk queue record {seq} failed CRC in "
                      f"{self.file.name}")
        return payload

    async def commit(self) -> None:
        """Write buffered records and fsync (reference group commit)."""
        if self._pending:
            blob = b"".join(self._pending)
            self._pending = []
            self._pending_offset = 0
            await self.file.write(self._write_offset, blob)
            self._write_offset += len(blob)
        await self.file.sync()

    def pop(self, up_to_seq: int) -> None:
        """Trim records <= seq (durably recorded with the NEXT append, as
        in the reference's lazy page-header update)."""
        if up_to_seq > self.popped_seq:
            self.popped_seq = up_to_seq
            for seq in [s for s in self._index if s <= up_to_seq]:
                del self._index[seq]

    # -- recovery (reference recovery scan) ----------------------------------
    async def recover(self) -> List[Tuple[int, bytes]]:
        """Scan from the start; return surviving un-popped records in order.
        Stops at the first invalid/torn record: everything before it was
        durable, everything after never fully reached disk."""
        size = self.file.size()
        offset = 0
        records: List[Tuple[int, bytes]] = []
        max_popped = 0
        last_seq = 0
        while offset + _HDR.size <= size:
            hdr = await self.file.read(offset, _HDR.size)
            magic, seq, popped, length, crc = _HDR.unpack(hdr)
            if magic != _MAGIC or seq != last_seq + 1:
                break
            if offset + _HDR.size + length > size:
                break                      # torn tail
            payload = await self.file.read(offset + _HDR.size, length)
            if _frame_crc(seq, popped, payload) != crc:
                # Corrupt record: recovery keeps the valid prefix only
                # (torn tail OR mid-file rot — either way nothing past an
                # invalid frame may be trusted or served).
                test_coverage("DiskQueueCrcCaught")
                TraceEvent("DiskQueueCrcMismatch", Severity.Warn).detail(
                    "File", self.file.name).detail("Seq", seq).log()
                break                      # corrupt tail
            records.append((seq, payload))
            self._index[seq] = (offset + _HDR.size, length)
            max_popped = max(max_popped, popped)
            last_seq = seq
            offset += _HDR.size + length
        self.next_seq = last_seq + 1
        self.popped_seq = max_popped
        for seq in [s for s in self._index if s <= max_popped]:
            del self._index[seq]
        self._write_offset = offset
        # Anything beyond the valid prefix is garbage from a torn write:
        # discard it so future appends are consistent.
        await self.file.truncate(offset)
        await self.file.sync()
        out = [(s, p) for s, p in records if s > max_popped]
        TraceEvent("DiskQueueRecovered").detail(
            "File", self.file.name).detail("Records", len(out)).detail(
            "NextSeq", self.next_seq).detail("Popped", max_popped).log()
        return out
