"""fdbserver: one real OS process of the cluster.

Reference: fdbserver/fdbserver.actor.cpp:1655 main / worker.actor.cpp:2365
fdbd() — a process locks its data dir, optionally serves coordination,
campaigns for (or monitors) the cluster controller through the
coordinators, and runs workerServer so the CC can recruit any role onto it.

This is the REAL deployment plane: the same Worker / ClusterController /
Coordination code that runs under deterministic simulation runs here over
the real-IO reactor (core/scheduler.py) and the real TCP network
(rpc/real_network.py).  Start one process per role-capable node:

    python -m foundationdb_tpu.server.fdbserver \
        --port 4500 --coordinators 127.0.0.1:4500 \
        --datadir /tmp/fdb0 --class coordinator [--config '{"...": ...}']

The first coordinator-class process whose --port appears in --coordinators
serves the generation registers; stateless workers campaign for CC; the
winning CC recruits master/proxies/resolvers/TLogs/storage exactly as in
simulation.  Clients connect with client.database.connect("host:port,...").
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from ..core.futures import AsyncVar
from ..core.rng import DeterministicRandom, set_deterministic_random
from ..core.scheduler import EventLoop, set_event_loop
from ..core.trace import TraceEvent
from ..rpc.endpoint import NetworkAddress
from ..rpc.network import set_network
from ..rpc.real_network import RealNetwork, RealProcess
from .coordination import (CoordinationClientInterface, CoordinationServer,
                           monitor_leader, try_become_leader)
from .real_fs import RealFileSystem


def parse_coordinators(spec: str) -> List[NetworkAddress]:
    out = []
    for part in spec.split(","):
        host, port = part.strip().rsplit(":", 1)
        out.append(NetworkAddress(host, int(port)))
    return out


def build_config(config_json: Optional[str]):
    from .interfaces import DatabaseConfiguration
    cfg = DatabaseConfiguration()
    if config_json:
        for k, v in json.loads(config_json).items():
            setattr(cfg, k, v)
    return cfg


async def _cc_runner(process, cc, leader_var, my_change_id) -> None:
    """Run the CC role while this process holds leadership; halt on
    deposition (mirrors SimFdbCluster._cc_runner)."""
    started = False
    while True:
        leader = leader_var.get()
        is_me = leader is not None and leader.change_id == my_change_id
        if is_me and not started:
            cc.run(process)
            started = True
        elif not is_me and started:
            cc.halt()
            started = False
        await leader_var.on_change()


def serve(port: int, coordinators: List[NetworkAddress], datadir: str,
          process_class: str = "stateless", config=None,
          ip: str = "127.0.0.1", name: str = "", seed: int = 0,
          force_coordination: bool = False,
          tls: Optional[dict] = None) -> None:
    """Boot this process and serve forever."""
    from .cluster_controller import ClusterController
    from .worker import Worker

    import os
    from ..core.knobs import get_knobs
    from ..core.trace import Tracer, set_tracer
    os.makedirs(datadir, exist_ok=True)
    # Rolling trace output (reference FileTraceLogWriter): the active
    # trace.0.jsonl rolls to trace.1.jsonl (... keep-N) past the size
    # knob, and flushes every few events so a crash leaves usable traces.
    flow = get_knobs().flow
    set_tracer(Tracer(path=os.path.join(datadir, "trace.0.jsonl"),
                      roll_bytes=int(flow.TRACE_ROLL_FILE_BYTES),
                      keep_files=int(flow.TRACE_KEEP_FILES),
                      flush_every=int(flow.TRACE_FLUSH_EVERY_EVENTS)))

    # Cluster file (reference fdb.cluster): the durable connection spec.
    # An existing file WINS over --coordinators (the file tracks quorum
    # changes; the flag is only the first-boot seed), and coordinator
    # forwards rewrite it so a restart finds the moved quorum directly.
    cluster_file = os.path.join(datadir, "fdb.cluster")
    if os.path.exists(cluster_file):
        with open(cluster_file) as f:
            spec = f.read().strip()
        if spec:
            coordinators = parse_coordinators(spec)
    else:
        spec = ",".join(f"{c.ip}:{c.port}" for c in coordinators)
        with open(cluster_file, "w") as f:
            f.write(spec + "\n")

    def _on_forward(new_spec: str) -> None:
        tmp = cluster_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(new_spec + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, cluster_file)

    from .coordination import set_forward_hook
    set_forward_hook(_on_forward)
    loop = EventLoop(sim=False)
    set_event_loop(loop)
    # Seed uniquely PER INCARNATION: a rebooted process must not regenerate
    # its predecessor's endpoint tokens (stale requests could misdeliver to
    # the new incarnation's streams), and its CC candidacy must carry a NEW
    # change_id or leader monitors — which only react to change_id changes
    # — would never observe the re-election.
    import time as _time
    set_deterministic_random(DeterministicRandom(
        seed or ((os.getpid() << 16) ^ (_time.time_ns() & 0x7FFFFFFF)
                 ) & 0x7FFFFFFF))
    net = RealNetwork(loop, ip, port, tls=tls)
    set_network(net)
    fs = RealFileSystem(datadir)
    proc = RealProcess(loop, net, name=name or f"fdbserver:{port}",
                       process_class=process_class, fs=fs)

    # --coordination forces the role even when this address is not (yet)
    # in the spec: a changeQuorum target must already serve generation
    # registers when the management probe arrives.
    is_coordinator = force_coordination or any(
        c.ip == ip and c.port == port for c in coordinators)
    if is_coordinator:
        coord = CoordinationServer(f"coord.{port}", fs=fs)
        coord.run(proc)

    coord_clients = [CoordinationClientInterface.at_address(a)
                     for a in coordinators]
    leader_var: AsyncVar = AsyncVar(None)
    # Stateless workers campaign for CC (a storage worker winning would put
    # the control plane on a data node) — same policy as the simulation.
    if process_class == "stateless":
        from ..core.rng import deterministic_random
        cc = ClusterController(f"cc.{port}", coord_clients, config)
        cc.register_streams(proc)
        # Random change_id: unique per incarnation (see seed note above).
        change_id = deterministic_random().random_int(0, 1 << 30)
        proc.spawn(try_become_leader(coord_clients, cc.interface,
                                     leader_var, change_id=change_id,
                                     on_forward=_on_forward),
                   f"{proc.name}.campaign")
        proc.spawn(_cc_runner(proc, cc, leader_var, change_id),
                   f"{proc.name}.ccRunner")
    else:
        proc.spawn(monitor_leader(coord_clients, leader_var,
                                  on_forward=_on_forward),
                   f"{proc.name}.monitorLeader")

    worker = Worker(proc, coord_clients, process_class=process_class,
                    config=config)
    worker.run(leader_var)

    # Production observability (reference Net2 slow-task warnings +
    # flow/Profiler): every dispatched callback is timed against the
    # SLOW_TASK_THRESHOLD_S knob; FDB_PROFILE=1 also samples the reactor
    # thread's stack into periodic trace dumps (worker.run installs the
    # same hooks, so recruited-role processes are covered either way).
    from ..core.profiler import install_slow_task_detection, \
        maybe_start_profiler
    install_slow_task_detection(loop)
    maybe_start_profiler(spawn=proc.spawn)

    async def _flush_trace() -> None:
        from ..core.scheduler import delay
        from ..core.trace import get_tracer
        while True:
            await delay(0.5)
            get_tracer().flush()

    proc.spawn(_flush_trace(), f"{proc.name}.traceFlush")

    async def _gc_tick() -> None:
        """Periodic cycle collection: broken-promise delivery for DROPPED
        (not explicitly errored) ReplyPromises rides __del__, and a
        cancelled actor's frame can sit in a reference cycle; an idle
        process may not allocate enough to trigger gen-2 GC for minutes,
        stalling remote failure detection that long."""
        import gc
        from ..core.scheduler import delay
        n = 0
        while True:
            await delay(5.0)
            n += 1
            # Full (gen-2) passes only every 6th tick: jax registers a
            # gc callback that makes every FULL collection expensive
            # (profiled as bursty multi-ms reactor stalls across all
            # server processes under e2e load); young-generation passes
            # still deliver broken-promise __del__s for recently
            # dropped cycles, and the 30s full-pass bound keeps
            # long-lived cycles from stalling failure detection.
            gc.collect(2 if n % 6 == 0 else 1)

    proc.spawn(_gc_tick(), f"{proc.name}.gcTick")
    TraceEvent("FdbServerStarted").detail("Address", str(proc.address)) \
        .detail("Class", process_class).detail(
        "Coordinator", is_coordinator).log()
    loop.run_forever()


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(prog="fdbserver")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--ip", default="127.0.0.1")
    ap.add_argument("--coordinators", required=True,
                    help="comma-separated host:port list")
    ap.add_argument("--datadir", required=True)
    ap.add_argument("--class", dest="process_class", default="stateless",
                    choices=["stateless", "storage", "coordinator", "log",
                             "transaction"])
    ap.add_argument("--config", default=None,
                    help="DatabaseConfiguration overrides as JSON")
    ap.add_argument("--name", default="")
    ap.add_argument("--coordination", action="store_true",
                    help="serve generation registers even if this address "
                         "is not in the spec (changeQuorum target)")
    ap.add_argument("--tls-cert", default=None)
    ap.add_argument("--tls-key", default=None)
    ap.add_argument("--tls-ca", default=None)
    args = ap.parse_args(argv)
    tls = None
    if args.tls_cert:
        tls = {"cert": args.tls_cert, "key": args.tls_key or args.tls_cert,
               "ca": args.tls_ca or args.tls_cert}
    # "coordinator" class == a stateless worker that also serves
    # coordination if its address is in the coordinator list.
    pclass = ("stateless" if args.process_class == "coordinator"
              else args.process_class)
    serve(args.port, parse_coordinators(args.coordinators), args.datadir,
          process_class=pclass, config=build_config(args.config),
          ip=args.ip, name=args.name,
          force_coordination=args.coordination, tls=tls)


if __name__ == "__main__":
    sys.exit(main())
