"""Status: the machine-readable cluster state document.

Reference: fdbserver/Status.actor.cpp clusterGetStatus (:2684) aggregates
worker/process/role metrics into the status JSON exposed via `fdbcli
status json` and \\xff\\xff/status/json; schema documented in
documentation/sphinx/source/mr-status-json-schemas.rst.inc.  This builder
runs on the cluster controller and mirrors the top-level shape: cluster
{recovery_state, workload, qos, data, processes, ...} + client.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

from ..core.scheduler import now
from ..rpc.endpoint import RequestStream
from .ratekeeper import StorageQueuingMetricsRequest


@dataclass
class StatusRequest:
    reply: Any = None


_RECOVERY_DESCRIPTIONS = {
    "unrecruited": "Cluster controller has not recruited a master yet.",
    "recruiting": "Recruiting a new transaction system.",
    "accepting_commits": "The database is accepting commits.",
    "fully_recovered": "The database is fully recovered.",
}


def _roles_of(ifaces):
    for iface in ifaces:
        role = getattr(iface, "role", None)
        if role is not None:
            yield role


def _merge_band(roles, group: str, hist_name: str, worker_docs=()):
    """One merged latency band (HistogramSnapshot status dict) for
    `hist_name` across every role instance that recorded it; None when no
    samples exist anywhere.  The metrics docs workers attach to their CC
    registrations WIN when any process shipped one: on a real cluster
    they cover every process — including roles co-hosted with the CC,
    whose locally-delivered interfaces keep `.role` backrefs that would
    otherwise mask the remote processes entirely.  Sim workers ship
    empty docs, so simulation always reads the (complete, fresh)
    backrefs; nothing is ever counted twice."""
    from ..core.metrics import HistogramSnapshot
    snaps = [HistogramSnapshot.from_wire(wire) for doc in worker_docs
             for wire in [doc.get(group, {}).get("histograms", {})
                          .get(hist_name)] if wire is not None]
    if not worker_docs:
        snaps = [role.metrics.histograms[hist_name].snapshot()
                 for role in roles
                 if getattr(role, "metrics", None) is not None
                 and hist_name in role.metrics.histograms]
    if not snaps:
        return None
    merged = HistogramSnapshot.merged(snaps)
    return merged.to_status() if merged.count else None


def collect_latency_bands(info, worker_docs=()) -> Dict[str, Any]:
    """cluster.latency_statistics: every commit-pipeline stage as a
    p50/p95/p99 band, merged across role instances (reference
    latency_statistics in mr-status; the sub-stage split mirrors
    CommitProxyServer.actor.cpp:403-409's per-stage histograms).  TPU
    bands come from the resolvers' supervised conflict backends
    (conflict/supervisor.py "TpuBackend" collections)."""
    grv = list(_roles_of(info.grv_proxies))
    cp = list(_roles_of(info.commit_proxies))
    res = list(_roles_of(info.resolvers))
    tlogs = list(_roles_of(info.tlogs))
    ss = list(_roles_of(info.storage_servers.values()))
    backends = [r.conflict_set for r in res
                if getattr(getattr(r, "conflict_set", None),
                           "metrics", None) is not None]
    spec = [
        ("grv", grv, "GrvProxy", "GRVLatency"),
        ("grv_queue", grv, "GrvProxy", "QueueWait"),
        ("commit", cp, "CommitProxy", "Commit"),
        ("commit_batch_assembly", cp, "CommitProxy", "BatchAssembly"),
        ("commit_version_wait", cp, "CommitProxy", "VersionWait"),
        ("commit_resolution", cp, "CommitProxy", "Resolution"),
        ("commit_tlog_logging", cp, "CommitProxy", "TLogLogging"),
        ("commit_reply", cp, "CommitProxy", "Reply"),
        ("resolver_queue", res, "Resolver", "QueueWait"),
        ("resolver_resolve", res, "Resolver", "Resolve"),
        ("tlog_append", tlogs, "TLog", "Append"),
        ("tlog_durable", tlogs, "TLog", "DurableWait"),
        # Hot-RPC serialization cost (ISSUE 14, rpc/serde.py "Rpc"
        # collection): real clusters only — the bands ride the worker
        # metrics docs (sim passes objects, no serialization, no roles
        # to backref), so e2e stage attribution can decompose encode/
        # decode time instead of hiding it in queue waits.
        ("rpc_encode", [], "Rpc", "Encode"),
        ("rpc_decode", [], "Rpc", "Decode"),
        ("storage_read", ss, "StorageServer", "ReadLatency"),
        ("storage_fetch", ss, "StorageServer", "TLogPeek"),
        ("tpu_dispatch", backends, "TpuBackend", "Dispatch"),
        ("tpu_device_batch", backends, "TpuBackend", "DeviceBatch"),
        ("tpu_mirror_resolve", backends, "TpuBackend", "MirrorResolve"),
        # Pipeline occupancy (a COUNT histogram, not seconds): batches in
        # flight on the device at each dispatch (conflict/supervisor.py
        # depth-N pipeline; PipelineStalls counts dispatches that found
        # the pipeline full).
        ("tpu_inflight_depth", backends, "TpuBackend", "InflightDepth"),
    ]
    out: Dict[str, Any] = {}
    for name, roles, group, hist in spec:
        band = _merge_band(roles, group, hist, worker_docs)
        if band is not None:
            out[name] = band
    return out


def collect_cluster_metrics(info, worker_docs=()) -> Dict[str, Any]:
    """cluster.metrics: per-group counter sums across the role instances
    this status builder can reach — sim backrefs, or (real clusters) the
    workers' registered metrics docs."""
    groups = [
        ("GrvProxy", _roles_of(info.grv_proxies)),
        ("CommitProxy", _roles_of(info.commit_proxies)),
        ("Resolver", _roles_of(info.resolvers)),
        ("TLog", _roles_of(info.tlogs)),
        ("StorageServer", _roles_of(info.storage_servers.values())),
        ("TpuBackend", (r.conflict_set for r in _roles_of(info.resolvers)
                        if getattr(getattr(r, "conflict_set", None),
                                   "metrics", None) is not None)),
    ]
    out: Dict[str, Any] = {}
    if worker_docs:
        # Real cluster: the workers' registered counter docs cover every
        # process (co-hosted backref roles included) — summing backrefs
        # on top would double-count the CC's local roles.
        for doc in worker_docs:
            for group, g in doc.items():
                sums = out.setdefault(group, {})
                for name, v in (g.get("counters") or {}).items():
                    sums[name] = sums.get(name, 0) + v
        return out
    for group, roles in groups:
        sums: Dict[str, int] = {}
        for role in roles:
            metrics = getattr(role, "metrics", None)
            if metrics is None:
                continue
            for name, c in metrics.counters.items():
                sums[name] = sums.get(name, 0) + c.value
        if sums:
            out[group] = sums
    return out


def collect_resolution_plane(info) -> Dict[str, Any]:
    """cluster.resolution: the partitioned resolution plane — key-range
    ownership of this generation (ServerDBInfo.resolver_ranges, the
    \xff system range rendered as "all") plus per-resolver conflict
    counters, Resolve latency band, and conflict-backend supervision
    state keyed by resolver id (ISSUE 7 observability satellite).
    Reads the sim-side role backrefs; on a real cluster remote resolver
    processes surface through cluster.roles/metrics instead."""

    def kstr(b) -> str:
        return b.decode("utf-8", "backslashreplace") \
            if isinstance(b, (bytes, bytearray)) else str(b)

    ranges = [{"begin": kstr(b), "end": kstr(e),
               "resolver": ("all" if idx < 0 else idx)}
              for b, e, idx in getattr(info, "resolver_ranges", []) or []]
    resolvers: Dict[str, Any] = {}
    for iface in info.resolvers:
        role = getattr(iface, "role", None)
        metrics = getattr(role, "metrics", None)
        if metrics is None:
            resolvers[getattr(iface, "id", "?")] = {"reachable": False}
            continue
        entry: Dict[str, Any] = {
            "txn_resolved": metrics.counter("TxnResolved").value,
            "txn_conflicts": metrics.counter("TxnConflicts").value,
            "resolved_batches": getattr(role, "resolved_batches", 0),
            "version": role.version.get(),
        }
        h = metrics.histograms.get("Resolve")
        if h is not None:
            snap = h.snapshot()
            if snap.count:
                entry["resolve"] = snap.to_status()
        backend = getattr(role, "backend_status", None)
        bs = backend() if callable(backend) else None
        if bs:
            entry["conflict_backend"] = bs
        resolvers[metrics.role_id] = entry
    return {"count": len(info.resolvers), "ranges": ranges,
            "resolvers": resolvers}


def collect_scheduler(info) -> Dict[str, Any]:
    """cluster.scheduler: the conflict-aware scheduling plane (ISSUE 12)
    — per-GRV-proxy predictor tables + admission deferrals, per-commit-
    proxy reorder/repair counters, knob posture, and cluster totals.
    This document is ALSO what \xff\xff/metrics/scheduler/ and the
    fdbcli `metrics` Scheduler section render, so the three surfaces
    agree by construction (the PR-8 heat-plane pattern).  Reads the
    sim-side role backrefs like collect_resolution_plane."""
    from ..core.knobs import server_knobs
    knobs = server_knobs()
    totals = {"deferrals": 0, "reorder_batches": 0, "reorder_swaps": 0,
              "repairs_attempted": 0, "repairs_succeeded": 0,
              "repairs_exhausted": 0}
    grv: Dict[str, Any] = {}
    for iface in info.grv_proxies:
        role = getattr(iface, "role", None)
        ss = getattr(role, "scheduler_status", None)
        if not callable(ss):
            continue
        doc = ss()
        grv[role.id] = doc
        totals["deferrals"] += int(doc.get("deferrals", 0))
    commit: Dict[str, Any] = {}
    for iface in info.commit_proxies:
        role = getattr(iface, "role", None)
        ss = getattr(role, "scheduler_status", None)
        if not callable(ss):
            continue
        doc = ss()
        commit[role.id] = doc
        for key in ("reorder_batches", "reorder_swaps",
                    "repairs_attempted", "repairs_succeeded",
                    "repairs_exhausted"):
            totals[key] += int(doc.get(key, 0))
    return {
        "enabled": {
            "predictor": bool(knobs.SCHED_PREDICTOR_ENABLED),
            "reorder": bool(knobs.SCHED_REORDER_ENABLED),
            "repair": bool(knobs.SCHED_REPAIR_ENABLED),
        },
        "grv_proxies": grv,
        "commit_proxies": commit,
        "totals": totals,
    }


def collect_regions(info, workers=None) -> Dict[str, Any]:
    """cluster.regions: the generation's DR posture (ISSUE 10) — region
    configuration, async-plane health (log routers / remote TLogs /
    remote replicas of this epoch), per-dc worker counts, and the
    durable failover record: failover_version (the adopted
    min(end_version) across locked remote TLogs — every commit acked at
    or below it survived), lost_tail_versions (the visible un-replicated
    tail an undrained hard kill cost), and drained (True for the
    fdbcli-style switchover that lost nothing).  The master assembles
    the document at recovery (ServerDBInfo.regions) and the in-epoch
    plane heal refreshes the counts."""
    doc = dict(getattr(info, "regions", None) or {})
    doc.setdefault("configured", False)
    doc.setdefault("replication", "primary_only")
    if workers:
        by_dc: Dict[str, int] = {}
        for reg in workers:
            dc = (getattr(reg, "locality", ("", "", "")) or ("",))[0] or "?"
            by_dc[dc] = by_dc.get(dc, 0) + 1
        doc["datacenters"] = {dc: {"workers": n}
                              for dc, n in sorted(by_dc.items())}
    return doc


def collect_heat(info, read_hot: Dict[str, Any]) -> Dict[str, Any]:
    """cluster.heat: the cluster-wide heat telemetry plane (ISSUE 8) —
    per-resolver decayed top-K conflict ranges keyed by resolver id
    (conflict/heat.py via Resolver.heat_status), per-storage read-hot
    shards (the queuing-metrics read_hot_shards rows assembled by
    build_status), and the cluster-wide busiest tags/tenants folded
    across resolvers.  This document is ALSO what the
    \xff\xff/metrics/conflict_ranges/ and /read_hot_ranges/ special-key
    modules and `fdbcli top` render, so the three surfaces agree by
    construction.  Conflict side reads the sim-side role backrefs (like
    collect_resolution_plane); on a real cluster a remote resolver's
    heat surfaces through its HotConflictRange trace events instead,
    while the read-hot side rides the queuing-metrics RPC and works
    everywhere."""
    from ..core.knobs import server_knobs
    k = int(server_knobs().CONFLICT_HEAT_TOP_K)
    conflict: Dict[str, Any] = {}
    tag_tot: Dict[str, int] = {}
    tenant_tot: Dict[int, int] = {}
    for iface in info.resolvers:
        role = getattr(iface, "role", None)
        hs = getattr(role, "heat_status", None)
        if not callable(hs):
            continue
        conflict[role.id] = hs()
        # Cluster-wide busiest folding reads the FULL (decayed) tenant/
        # tag tables, not the per-resolver top-K rows: a tag ranking 9th
        # on each of 4 resolvers can still be the cluster's busiest.
        tracker = getattr(role, "heat", None)
        for tag, c in getattr(tracker, "tags", {}).items():
            tag_tot[tag] = tag_tot.get(tag, 0) + c
        for tenant, c in getattr(tracker, "tenants", {}).items():
            tenant_tot[tenant] = tenant_tot.get(tenant, 0) + c
    return {
        "conflict_ranges": conflict,
        "read_hot_ranges": read_hot,
        "busiest_tags": [
            {"tag": t, "conflicts": c} for t, c in sorted(
                tag_tot.items(), key=lambda kv: (-kv[1], kv[0]))[:k]],
        "busiest_tenants": [
            {"tenant_id": t, "conflicts": c} for t, c in sorted(
                tenant_tot.items(), key=lambda kv: (-kv[1], kv[0]))[:k]],
    }


def collect_peer_health(cc) -> Dict[str, Any]:
    """cluster.peer_health: the CC's aggregated gray-failure verdict
    (ClusterController.compute_peer_health) — degraded links with their
    reporters/evidence plus the >= CC_DEGRADATION_REPORTERS process
    convictions.  This document is ALSO what
    \\xff\\xff/metrics/peer_health/ and the fdbcli `metrics` Peer health
    section render, so the three surfaces agree by construction (the
    PR-8/12 pattern)."""
    return cc.compute_peer_health()


def collect_messages() -> Dict[str, Any]:
    """cluster.messages: process-wide trace-event counts per severity
    label (reference status cluster.messages) — a cheap first question
    ("is anything screaming?") answered without grepping trace files."""
    from ..core.trace import get_tracer
    tracer = get_tracer()
    return {"severity_counts": tracer.messages(),
            "error_count": tracer.error_count,
            "events_emitted": tracer.events_emitted}


def _register_interval() -> float:
    """The worker re-registration cadence the staleness flags are judged
    against: the fixed 10s sim interval, or WORKER_REGISTER_INTERVAL_S
    (worker.py _stats_announce_loop)."""
    from ..core.knobs import server_knobs
    from ..core.scheduler import get_event_loop
    if get_event_loop().sim:
        return 10.0
    return float(server_knobs().WORKER_REGISTER_INTERVAL_S)


async def build_status(cc) -> Dict[str, Any]:
    """Assemble the status document from the CC's view + live role polls
    (all polls issued in parallel — one clogged role must not stall the
    whole document)."""
    from ..core.futures import swallow, wait_all
    from .ratekeeper import RatekeeperStatusRequest
    info = cc.db_info
    tags = list(info.storage_servers.items())
    ss_futures = [RequestStream.at(ssi.queuing_metrics.endpoint).get_reply(
        StorageQueuingMetricsRequest()) for _tag, ssi in tags]
    rk_future = None
    if info.ratekeeper is not None:
        rk_future = RequestStream.at(
            info.ratekeeper.get_status.endpoint).get_reply(
            RatekeeperStatusRequest())
    await wait_all([swallow(f) for f in ss_futures +
                    ([rk_future] if rk_future else [])])

    storage_status = {}
    total_kv_bytes = 0
    worst_queue = 0
    read_hot: Dict[str, Any] = {}
    for (tag, ssi), f in zip(tags, ss_futures):
        if f.is_error():
            storage_status[str(tag)] = {"id": ssi.id, "reachable": False}
            continue
        m = f.get()
        storage_status[str(tag)] = {
            "id": ssi.id,
            "stored_bytes": m.stored_bytes,
            "input_queue_bytes": m.queue_bytes,
            "durability_lag_versions": m.durability_lag,
        }
        total_kv_bytes += m.stored_bytes
        worst_queue = max(worst_queue, m.queue_bytes)
        # Read-hot shards this server reported at its last heat tick
        # (server/storage.py _fold_read_heat) -> cluster.heat rows.
        hot_rows = [
            {"begin": b.decode("utf-8", "backslashreplace"),
             "end": e.decode("utf-8", "backslashreplace"),
             "begin_hex": b.hex(), "end_hex": e.hex(),
             "read_ops_per_sec": ops, "read_bytes_per_sec": nbytes,
             "storage_server": ssi.id}
            for b, e, ops, nbytes in getattr(m, "read_hot_shards", [])]
        if hot_rows:
            read_hot[str(tag)] = hot_rows
    rk = rk_future.get() if rk_future is not None and \
        not rk_future.is_error() else None
    peer_health = collect_peer_health(cc)

    processes = {}
    stale_after = 2.0 * _register_interval()
    for wid, reg in sorted(cc.workers.items()):
        entry = {"class_type": reg.process_class, "excluded": False}
        loc = getattr(reg, "locality", ("", "", ""))
        if loc and loc[0]:
            entry["locality"] = {"dcid": loc[0], "zoneid": loc[1],
                                 "machineid": loc[2]}
        stats = getattr(reg, "machine_stats", None)
        if stats:
            # Reference status process sections: cpu/memory per process
            # (SystemMonitor ProcessMetrics).
            entry["cpu"] = {"usage_seconds": stats.get("cpu_seconds")}
            entry["memory"] = {
                "rss_bytes": stats.get("memory_rss_bytes")}
            entry["uptime_seconds"] = stats.get("uptime_seconds")
        # Staleness stamp: age of this worker's latest metrics-doc
        # report.  A process silent past twice its register interval is
        # flagged — its stats/health sections describe the PAST, and a
        # reader deciding from them should know.
        age = now() - getattr(reg, "registered_at", 0.0)
        entry["seconds_since_last_report"] = round(age, 3)
        entry["stale"] = bool(age > stale_after)
        processes[wid] = entry

    # Role latency/counter metrics via the sim-side interface backrefs
    # (reference: roles push TDMetrics / the status collector polls each
    # worker; here the collections are read in place).
    roles = {}
    tenants_doc: Dict[str, Any] = {}
    for kind, ifaces in (
            ("commit_proxies", info.commit_proxies),
            ("grv_proxies", info.grv_proxies),
            ("resolvers", info.resolvers),
            ("logs", info.tlogs),
            ("storage_servers", list(info.storage_servers.values()))):
        entries = {}
        for iface in ifaces:
            role = getattr(iface, "role", None)
            metrics = getattr(role, "metrics", None)
            if metrics is not None:
                entry = metrics.to_status()
                # Resolver conflict-backend supervision state (degraded /
                # tripped / fallback counters, conflict/supervisor.py).
                backend = getattr(role, "backend_status", None)
                bs = backend() if callable(backend) else None
                if bs:
                    entry["conflict_backend"] = bs
                # Commit-proxy tenant cache + per-tenant write metering
                # (tenant fence, server/commit_proxy.py).
                ts = getattr(role, "tenant_status", None)
                td = ts() if callable(ts) else None
                if td:
                    entry["tenants"] = td
                    tenants_doc.setdefault("num_tenants", td["count"])
                    tenants_doc.setdefault(
                        "metadata_version", td["metadata_version"])
                entries[metrics.role_id] = entry
        roles[kind] = entries
    if rk is not None:
        # Per-tenant quotas + measured read rates + live throttles
        # (server/ratekeeper.py quota enforcement).
        tenants_doc["quotas"] = getattr(rk, "tenant_quotas", {}) or {}
        tenants_doc["throttled_tags"] = rk.throttled_tags
        tenants_doc["tag_read_ops_per_sec"] = \
            getattr(rk, "tag_read_ops", {}) or {}
        tenants_doc["tag_read_bytes_per_sec"] = \
            getattr(rk, "tag_read_bytes", {}) or {}

    return {
        "client": {
            "cluster_file": {"up_to_date": True},
            "database_status": {
                "available": info.recovery_state in ("accepting_commits",
                                                     "fully_recovered"),
                "healthy": info.recovery_state in ("accepting_commits",
                                                   "fully_recovered"),
            },
        },
        "cluster": {
            "generation": info.epoch,
            "recovery_state": {
                "name": info.recovery_state,
                "description": _RECOVERY_DESCRIPTIONS.get(
                    info.recovery_state, info.recovery_state),
            },
            "database_available": info.recovery_state in (
                "accepting_commits", "fully_recovered"),
            "machines": {},
            "processes": processes,
            "workload": {
                "transactions": {},
                "operations": {},
            },
            "qos": {
                "worst_queue_bytes_storage_server": worst_queue,
                "transactions_per_second_limit":
                    (None if rk is None or rk.tps_limit == float("inf")
                     else rk.tps_limit),
                "released_transactions_per_second":
                    (None if rk is None else rk.released_tps),
                "performance_limited_by": {
                    "name": rk.limit_reason if rk else "workload"},
            },
            "data": {
                "total_kv_size_bytes": total_kv_bytes,
                "state": {"healthy": True, "name": "healthy"},
            },
            "layers": {"_valid": True},
            "tenants": tenants_doc,
            "roles": roles,
            # Partitioned resolution plane: per-resolver conflict stats,
            # backend supervision, and the generation's key-range
            # ownership (ISSUE 7).
            "resolution": collect_resolution_plane(info),
            # DR posture + failover record (ISSUE 10): region
            # configuration, async-plane health, drained-vs-undrained
            # failover history with the surfaced loss window.
            "regions": collect_regions(info, cc.workers.values()),
            # Cluster heat telemetry (ISSUE 8): per-resolver hot
            # conflict ranges, per-storage read-hot shards, busiest
            # tags/tenants — the feed for \xff\xff/metrics/ and
            # `fdbcli top`.
            "heat": collect_heat(info, read_hot),
            # Conflict-aware scheduling plane (ISSUE 12): per-proxy
            # predictor deferrals, reorder swaps, repair counters — the
            # feed for \xff\xff/metrics/scheduler/ and the fdbcli
            # `metrics` Scheduler section.
            "scheduler": collect_scheduler(info),
            # Gray-failure plane (ISSUE 18): the CC's aggregated per-peer
            # health verdict — degraded links + >= K-reporter process
            # convictions, the feed for \xff\xff/metrics/peer_health/
            # and the fdbcli `metrics` Peer health section.
            "peer_health": peer_health,
            "degraded_processes": [
                e["address"] for e in peer_health["degraded_processes"]],
            # Trace-severity rollup (ISSUE 18 satellite): per-severity
            # event counts of the status builder's process.
            "messages": collect_messages(),
            # Per-stage commit-pipeline latency bands + per-group counter
            # sums (ISSUE 3: the `fdbcli metrics` surface).  Sources:
            # sim-side role backrefs, else the workers' registered
            # metrics docs (real clusters).
            "latency_statistics": collect_latency_bands(
                info, [r.metrics_doc for r in cc.workers.values()
                       if getattr(r, "metrics_doc", None)]),
            "metrics": collect_cluster_metrics(
                info, [r.metrics_doc for r in cc.workers.values()
                       if getattr(r, "metrics_doc", None)]),
            "cluster_controller_timestamp": round(now(), 3),
            # The quorum this CC is operating against (reference status
            # coordinators section; addresses resolved from the CC's own
            # coordinator handles, which forward-following keeps current).
            "coordinators": {
                "quorum": [
                    f"{a.ip}:{a.port}" for a in (
                        getattr(getattr(c, "reg_read", None), "address",
                                None) for c in cc.coordinators)
                    if a is not None],
            },
            "configuration": {
                "logs": len(info.tlogs),
                "resolvers": len(info.resolvers),
                "commit_proxies": len(info.commit_proxies),
                "grv_proxies": len(info.grv_proxies),
                "storage_servers": len(info.storage_servers),
            },
        },
    }


async def serve_status(cc) -> None:
    """The CC's status endpoint actor."""
    async for req in cc.interface.get_status.queue:
        cc._spawn(_answer(cc, req), f"{cc.id}.status")


async def _answer(cc, req: StatusRequest) -> None:
    req.reply.send(await build_status(cc))
