"""Copy-on-write B-tree storage engine over paged files.

Reference: fdbserver/VersionedBTree.actor.cpp (Redwood) — a paged
copy-on-write B+tree behind IKeyValueStore: modified pages are written to
fresh page ids, parents re-point up to a new root, and a double-slot
header commits the new root atomically (IPager.h versioned pager).  This
engine keeps Redwood's crash-consistency shape without its versioning,
prefix compression, or page reuse (pages are append-only between
compactions — a documented simplification; Redwood's free list is the
remaining step):

  page 0/1: alternating header slots (magic, commit_seq, root id, page
            count, crc) — recovery picks the valid slot with the higher
            seq, so a power failure mid-commit always lands on a complete
            tree (old or new, never torn).
  leaves:   sorted (key, value) records.
  internal: child ids + separator keys (child i covers keys < sep[i]).

Commit protocol: write all new pages, fsync, write the next header slot,
fsync — the reference's "commit is one header write" invariant.
"""

from __future__ import annotations

import bisect
import zlib
from typing import Dict, List, Optional, Tuple

from ..core.trace import TraceEvent
from ..core.wire import Reader, Writer
from .kvstore import IKeyValueStore
from .sim_fs import SimFileSystem

PAGE_SIZE = 4096
_MAGIC = 0x0FDBB7EE
_LEAF, _INTERNAL = 0, 1
# Split when a serialized page exceeds this (leaving headroom for the
# page header fields).
_SPLIT_BYTES = PAGE_SIZE - 64


class _Node:
    __slots__ = ("kind", "keys", "values", "children")

    def __init__(self, kind: int, keys=None, values=None, children=None):
        self.kind = kind
        self.keys: List[bytes] = keys or []       # leaf: record keys;
        self.values: List[bytes] = values or []   # internal: separators
        self.children: List[int] = children or []

    def encode(self) -> bytes:
        w = Writer().u8(self.kind).u32(len(self.keys))
        for k in self.keys:
            w.bytes_(k)
        if self.kind == _LEAF:
            for v in self.values:
                w.bytes_(v)
        else:
            w.u32(len(self.children))
            for c in self.children:
                w.u32(c)
        return w.done()

    @classmethod
    def decode(cls, blob: bytes) -> "_Node":
        r = Reader(blob)
        kind = r.u8()
        n = r.u32()
        keys = [r.bytes_() for _ in range(n)]
        if kind == _LEAF:
            return cls(_LEAF, keys, [r.bytes_() for _ in range(n)])
        children = [r.u32() for _ in range(r.u32())]
        return cls(_INTERNAL, keys, None, children)

    def size(self) -> int:
        base = sum(len(k) + 8 for k in self.keys)
        if self.kind == _LEAF:
            return base + sum(len(v) for v in self.values)
        return base + 4 * len(self.children)


class KVStoreBTree(IKeyValueStore):
    """COW B+tree engine (reference Redwood, simplified)."""

    def __init__(self, fs: SimFileSystem, prefix: str) -> None:
        self.fs = fs
        self.file = fs.open(prefix + ".btree")
        self._uncommitted: List[Tuple[int, bytes, bytes]] = []
        self._cache: Dict[int, _Node] = {}
        self._dirty: Dict[int, _Node] = {}
        self.root = 0          # 0 = empty tree
        self.page_count = 2    # slots 0,1 are headers
        self.commit_seq = 0

    # -- paging --------------------------------------------------------------
    async def _read_node(self, page_id: int) -> _Node:
        node = self._dirty.get(page_id) or self._cache.get(page_id)
        if node is None:
            blob = await self.file.read(page_id * PAGE_SIZE, PAGE_SIZE)
            (n,) = (int.from_bytes(blob[:4], "little"),)
            node = _Node.decode(blob[4:4 + n])
            self._cache[page_id] = node
        return node

    def _alloc(self, node: _Node) -> int:
        page_id = self.page_count
        self.page_count += 1
        self._dirty[page_id] = node
        return page_id

    def _header_blob(self) -> bytes:
        w = Writer().u32(_MAGIC).i64(self.commit_seq).u32(self.root)
        w.u32(self.page_count)
        body = w.done()
        return body + zlib.crc32(body).to_bytes(4, "little")

    # -- mutation ------------------------------------------------------------
    def set(self, key: bytes, value: bytes) -> None:
        self._uncommitted.append((0, key, value))

    def clear(self, begin: bytes, end: bytes) -> None:
        self._uncommitted.append((1, begin, end))

    async def _cow_set(self, page_id: int, key: bytes, value: bytes) -> int:
        """Insert/overwrite; returns the NEW page id for this subtree
        (list of ids if the node split)."""
        if page_id == 0:
            return self._alloc(_Node(_LEAF, [key], [value]))
        node = await self._read_node(page_id)
        if node.kind == _LEAF:
            i = bisect.bisect_left(node.keys, key)
            keys, values = list(node.keys), list(node.values)
            if i < len(keys) and keys[i] == key:
                values[i] = value
            else:
                keys.insert(i, key)
                values.insert(i, value)
            return self._finish(_Node(_LEAF, keys, values))
        ci = bisect.bisect_right(node.keys, key)
        new_child = await self._cow_set(node.children[ci], key, value)
        return self._replace_child(node, ci, new_child)

    def _finish(self, node: _Node):
        """Allocate `node`, splitting when oversized; returns page id or
        (left_id, sep_key, right_id)."""
        if node.size() <= _SPLIT_BYTES or len(node.keys) < 2:
            return self._alloc(node)
        mid = len(node.keys) // 2
        if node.kind == _LEAF:
            left = _Node(_LEAF, node.keys[:mid], node.values[:mid])
            right = _Node(_LEAF, node.keys[mid:], node.values[mid:])
            sep = node.keys[mid]
        else:
            # separator mid is promoted, not kept.
            left = _Node(_INTERNAL, node.keys[:mid], None,
                         node.children[:mid + 1])
            right = _Node(_INTERNAL, node.keys[mid + 1:], None,
                          node.children[mid + 1:])
            sep = node.keys[mid]
        return (self._alloc(left), sep, self._alloc(right))

    def _replace_child(self, node: _Node, ci: int, new_child):
        keys = list(node.keys)
        children = list(node.children)
        if isinstance(new_child, tuple):
            lid, sep, rid = new_child
            children[ci:ci + 1] = [lid, rid]
            keys.insert(ci, sep)
        else:
            children[ci] = new_child
        return self._finish(_Node(_INTERNAL, keys, None, children))

    async def _cow_clear(self, page_id: int, begin: bytes,
                         end: bytes) -> int:
        if page_id == 0:
            return 0
        node = await self._read_node(page_id)
        if node.kind == _LEAF:
            pairs = [(k, v) for k, v in zip(node.keys, node.values)
                     if not begin <= k < end]
            if len(pairs) == len(node.keys):
                return page_id     # nothing cleared: no COW churn
            if not pairs:
                return 0
            return self._alloc(_Node(_LEAF, [k for k, _ in pairs],
                                     [v for _, v in pairs]))
        lo = bisect.bisect_right(node.keys, begin)
        hi = bisect.bisect_left(node.keys, end) + 1
        keys: List[bytes] = []
        children: List[int] = []
        changed = False
        for ci, child in enumerate(node.children):
            if lo <= ci < hi:
                new_child = await self._cow_clear(child, begin, end)
                changed = changed or new_child != child
                child = new_child
            if child != 0:
                if children:
                    # Separator between the previous kept child and this
                    # one: the original separator just left of child ci
                    # upper-bounds every earlier subtree and lower-bounds
                    # this one (ci > 0 whenever a child was already kept).
                    keys.append(node.keys[ci - 1])
                children.append(child)
        if not changed:
            return page_id         # subtree untouched: keep the old pages
        if not children:
            return 0
        if len(children) == 1:
            return children[0]
        return self._finish(_Node(_INTERNAL, keys, None, children))

    async def commit(self) -> None:
        batch, self._uncommitted = self._uncommitted, []
        self._page_count_at_commit_start = self.page_count
        root = self.root
        for op, a, b in batch:
            if op == 0:
                r = await self._cow_set(root, a, b)
            else:
                r = await self._cow_clear(root, a, b)
            if isinstance(r, tuple):
                lid, sep, rid = r
                r = self._alloc(_Node(_INTERNAL, [sep], None, [lid, rid]))
            root = r
        # Validate page sizes BEFORE any write so an oversized record
        # (single k/v too big for a page; overflow pages are a pending
        # feature vs Redwood) fails cleanly with the tree untouched.
        encoded = {}
        for page_id, node in self._dirty.items():
            blob = node.encode()
            if 4 + len(blob) > PAGE_SIZE:
                from ..core.error import err
                self._dirty = {}
                self.page_count = self._page_count_at_commit_start
                raise err("operation_failed",
                          "btree record exceeds page size "
                          "(overflow pages not yet implemented)")
            encoded[page_id] = blob
        # Write dirty pages, fsync, then the next header slot, fsync
        # (reference: commit == one durable header write).
        for page_id, blob in encoded.items():
            await self.file.write(page_id * PAGE_SIZE,
                                  len(blob).to_bytes(4, "little") + blob)
        await self.file.sync()
        self._cache.update(self._dirty)
        self._dirty = {}
        self.root = root
        self.commit_seq += 1
        slot = self.commit_seq % 2
        await self.file.write(slot * PAGE_SIZE, self._header_blob())
        await self.file.sync()

    # -- reads ---------------------------------------------------------------
    def read_value(self, key: bytes) -> Optional[bytes]:
        return self._sync(self._aread_value(key))

    async def _aread_value(self, key: bytes) -> Optional[bytes]:
        page_id = self.root
        while page_id != 0:
            node = await self._read_node(page_id)
            if node.kind == _LEAF:
                i = bisect.bisect_left(node.keys, key)
                if i < len(node.keys) and node.keys[i] == key:
                    return node.values[i]
                return None
            page_id = node.children[bisect.bisect_right(node.keys, key)]
        return None

    def read_range(self, begin: bytes, end: bytes, limit: int = 1 << 30
                   ) -> List[Tuple[bytes, bytes]]:
        out: List[Tuple[bytes, bytes]] = []
        self._sync(self._collect(self.root, begin, end, limit, out))
        return out

    async def _collect(self, page_id: int, begin: bytes, end: bytes,
                       limit: int, out: List) -> None:
        if page_id == 0 or len(out) >= limit:
            return
        node = await self._read_node(page_id)
        if node.kind == _LEAF:
            for k, v in zip(node.keys, node.values):
                if begin <= k < end:
                    out.append((k, v))
                    if len(out) >= limit:
                        return
            return
        lo = bisect.bisect_right(node.keys, begin)
        hi = bisect.bisect_left(node.keys, end) + 1
        for ci in range(lo, min(hi, len(node.children))):
            await self._collect(node.children[ci], begin, end, limit, out)
            if len(out) >= limit:
                return

    @staticmethod
    def _sync(coro):
        """Drive a SimFile coroutine to completion synchronously (reads
        are page-cache hits after recovery; SimFile.read itself never
        blocks on other actors)."""
        try:
            while True:
                coro.send(None)
        except StopIteration as e:
            return e.value

    # -- recovery ------------------------------------------------------------
    async def recover(self) -> None:
        best_seq = -1
        for slot in (0, 1):
            blob = await self.file.read(slot * PAGE_SIZE, PAGE_SIZE)
            if len(blob) < 24:
                continue
            body, crc = blob[:20], blob[20:24]
            if zlib.crc32(body) != int.from_bytes(crc, "little"):
                continue
            r = Reader(body)
            if r.u32() != _MAGIC:
                continue
            seq = r.i64()
            root = r.u32()
            count = r.u32()
            if seq > best_seq:
                best_seq, self.root, self.page_count = seq, root, count
        if best_seq >= 0:
            self.commit_seq = best_seq
        else:
            self.root, self.page_count, self.commit_seq = 0, 2, 0
        self._cache.clear()
        self._dirty = {}
        TraceEvent("BTreeRecovered").detail("Seq", self.commit_seq).detail(
            "Root", self.root).detail("Pages", self.page_count).log()
