"""Copy-on-write B-tree storage engine over paged files.

Reference: fdbserver/VersionedBTree.actor.cpp (Redwood) — a paged
copy-on-write B+tree behind IKeyValueStore: modified pages are written to
fresh page ids, parents re-point up to a new root, and a double-slot
header commits the new root atomically (IPager.h versioned pager).  This
engine keeps Redwood's crash-consistency shape without its versioning or
prefix compression, and carries the pager features that bound file growth
and record size:

  page 0/1:  alternating header slots (magic, commit_seq, root id, page
             count, crc) — recovery picks the valid slot with the higher
             seq, so a power failure mid-commit always lands on a complete
             tree (old or new, never torn).
  leaves:    sorted (key, value-or-overflow-ref) records.
  internal:  child ids + SHORTENED separator keys (child i covers keys
             < sep[i]; separators are the shortest prefix of the right
             sibling's first key that still separates — Redwood's prefix
             truncation keeps internal nodes small under large keys).
  overflow:  values larger than _OVERFLOW_BYTES live in chains of whole
             pages referenced from the leaf record (reference Redwood
             "big value" overflow pages); the ref carries the page list
             so replaced/cleared records free their chains.
  free list: pages orphaned by COW replacement are reusable from the NEXT
             commit on (a torn commit must still find the previous tree
             intact — the reference pager's delayed-free queue).  The
             list is rebuilt at recovery by a reachability walk, so it
             needs no durable format of its own.

Commit protocol: write all new pages, fsync, write the next header slot,
fsync — the reference's "commit is one header write" invariant.
"""

from __future__ import annotations

import bisect
import zlib
from typing import Dict, List, Optional, Tuple, Union

from ..core.coverage import test_coverage
from ..core.knobs import server_knobs
from ..core.trace import Severity, TraceEvent
from ..core.wire import Reader, Writer
from .kvstore import IKeyValueStore
from .sim_fs import SimFileSystem

PAGE_SIZE = 4096
_MAGIC = 0x0FDBB7EE
# Page kinds.  _LEAF_C (ISSUE 15) is the prefix-COMPRESSED leaf: one
# shared page prefix + per-entry key suffixes (the reference's Redwood
# page key compression).  Written only under BTREE_PREFIX_COMPRESSION;
# DECODED unconditionally — plain and compressed pages coexist in one
# file, so the knob can flip on a live store and COW rewrites migrate
# pages incrementally (and knobs-off readers still read everything).
_LEAF, _INTERNAL, _LEAF_C = 0, 1, 2
# Split when a serialized page exceeds this (leaving headroom for the
# page header fields).
_SPLIT_BYTES = PAGE_SIZE - 64
# Values above this spill to overflow page chains.
_OVERFLOW_BYTES = 1024
# Usable payload per overflow page (after the 8-byte len+crc frame).
_OVF_PAYLOAD = PAGE_SIZE - 8


def _frame_page(blob: bytes) -> bytes:
    """len:4 | crc:4 | blob — every data/overflow page carries a CRC
    (reference: Redwood checksums every page).  Bit-rot that still
    DECODES would otherwise be served silently; the header-slot CRC only
    protects the roots."""
    return (len(blob).to_bytes(4, "little") +
            zlib.crc32(blob).to_bytes(4, "little") + blob)


def _unframe_page(raw: bytes) -> Optional[bytes]:
    """The page payload, or None if the frame fails its CRC."""
    n = int.from_bytes(raw[:4], "little")
    blob = raw[8:8 + n]
    if len(blob) != n or \
            zlib.crc32(blob) != int.from_bytes(raw[4:8], "little"):
        return None
    return blob


class OverflowRef:
    """A leaf record's value stored out-of-line in whole pages."""

    __slots__ = ("length", "pages")

    def __init__(self, length: int, pages: List[int]) -> None:
        self.length = length
        self.pages = pages

    def ref_size(self) -> int:
        return 8 + 4 * len(self.pages)


Value = Union[bytes, OverflowRef]

# Keys are sorted within a page, so the prefix shared by first and last
# is shared by EVERY key (one implementation: core/wire.py, shared with
# the columnar wire frames).
from ..core.wire import longest_common_prefix_len as _shared_prefix_len  # noqa: E402


class _Node:
    __slots__ = ("kind", "keys", "values", "children")

    def __init__(self, kind: int, keys=None, values=None, children=None):
        self.kind = kind
        self.keys: List[bytes] = keys or []       # leaf: record keys;
        self.values: List[Value] = values or []   # internal: separators
        self.children: List[int] = children or []

    def _page_prefix_len(self) -> int:
        keys = self.keys
        if not keys:
            return 0
        return _shared_prefix_len(keys[0], keys[-1])

    def encode(self) -> bytes:
        if self.kind == _LEAF:
            blob = self._encode_leaf(
                bool(server_knobs().BTREE_PREFIX_COMPRESSION))
            if blob[0] == _LEAF and 8 + len(blob) > PAGE_SIZE:
                # Knob-flip safety valve: a leaf PACKED under the
                # compressed size estimate (knob was on) being COW-
                # rewritten with the knob now OFF can exceed a page in
                # plain form — and the split machinery can't always
                # recover (halves may still be oversized; clears don't
                # split at all).  Keep such pages compressed: pages
                # self-describe via their kind byte, so the store stays
                # readable either way and the flip stays live-safe.
                blob = self._encode_leaf(True)
            return blob
        w = Writer().u8(self.kind).u32(len(self.keys))
        for k in self.keys:
            w.bytes_(k)
        w.u32(len(self.children))
        for c in self.children:
            w.u32(c)
        return w.done()

    def _encode_leaf(self, compressed: bool) -> bytes:
        if compressed:
            # Compressed leaf: shared prefix once, suffixes per entry.
            p = self._page_prefix_len()
            w = Writer().u8(_LEAF_C).u32(len(self.keys))
            w.bytes_(self.keys[0][:p] if self.keys else b"")
            for k in self.keys:
                w.bytes_(k[p:])
        else:
            w = Writer().u8(_LEAF).u32(len(self.keys))
            for k in self.keys:
                w.bytes_(k)
        for v in self.values:
            if isinstance(v, OverflowRef):
                w.u8(1).u32(v.length).u32(len(v.pages))
                for p in v.pages:
                    w.u32(p)
            else:
                w.u8(0).bytes_(v)
        return w.done()

    @classmethod
    def decode(cls, blob: bytes) -> "_Node":
        r = Reader(blob)
        kind = r.u8()
        n = r.u32()
        if kind == _LEAF_C:
            # Prefix-compressed leaf: reconstruct full keys (always
            # decodable, knob or not — on-disk compat both directions).
            prefix = r.bytes_()
            keys = [prefix + r.bytes_() for _ in range(n)]
            kind = _LEAF
        else:
            keys = [r.bytes_() for _ in range(n)]
        if kind == _LEAF:
            values: List[Value] = []
            for _ in range(n):
                if r.u8():
                    length = r.u32()
                    pages = [r.u32() for _ in range(r.u32())]
                    values.append(OverflowRef(length, pages))
                else:
                    values.append(r.bytes_())
            return cls(_LEAF, keys, values)
        children = [r.u32() for _ in range(r.u32())]
        return cls(_INTERNAL, keys, None, children)

    def size(self) -> int:
        if self.kind == _LEAF:
            if server_knobs().BTREE_PREFIX_COMPRESSION:
                # Split threshold tracks the COMPRESSED encoding, so
                # dense same-prefix keyspaces genuinely pack more
                # entries per page (the estimate stays >= the encoded
                # bytes; commit() still hard-checks PAGE_SIZE).
                p = self._page_prefix_len()
                base = p + 8 + sum(len(k) - p + 8 for k in self.keys)
            else:
                base = sum(len(k) + 8 for k in self.keys)
            return base + sum(
                v.ref_size() if isinstance(v, OverflowRef) else len(v) + 1
                for v in self.values)
        base = sum(len(k) + 8 for k in self.keys)
        return base + 4 * len(self.children)


def _shorten_sep(left_last: bytes, right_first: bytes) -> bytes:
    """Shortest prefix of right_first that still exceeds left_last
    (Redwood-style separator truncation: internal nodes stay small no
    matter how large leaf keys grow)."""
    for i in range(len(right_first)):
        if i >= len(left_last) or right_first[i] != left_last[i]:
            return right_first[:i + 1]
    return right_first


class KVStoreBTree(IKeyValueStore):
    """COW B+tree engine (reference Redwood, simplified)."""

    def __init__(self, fs: SimFileSystem, prefix: str) -> None:
        self.fs = fs
        self.file = fs.open(prefix + ".btree")
        self._uncommitted: List[Tuple[int, bytes, bytes]] = []
        self._cache: Dict[int, _Node] = {}
        # page id -> _Node (tree page) or bytes (raw overflow payload)
        self._dirty: Dict[int, Union[_Node, bytes]] = {}
        self.root = 0          # 0 = empty tree
        self.page_count = 2    # slots 0,1 are headers
        self.commit_seq = 0
        # Reusable page ids (freed by PREVIOUS commits; see module doc).
        self.free: List[int] = []
        self._freed_this_commit: List[int] = []

    # -- paging --------------------------------------------------------------
    async def _read_node(self, page_id: int) -> _Node:
        node = self._dirty.get(page_id) or self._cache.get(page_id)
        if node is None:
            raw = await self.file.read(page_id * PAGE_SIZE, PAGE_SIZE)
            blob = _unframe_page(raw)
            try:
                if blob is None:
                    raise ValueError("page CRC mismatch")
                node = _Node.decode(blob)
            except Exception as e:
                # Rotted page (CRC) or undecodable bytes: this engine
                # must never hand garbage upward — io_error is
                # process-fatal in the storage role above.
                from ..core.error import err
                TraceEvent("BTreePageCorrupt", Severity.Error).detail(
                    "File", self.file.name).detail(
                    "Page", page_id).detail("Reason", repr(e)).log()
                raise err("io_error",
                          f"btree page {page_id} corrupt in "
                          f"{self.file.name}")
            self._cache[page_id] = node
        return node

    def _alloc_id(self) -> int:
        if self.free:
            return self.free.pop()
        page_id = self.page_count
        self.page_count += 1
        return page_id

    def _alloc(self, node: _Node) -> int:
        page_id = self._alloc_id()
        self._dirty[page_id] = node
        return page_id

    def _free_page(self, page_id: int) -> None:
        if page_id >= 2:
            self._freed_this_commit.append(page_id)
            self._cache.pop(page_id, None)
            self._dirty.pop(page_id, None)

    def _free_value(self, v: Value) -> None:
        if isinstance(v, OverflowRef):
            for p in v.pages:
                self._free_page(p)

    def _store_value(self, value: bytes) -> Value:
        """Inline small values; spill large ones to an overflow chain."""
        if len(value) <= _OVERFLOW_BYTES:
            return value
        pages: List[int] = []
        for off in range(0, len(value), _OVF_PAYLOAD):
            chunk = value[off:off + _OVF_PAYLOAD]
            pid = self._alloc_id()
            self._dirty[pid] = bytes(chunk)
            pages.append(pid)
        return OverflowRef(len(value), pages)

    async def _load_value(self, v: Value) -> bytes:
        if not isinstance(v, OverflowRef):
            return v
        parts: List[bytes] = []
        remaining = v.length
        for pid in v.pages:
            raw = self._dirty.get(pid)
            if isinstance(raw, bytes):
                part = raw
            else:
                part = _unframe_page(
                    await self.file.read(pid * PAGE_SIZE, PAGE_SIZE))
                if part is None:
                    from ..core.error import err
                    TraceEvent("BTreePageCorrupt", Severity.Error).detail(
                        "File", self.file.name).detail("Page", pid).detail(
                        "Reason", "overflow CRC mismatch").log()
                    raise err("io_error",
                              f"btree overflow page {pid} corrupt in "
                              f"{self.file.name}")
            parts.append(part[:remaining])
            remaining -= len(parts[-1])
        return b"".join(parts)

    def _header_blob(self) -> bytes:
        w = Writer().u32(_MAGIC).i64(self.commit_seq).u32(self.root)
        w.u32(self.page_count)
        body = w.done()
        return body + zlib.crc32(body).to_bytes(4, "little")

    # -- mutation ------------------------------------------------------------
    def set(self, key: bytes, value: bytes) -> None:
        self._uncommitted.append((0, key, value))

    def clear(self, begin: bytes, end: bytes) -> None:
        self._uncommitted.append((1, begin, end))

    async def _cow_set(self, page_id: int, key: bytes, value: bytes) -> int:
        """Insert/overwrite; returns the NEW page id for this subtree
        (list of ids if the node split)."""
        if page_id == 0:
            return self._alloc(_Node(_LEAF, [key], [self._store_value(value)]))
        node = await self._read_node(page_id)
        if node.kind == _LEAF:
            i = bisect.bisect_left(node.keys, key)
            keys, values = list(node.keys), list(node.values)
            stored = self._store_value(value)
            if i < len(keys) and keys[i] == key:
                self._free_value(values[i])   # replaced value's chain
                values[i] = stored
            else:
                keys.insert(i, key)
                values.insert(i, stored)
            self._free_page(page_id)
            return self._finish(_Node(_LEAF, keys, values))
        ci = bisect.bisect_right(node.keys, key)
        new_child = await self._cow_set(node.children[ci], key, value)
        return self._replace_child(page_id, node, ci, new_child)

    def _finish(self, node: _Node):
        """Allocate `node`, splitting when oversized; returns page id or
        (left_id, sep_key, right_id)."""
        if node.size() <= _SPLIT_BYTES or len(node.keys) < 2:
            return self._alloc(node)
        mid = len(node.keys) // 2
        if node.kind == _LEAF:
            left = _Node(_LEAF, node.keys[:mid], node.values[:mid])
            right = _Node(_LEAF, node.keys[mid:], node.values[mid:])
            sep = _shorten_sep(node.keys[mid - 1], node.keys[mid])
        else:
            # separator mid is promoted, not kept.
            left = _Node(_INTERNAL, node.keys[:mid], None,
                         node.children[:mid + 1])
            right = _Node(_INTERNAL, node.keys[mid + 1:], None,
                          node.children[mid + 1:])
            sep = node.keys[mid]
        return (self._alloc(left), sep, self._alloc(right))

    def _replace_child(self, page_id: int, node: _Node, ci: int, new_child):
        keys = list(node.keys)
        children = list(node.children)
        if isinstance(new_child, tuple):
            lid, sep, rid = new_child
            children[ci:ci + 1] = [lid, rid]
            keys.insert(ci, sep)
        else:
            children[ci] = new_child
        self._free_page(page_id)
        return self._finish(_Node(_INTERNAL, keys, None, children))

    async def _cow_clear(self, page_id: int, begin: bytes,
                         end: bytes) -> int:
        if page_id == 0:
            return 0
        node = await self._read_node(page_id)
        if node.kind == _LEAF:
            pairs = []
            for k, v in zip(node.keys, node.values):
                if begin <= k < end:
                    self._free_value(v)       # cleared record's chain
                else:
                    pairs.append((k, v))
            if len(pairs) == len(node.keys):
                return page_id     # nothing cleared: no COW churn
            self._free_page(page_id)
            if not pairs:
                return 0
            return self._alloc(_Node(_LEAF, [k for k, _ in pairs],
                                     [v for _, v in pairs]))
        lo = bisect.bisect_right(node.keys, begin)
        hi = bisect.bisect_left(node.keys, end) + 1
        keys: List[bytes] = []
        children: List[int] = []
        changed = False
        for ci, child in enumerate(node.children):
            if lo <= ci < hi:
                new_child = await self._cow_clear(child, begin, end)
                changed = changed or new_child != child
                child = new_child
            if child != 0:
                if children:
                    # Separator between the previous kept child and this
                    # one: the original separator just left of child ci
                    # upper-bounds every earlier subtree and lower-bounds
                    # this one (ci > 0 whenever a child was already kept).
                    keys.append(node.keys[ci - 1])
                children.append(child)
        if not changed:
            return page_id         # subtree untouched: keep the old pages
        self._free_page(page_id)
        if not children:
            return 0
        if len(children) == 1:
            return children[0]
        return self._finish(_Node(_INTERNAL, keys, None, children))

    async def commit(self) -> None:
        batch, self._uncommitted = self._uncommitted, []  # flowlint: state -- owns the drained batch (swap pattern)
        page_count0 = self.page_count  # flowlint: state -- commit-entry snapshot
        free0 = list(self.free)
        root = self.root  # flowlint: state -- commit writes the entry-time root
        for op, a, b in batch:
            if op == 0:
                r = await self._cow_set(root, a, b)
            else:
                r = await self._cow_clear(root, a, b)
            if isinstance(r, tuple):
                lid, sep, rid = r
                r = self._alloc(_Node(_INTERNAL, [sep], None, [lid, rid]))
            root = r
        # Validate page sizes BEFORE any write so an oversized record
        # (a single KEY too large for a page — values overflow, keys do
        # not) fails cleanly with the tree untouched.
        encoded = {}
        for page_id, node in self._dirty.items():
            if isinstance(node, bytes):
                encoded[page_id] = node        # raw overflow payload
                continue
            blob = node.encode()
            if 8 + len(blob) > PAGE_SIZE:
                from ..core.error import err
                self._dirty = {}
                self.page_count = page_count0
                self.free = free0
                self._freed_this_commit = []
                raise err("operation_failed",
                          "btree key exceeds page capacity")
            encoded[page_id] = blob
        # Write dirty pages, fsync, then the next header slot, fsync
        # (reference: commit == one durable header write).
        for page_id, blob in encoded.items():
            await self.file.write(page_id * PAGE_SIZE, _frame_page(blob))
        await self.file.sync()
        for page_id, node in self._dirty.items():
            if isinstance(node, _Node):
                self._cache[page_id] = node
        self._dirty = {}
        self.root = root
        self.commit_seq += 1
        slot = self.commit_seq % 2
        await self.file.write(slot * PAGE_SIZE, self._header_blob())
        await self.file.sync()
        # Pages orphaned by THIS commit become reusable from the next one
        # (the previous tree stays intact under this commit's writes, so a
        # torn next-commit still recovers cleanly).
        self.free.extend(self._freed_this_commit)
        self._freed_this_commit = []

    # -- reads ---------------------------------------------------------------
    def read_value(self, key: bytes) -> Optional[bytes]:
        return self._sync(self._aread_value(key))

    async def _aread_value(self, key: bytes) -> Optional[bytes]:
        page_id = self.root
        while page_id != 0:
            node = await self._read_node(page_id)
            if node.kind == _LEAF:
                i = bisect.bisect_left(node.keys, key)
                if i < len(node.keys) and node.keys[i] == key:
                    return await self._load_value(node.values[i])
                return None
            page_id = node.children[bisect.bisect_right(node.keys, key)]
        return None

    def read_range(self, begin: bytes, end: bytes, limit: int = 1 << 30
                   ) -> List[Tuple[bytes, bytes]]:
        out: List[Tuple[bytes, bytes]] = []
        if server_knobs().STORAGE_VECTORIZED_SCAN:
            self._sync(self._scan_slices(begin, end, limit, out))
        else:
            self._sync(self._collect(self.root, begin, end, limit, out))
        return out

    async def _scan_slices(self, begin: bytes, end: bytes, limit: int,
                           out: List) -> None:
        """Vectorized scan (STORAGE_VECTORIZED_SCAN, ISSUE 15): an
        iterative walk emitting each leaf's contribution as ONE bisected
        slice (zip over the page's key/value arrays) instead of the
        recursive path's per-key range compare + append — on a
        prefix-compressed store the slice is a near-memcpy of page
        entries.  Output is bit-identical to _collect (parity-tested)."""
        if self.root == 0:
            return
        stack = [self.root]  # flowlint: state -- traversal pinned to entry-time root (COW)
        while stack:
            node = await self._read_node(stack.pop())
            if node.kind != _LEAF:
                lo = bisect.bisect_right(node.keys, begin)
                hi = bisect.bisect_left(node.keys, end) + 1
                # Reversed push: the leftmost child pops first, so rows
                # emit in key order.
                stack.extend(reversed(node.children[lo:hi]))
                continue
            lo = bisect.bisect_left(node.keys, begin)
            hi = bisect.bisect_left(node.keys, end)
            if hi - lo > limit - len(out):
                hi = lo + (limit - len(out))
            if lo >= hi:
                continue
            vs = node.values[lo:hi]
            if any(isinstance(v, OverflowRef) for v in vs):
                for k, v in zip(node.keys[lo:hi], vs):
                    out.append((k, await self._load_value(v)))
            else:
                out.extend(zip(node.keys[lo:hi], vs))
            if len(out) >= limit:
                return

    def stats(self) -> dict:
        """Engine shape for bench/status: page accounting feeds the
        compression-ratio figure (pages needed for the same keyspace,
        compressed vs plain)."""
        return {"engine": "btree", "page_count": self.page_count,
                "free_pages": len(self.free), "commit_seq": self.commit_seq}

    async def _collect(self, page_id: int, begin: bytes, end: bytes,
                       limit: int, out: List) -> None:
        if page_id == 0 or len(out) >= limit:
            return
        node = await self._read_node(page_id)
        if node.kind == _LEAF:
            for k, v in zip(node.keys, node.values):
                if begin <= k < end:
                    out.append((k, await self._load_value(v)))
                    if len(out) >= limit:
                        return
            return
        lo = bisect.bisect_right(node.keys, begin)
        hi = bisect.bisect_left(node.keys, end) + 1
        for ci in range(lo, min(hi, len(node.children))):
            await self._collect(node.children[ci], begin, end, limit, out)
            if len(out) >= limit:
                return

    @staticmethod
    def _sync(coro):
        """Drive a SimFile coroutine to completion synchronously (reads
        are page-cache hits after recovery; SimFile.read itself never
        blocks on other actors)."""
        try:
            while True:
                coro.send(None)
        except StopIteration as e:
            return e.value

    # -- recovery ------------------------------------------------------------
    async def recover(self) -> None:
        best_seq = -1
        for slot in (0, 1):
            blob = await self.file.read(slot * PAGE_SIZE, PAGE_SIZE)
            if len(blob) < 24:
                continue
            body, crc = blob[:20], blob[20:24]
            if zlib.crc32(body) != int.from_bytes(crc, "little"):
                # The double-slot protocol's whole point: a torn or rotted
                # header slot is DETECTED here and the other (older but
                # intact) slot wins — never a half-written root.
                test_coverage("BTreeSlotCrcCaught")
                TraceEvent("BTreeHeaderSlotCorrupt", Severity.Warn).detail(
                    "File", self.file.name).detail("Slot", slot).log()
                continue
            r = Reader(body)
            if r.u32() != _MAGIC:
                continue
            seq = r.i64()
            root = r.u32()
            count = r.u32()
            if seq > best_seq:
                best_seq, self.root, self.page_count = seq, root, count
        if best_seq >= 0:
            self.commit_seq = best_seq
        else:
            self.root, self.page_count, self.commit_seq = 0, 2, 0
        self._cache.clear()
        self._dirty = {}
        await self._rebuild_free_list()
        TraceEvent("BTreeRecovered").detail("Seq", self.commit_seq).detail(
            "Root", self.root).detail("Pages", self.page_count).detail(
            "Free", len(self.free)).log()

    async def _rebuild_free_list(self) -> None:
        """Reachability walk from the recovered root: every allocated page
        not referenced by the live tree (or its overflow chains) is free.
        The free list thus needs no durable format — the reference pager
        persists its free-list pages instead; a scan is the simpler
        equivalent at this engine's scale."""
        reachable = {0, 1}
        stack = [self.root] if self.root else []  # flowlint: state -- traversal pinned to entry-time root (COW)
        while stack:
            pid = stack.pop()
            if pid in reachable:
                continue
            reachable.add(pid)
            node = await self._read_node(pid)
            if node.kind == _LEAF:
                for v in node.values:
                    if isinstance(v, OverflowRef):
                        reachable.update(v.pages)
            else:
                stack.extend(node.children)
        self.free = [p for p in range(2, self.page_count)
                     if p not in reachable]
