"""DR: continuous asynchronous replication to a SECOND cluster.

Reference: fdbclient/DatabaseBackupAgent.actor.cpp (the `fdbdr` agent):
the source cluster's mutation stream is applied transactionally to a
target cluster, preceded by an initial snapshot copy, so the target
tracks the source with bounded lag and can take over (switchover) after
a drain.  Like the reference's DR (and unlike the backup worker role),
the agent is CLIENT-side: it holds handles to both clusters.

Apply pipeline: mutations are applied in version order; each applied
version batch commits a progress marker in the TARGET database, so a
commit_unknown_result is disambiguated instead of double-applying
(atomic ops are not idempotent) and a restarted agent resumes exactly
where the last one committed."""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..core.error import FdbError, err
from ..core.scheduler import delay
from ..core.trace import TraceEvent
from ..txn.types import Mutation, MutationType, Version
from ..server.system_data import BACKUP_STARTED_KEY, BACKUP_TAG

DR_PROGRESS_KEY = b"\xff/drProgress"


class DatabaseBackupAgent:
    """One DR relationship: source cluster -> target db."""

    def __init__(self, source_cluster, source_db, target_db,
                 tag: str = "dr", info_fn=None) -> None:
        self.cluster = source_cluster
        self.src = source_db
        self.dst = target_db
        self.tag = tag
        # Real-mode source of the live ServerDBInfo (the CLI's CC
        # long-poll); sim tests read it straight off the cluster object.
        self._info_fn = info_fn
        self.start_version: Version = 0
        self.applied_through: Version = 0
        self._stop = False
        self._agent_f = None

    async def _server_db_info(self):
        if self._info_fn is not None:
            return await self._info_fn()
        cc = self.cluster.current_cc()
        return cc.db_info if cc is not None else None

    async def _set_flag(self, on: bool) -> Version:
        t = self.src.create_transaction()
        t.access_system_keys = True
        t.lock_aware = True        # switchover sets the flag under lock
        while True:
            try:
                t.set(BACKUP_STARTED_KEY, b"1" if on else b"0")
                return await t.commit()
            except FdbError as e:
                await t.on_error(e)

    async def _copy_snapshot(self) -> Version:
        """Initial full copy at one source version (chunked writes)."""
        t = self.src.create_transaction()
        while True:
            try:
                kvs = []
                cursor = b""
                while True:
                    chunk = await t.get_range(cursor, b"\xff", limit=1000)
                    kvs.extend(chunk)
                    if len(chunk) < 1000:
                        break
                    cursor = chunk[-1][0] + b"\x00"
                snap_v = (await t.get_read_version()).version
                break
            except FdbError as e:
                await t.on_error(e)
        for i in range(0, len(kvs), 500):
            t2 = self.dst.create_transaction()
            while True:
                try:
                    for k, v in kvs[i:i + 500]:
                        t2.set(k, v)
                    await t2.commit()
                    break
                except FdbError as e:
                    await t2.on_error(e)
        TraceEvent("DRSnapshotCopied").detail("Keys", len(kvs)).detail(
            "Version", snap_v).log()
        return snap_v

    async def _apply_batch(self, version: Version,
                           muts: List[Mutation]) -> None:
        marker = b"%020d" % version
        t = self.dst.create_transaction()
        t.access_system_keys = True
        while True:
            try:
                seen = await t.get(DR_PROGRESS_KEY + self.tag.encode())
                if seen is not None and seen >= marker:
                    return          # already applied (restart/unknown)
                t.set(DR_PROGRESS_KEY + self.tag.encode(), marker)
                for m in muts:
                    if m.type == MutationType.SetValue:
                        t.set(m.param1, m.param2)
                    elif m.type == MutationType.ClearRange:
                        t.clear(m.param1, m.param2)
                    else:
                        t.atomic_op(m.type, m.param1, m.param2)
                await t.commit()
                return
            except FdbError as e:
                await t.on_error(e)

    async def _apply_loop(self, from_version: Version) -> None:
        """Pull BACKUP_TAG from the source's live log system and apply to
        the target in version order."""
        fetch_from = from_version + 1
        while not self._stop:
            info = await self._server_db_info()
            if info is None or not info.tlogs:
                await delay(0.2)
                continue
            from ..server.commit_proxy import LogSystemClient
            # Replication from the broadcast info itself (sim config as
            # fallback for old snapshots): popping with too small a
            # factor would leave replica TLogs' BACKUP_TAG queues
            # growing forever.
            repl = getattr(info, "log_replication", 0) or getattr(
                getattr(self.cluster, "config", None),
                "log_replication", 1)
            ls = LogSystemClient(info.tlogs, repl)
            try:
                reply = await ls.peek_tag(BACKUP_TAG, fetch_from)
            except FdbError:
                await delay(0.2)
                continue
            for version, msgs in reply.messages:
                if version >= fetch_from and msgs:
                    # Only user-range mutations ride BACKUP_TAG (the
                    # proxy clips them), so applying verbatim is safe.
                    await self._apply_batch(version, msgs)
            self.applied_through = max(self.applied_through,
                                       reply.end - 1)
            if reply.messages:
                ls.pop(BACKUP_TAG, reply.messages[-1][0])
            fetch_from = max(fetch_from, reply.end)
            if not reply.messages:
                await delay(0.05)

    async def submit(self) -> None:
        """Start DR: activate the source's mutation capture, copy the
        snapshot, then stream continuously.  Replay starts AFTER the
        snapshot version — mutations in (start, snap_v] are already
        inside the copied snapshot, and replaying them again would
        double-apply non-idempotent atomic ops."""
        self.start_version = await self._set_flag(True)
        snap_v = await self._copy_snapshot()
        self.applied_through = snap_v
        self._agent_f = self.cluster.loop.spawn(
            self._apply_loop(snap_v), f"dr.{self.tag}")
        TraceEvent("DRStarted").detail("StartVersion",
                                       self.start_version).detail(
            "SnapshotVersion", snap_v).log()

    async def drain(self) -> Version:
        """Quiesce point: wait until everything committed on the source
        so far has been applied to the target."""
        t = self.src.create_transaction()
        while True:
            try:
                target = (await t.get_read_version()).version
                break
            except FdbError as e:
                await t.on_error(e)
        while self.applied_through < target:
            await delay(0.05)
        return target

    async def switchover(self) -> Version:
        """Drained handover (reference atomicSwitchover): LOCK the source
        (write fence — no commit can land past the drain point), stop
        capture, apply the tail, and return the version through which the
        target is an exact copy.  The caller then points clients at the
        target cluster; the source stays locked until an operator
        unlock_database()s it."""
        from .management import lock_database
        await lock_database(self.src, uid=b"dr:" + self.tag.encode())
        stop_version = await self._set_flag(False)
        while self.applied_through < stop_version - 1:
            await delay(0.05)
        self._stop = True
        if self._agent_f is not None:
            await self._agent_f
        TraceEvent("DRSwitchover").detail(
            "Through", self.applied_through).log()
        return self.applied_through

    def abort(self) -> None:
        self._stop = True
        if self._agent_f is not None and not self._agent_f.is_ready():
            self._agent_f.cancel()
