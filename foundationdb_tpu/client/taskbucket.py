"""TaskBucket: the database-resident resumable task queue.

Reference: fdbclient/TaskBucket.actor.cpp (:1361 and the available/
timeouts/ keyspaces): long operations (backup snapshots, restores) are
decomposed into small tasks stored IN the database; any number of
stateless agents claim tasks transactionally, heartbeat ownership, and
either finish them or die — a timed-out task simply becomes claimable
again, so progress survives any individual agent.  Exactly-once effects
come from doing a task's final effects and its removal in ONE
transaction.

Keyspace (under `prefix`):
  avail/<uid>              packed task (claimable)
  run/<deadline>/<uid>     packed task (claimed; deadline = version time)
Claim moves avail -> run with a deadline; extend() pushes the deadline;
finish() removes; claim() also reclaims any run/ entry whose deadline
passed (the crashed-agent path).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from ..core.error import FdbError, err
from ..core.scheduler import delay
from ..core.trace import Severity, TraceEvent
from ..core.wire import Reader, Writer


class Task:
    def __init__(self, uid: bytes, task_type: str,
                 params: Dict[bytes, bytes], deadline: int = 0) -> None:
        self.uid = uid
        self.type = task_type
        self.params = params
        self.deadline = deadline

    def pack(self) -> bytes:
        w = Writer().str_(self.type).u16(len(self.params))
        for k, v in self.params.items():
            w.bytes_(k).bytes_(v)
        return w.done()

    @classmethod
    def unpack(cls, uid: bytes, blob: bytes, deadline: int = 0) -> "Task":
        r = Reader(blob)
        t = r.str_()
        params = {r.bytes_(): r.bytes_() for _ in range(r.u16())}
        return cls(uid, t, params, deadline)


class TaskBucket:
    """One task queue rooted at `prefix` (reference TaskBucket)."""

    def __init__(self, prefix: bytes = b"\xff/taskBucket/",
                 timeout_versions: int = 5_000_000) -> None:
        self.prefix = prefix
        self.timeout = timeout_versions   # ~5s of version time

    def _avail(self, uid: bytes = b"") -> bytes:
        return self.prefix + b"avail/" + uid

    def _run(self, deadline: int = 0, uid: bytes = b"") -> bytes:
        return self.prefix + b"run/" + b"%020d/" % deadline + uid

    # -- producer ------------------------------------------------------------
    def add(self, tr, task_type: str, params: Dict[bytes, bytes],
            uid: Optional[bytes] = None) -> bytes:
        """Add a task inside the caller's transaction (so task creation
        is atomic with whatever scheduled it)."""
        if uid is None:
            from ..core.rng import deterministic_random
            uid = deterministic_random().random_unique_id().encode()
        tr.access_system_keys = True
        tr.set(self._avail(uid), Task(uid, task_type, params).pack())
        return uid

    async def add_task(self, db, task_type: str,
                       params: Dict[bytes, bytes]) -> bytes:
        t = db.create_transaction()
        while True:
            try:
                uid = self.add(t, task_type, params)
                await t.commit()
                return uid
            except FdbError as e:
                await t.on_error(e)

    # -- consumer ------------------------------------------------------------
    async def claim_one(self, db) -> Optional[Task]:
        """Claim an available task, or reclaim a timed-out running one.
        Returns None when nothing is claimable."""
        t = db.create_transaction()
        t.access_system_keys = True
        while True:
            try:
                now_v = (await t.get_read_version()).version
                # Timed-out running tasks first (deadline ordering makes
                # them the FIRST run/ entries).
                run_rows = await t.get_range(self._run(),
                                             self.prefix + b"run0", limit=1)
                if run_rows:
                    k, blob = run_rows[0]
                    tail = k[len(self.prefix) + 4:]
                    deadline = int(tail[:20])
                    uid = tail[21:]
                    if deadline < now_v:
                        t.clear(k)
                        nd = now_v + self.timeout
                        t.set(self._run(nd, uid), blob)
                        await t.commit()
                        from ..core.coverage import test_coverage
                        test_coverage("TaskBucketReclaim")
                        TraceEvent("TaskBucketReclaimed").detail(
                            "Uid", uid).log()
                        return Task.unpack(uid, blob, nd)
                rows = await t.get_range(self._avail(),
                                         self.prefix + b"avail0", limit=1)
                if not rows:
                    return None
                k, blob = rows[0]
                uid = k[len(self._avail()):]
                t.clear(k)
                nd = now_v + self.timeout
                t.set(self._run(nd, uid), blob)
                await t.commit()
                return Task.unpack(uid, blob, nd)
            except FdbError as e:
                await t.on_error(e)

    async def check_owned(self, tr, task: Task) -> None:
        """Assert ownership INSIDE a work transaction: reads the run
        entry (adding a read-conflict range), so if the task was
        reclaimed — before or concurrently — this transaction aborts
        instead of applying a zombie's effects.  Every non-idempotent
        batch a long task commits must call this (reference TaskBucket
        verifyTask)."""
        tr.access_system_keys = True
        if await tr.get(self._run(task.deadline, task.uid)) is None:
            raise err("operation_failed",
                      "task reclaimed by another agent")

    async def finish(self, tr, task: Task) -> None:
        """Remove a claimed task INSIDE the caller's transaction: commit
        the task's final effects and its completion atomically (the
        exactly-once contract).  Verifies ownership by READING the run
        entry — if the task timed out and was reclaimed, this raises and
        the whole final transaction (effects included) aborts, leaving
        the reclaimer's execution as the only one whose effects land."""
        tr.access_system_keys = True
        key = self._run(task.deadline, task.uid)
        cur = await tr.get(key)
        if cur is None:
            # NON-retryable (operation_failed): retrying through
            # on_error would loop forever — the run entry is gone for
            # good.  run_tasks catches this and moves to the next task;
            # the reclaimer owns the re-execution.
            raise err("operation_failed",
                      "task reclaimed by another agent")
        tr.clear(key)

    async def extend(self, db, task: Task) -> bool:
        """Heartbeat: push the deadline.  False if the task was reclaimed
        or finished elsewhere (the agent must abandon it)."""
        t = db.create_transaction()
        t.access_system_keys = True
        while True:
            try:
                cur = await t.get(self._run(task.deadline, task.uid))
                if cur is None:
                    return False
                now_v = (await t.get_read_version()).version
                t.clear(self._run(task.deadline, task.uid))
                nd = now_v + self.timeout
                t.set(self._run(nd, task.uid), cur)
                await t.commit()
                task.deadline = nd
                return True
            except FdbError as e:
                await t.on_error(e)

    async def is_empty(self, db) -> bool:
        t = db.create_transaction()
        t.access_system_keys = True
        while True:
            try:
                rows = await t.get_range(self.prefix, self.prefix + b"\xff",
                                         limit=1)
                return not rows
            except FdbError as e:
                await t.on_error(e)


async def run_tasks(db, bucket: TaskBucket,
                    handlers: Dict[str, Callable], agent_id: str = "agent",
                    idle_delay: float = 0.2,
                    stop: Optional[Callable[[], bool]] = None) -> None:
    """An agent loop (reference TaskBucket's doOne/run): claim, dispatch
    to the handler registry, repeat.  Handlers receive (db, bucket, task)
    and MUST call bucket.finish(tr, task) inside their final transaction;
    a handler that dies leaves the task to time out and be reclaimed."""
    while not (stop and stop()):
        task = await bucket.claim_one(db)
        if task is None:
            await delay(idle_delay)
            continue
        handler = handlers.get(task.type)
        if handler is None:
            TraceEvent("TaskBucketUnknownType", Severity.Warn).detail(
                "Type", task.type).log()
            await delay(idle_delay)
            continue
        try:
            await handler(db, bucket, task)
            TraceEvent("TaskBucketDone").detail("Agent", agent_id).detail(
                "Type", task.type).detail("Uid", task.uid).log()
        except FdbError as e:
            TraceEvent("TaskBucketTaskError", Severity.Warn).detail(
                "Type", task.type).detail("Error", e.name).log()
            await delay(idle_delay)
