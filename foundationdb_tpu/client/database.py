"""Database / Transaction: the client API.

Reference: fdbclient/NativeAPI.actor.cpp (Database/Transaction — GRV
batching :2717, location cache :2334, getValue :2476, getRange :3311,
tryCommit :5018, onError retry loop) layered with ReadYourWrites semantics
(fdbclient/ReadYourWrites.actor.cpp): reads see the transaction's own
uncommitted writes, and read/write conflict ranges accrue automatically.

Usage:
    db = Database(cluster)
    async def work():
        txn = db.create_transaction()
        while True:
            try:
                v = await txn.get(b"counter")
                txn.set(b"counter", bump(v))
                await txn.commit()
                return
            except FdbError as e:
                await txn.on_error(e)
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from ..core.error import FdbError, err
from ..core.futures import Future
from ..core.knobs import client_knobs
from ..core.scheduler import delay
from ..rpc.endpoint import RequestStream
from ..server.interfaces import (CommitTransactionRequest,
                                 GetKeyServerLocationsRequest,
                                 GetKeyValuesRequest, GetReadVersionRequest,
                                 GetValueRequest, TransactionPriority,
                                 WatchValueRequest)
from ..server.shardmap import RangeMap
from ..txn.types import (CommitTransactionRef, KeyRange, MutationType,
                         Version, key_after)
from .writemap import WriteMap

RETRYABLE = frozenset({
    "not_committed", "transaction_too_old", "future_version",
    "commit_unknown_result", "process_behind", "proxy_memory_limit_exceeded",
    "broken_promise", "request_maybe_delivered", "connection_failed",
    "wrong_shard_server",
})


class ClusterConnection:
    """Dynamic cluster connection: tracks the elected cluster controller
    via the coordinators and long-polls its ClientDBInfo (reference
    MonitorLeader.actor.cpp + OpenDatabaseRequest)."""

    def __init__(self, coordinators) -> None:
        from ..core.futures import AsyncVar
        from ..core.scheduler import spawn
        from ..server.coordination import monitor_leader
        from ..server.interfaces import ClientDBInfo
        self.coordinators = coordinators
        self.leader = AsyncVar(None)
        self.client_info = AsyncVar(ClientDBInfo())
        self._actors = [
            spawn(monitor_leader(coordinators, self.leader),
                  "client.monitorLeader"),
            spawn(self._open_database_loop(), "client.openDatabase"),
        ]

    @property
    def grv_proxies(self):
        return self.client_info.get().grv_proxies

    @property
    def commit_proxies(self):
        return self.client_info.get().commit_proxies

    async def wait_ready(self) -> None:
        while not (self.grv_proxies and self.commit_proxies):
            await self.client_info.on_change()

    async def _open_database_loop(self) -> None:
        from ..core.futures import wait_any
        from ..core.scheduler import delay
        from ..server.interfaces import OpenDatabaseRequest
        known_epoch = -1
        while True:
            leader = self.leader.get()
            cc = leader.serialized_info if leader else None
            if cc is None:
                await self.leader.on_change()
                continue
            reply_f = RequestStream.at(cc.open_database.endpoint).get_reply(
                OpenDatabaseRequest(known_epoch=known_epoch))
            # Race the long-poll against a leader change: a parked poll on
            # a deposed/dead CC must not strand us.
            change_f = self.leader.on_change()
            idx, _ = await wait_any([_swallow(reply_f), change_f])
            if idx == 1:
                continue
            if reply_f.is_error():
                await delay(0.5)
                continue
            info = reply_f.get()
            known_epoch = info.epoch
            self.client_info.set(info)

    async def get_status(self) -> dict:
        """Fetch the status JSON document from the cluster controller
        (reference `fdbcli status json` / \\xff\\xff/status/json)."""
        from ..server.status import StatusRequest
        while True:
            leader = self.leader.get()
            cc = leader.serialized_info if leader else None
            if cc is None:
                await self.leader.on_change()
                continue
            try:
                return await RequestStream.at(
                    cc.get_status.endpoint).get_reply(StatusRequest())
            except FdbError:
                from ..core.scheduler import delay
                await delay(0.5)

    def close(self) -> None:
        for a in self._actors:
            if not a.is_ready():
                a.cancel()


from ..core.futures import swallow as _swallow


class Database:
    """Client handle to a cluster (reference DatabaseContext)."""

    def __init__(self, cluster: Any) -> None:
        # `cluster` provides grv_proxies / commit_proxies interface lists —
        # a static harness adapter or a ClusterConnection.
        self.cluster = cluster
        self._location_cache: RangeMap = RangeMap(default=None)
        self._rr = 0   # round-robin over proxies / replicas
        # Per-replica EWMA latency (reference QueueModel feeding
        # LoadBalance.actor.h): reads prefer faster replicas; a failed
        # attempt is penalized so the replica sorts last until the
        # penalty decays and it proves itself again.
        self._replica_latency: dict = {}
        # TSS comparison mismatches observed by this client (reference
        # TSS metrics); tests assert on it.
        self.tss_mismatches = 0
        # Shadows this client already quarantined (by mirror endpoint):
        # no further comparison traffic is sent to a benched TSS.
        self._tss_quarantined: set = set()
        # Read-version acquisition fast paths (ISSUE 14; both knob-gated,
        # default off — the knobs-off client issues exactly one GRV per
        # transaction as before):
        #  - _grv_lease: (expires_at, reply) — a GRV_LEASE_S-bounded
        #    cached read version (causal-read-risky: a leased version may
        #    trail the latest commit; OCC still aborts stale read-write
        #    conflicts, and this client's OWN commits bump the lease
        #    floor so read-your-own-writes holds per client).
        #  - _grv_batch: waiters of the in-flight client-side GRV batch
        #    (reference readVersionBatcher): concurrent plain
        #    transactions share one GetReadVersionRequest with
        #    transaction_count = N.
        self._grv_lease: Optional[Tuple[float, Any]] = None
        self._grv_batch: Optional[List[Any]] = None
        self._grv_refreshing = False
        # This client's highest committed version: the floor below which
        # a GRV reply must never ARM the lease (a reply resolved at the
        # proxy before our commit can arrive after it — arming with it
        # would break per-client read-your-own-writes while the lease
        # was empty).
        self._grv_commit_floor: Version = 0
        # Lease hits not yet reported to the GRV plane: piggybacked on
        # the NEXT real request's transaction_count, so the ratekeeper's
        # released-rate accounting still sees the true transaction load.
        # Without this the lease starves the release signal, the
        # ratekeeper clamps tps to ~nothing whenever any spring dips,
        # and the few real GRVs (lease refreshes included!) queue for
        # seconds — measured as a ~2x e2e commits/s loss.
        self._grv_leases_unreported = 0
        self.grv_stats = {"leased": 0, "batched": 0, "requests": 0,
                          "refreshes": 0}

    from ..rpc.endpoint import TRANSPORT_ERRORS as _FAILOVER_ERRORS

    # Replicas whose EWMA latencies fall in the same band alternate
    # round-robin — strict fastest-first would pin ALL reads onto one
    # replica and halve the team's read throughput.
    _LATENCY_BAND = 0.05

    @staticmethod
    def _replica_key(ssi):
        ep = getattr(getattr(ssi, "get_value", None), "_endpoint", None)
        return ep or id(ssi)

    def _order_replicas(self, ssis):
        self._rr += 1
        rr = self._rr
        # Age penalties/estimates toward zero so a demoted replica is
        # re-probed eventually instead of staying blacklisted forever.
        for k in self._replica_latency:
            self._replica_latency[k] *= 0.9
        return sorted(
            ssis, key=lambda s: (
                int(self._replica_latency.get(self._replica_key(s), 0.0)
                    / self._LATENCY_BAND),
                (rr + ssis.index(s)) % len(ssis)))

    def _note_latency(self, ssi, dt: float) -> None:
        k = self._replica_key(ssi)
        prev = self._replica_latency.get(k, dt)
        self._replica_latency[k] = 0.8 * prev + 0.2 * dt

    def _tss_compare(self, pair, stream_of, make_request, reply) -> None:
        """TSS comparison (reference fdbrpc/TSSComparison.h + LoadBalance
        duplicate-to-TSS): mirror the read to the shadow OUT OF BAND and
        trace any divergence — the client never waits on the shadow.
        TSS_SAMPLE_RATE bounds the duplicate-read overhead."""
        from ..core.knobs import client_knobs
        from ..core.rng import deterministic_random
        from ..core.scheduler import spawn as _spawn
        if self._replica_key(pair) in self._tss_quarantined:
            return              # already benched: no more compare traffic
        rate = float(client_knobs().TSS_SAMPLE_RATE)
        if rate < 1.0 and deterministic_random().random01() > rate:
            return

        async def compare() -> None:
            from ..core.error import FdbError
            from ..core.trace import Severity, TraceEvent
            try:
                shadow = await RequestStream.at(
                    stream_of(pair).endpoint).get_reply(make_request())
            except FdbError:
                return          # shadow lag/death is not a mismatch
            for attr in ("value", "data"):
                a = getattr(reply, attr, None)
                b = getattr(shadow, attr, None)
                if a != b:
                    self.tss_mismatches += 1
                    TraceEvent("TSSMismatch", Severity.Error).detail(
                        "Field", attr).detail(
                        "Primary", repr(a)[:80]).detail(
                        "Shadow", repr(b)[:80]).log()
                    await self._quarantine_tss(pair, attr)
                    return
        _spawn(compare(), "client.tssCompare")

    async def _quarantine_tss(self, pair, field: str) -> None:
        """Bench a mismatching shadow (reference tssQuarantine follow-up to
        TSSComparison): tell the TSS to stop serving, and record the
        quarantine in the system keyspace so operators can find — and,
        after inspection, clear — it.  Both steps are best-effort: the
        mismatch is already traced, and a dead shadow needs no benching."""
        from ..core.error import FdbError
        from ..server.interfaces import TssQuarantineRequest
        from ..server.system_data import tss_quarantine_key
        self._tss_quarantined.add(self._replica_key(pair))
        try:
            await RequestStream.at(
                pair.tss_quarantine.endpoint).get_reply(
                TssQuarantineRequest(reason=f"mismatch on {field}"))
        except FdbError:
            pass
        for _ in range(5):      # commit the marker; retry cheap conflicts
            t = self.create_transaction()
            t.access_system_keys = True
            try:
                t.set(tss_quarantine_key(getattr(pair, "tag", 0)),
                      field.encode())
                await t.commit()
                return
            except FdbError as e:
                try:
                    await t.on_error(e)
                except FdbError:
                    return

    async def read_replica(self, ssis, stream_of, make_request):
        """One storage read with REPLICA FAILOVER and HEDGING (reference
        LoadBalance.actor.h): replicas are tried fastest-first; transport
        failures move to the next replica instead of surfacing, so a dead
        replica costs latency, not a client error.  When the preferred
        replica is SLOW (no reply within the hedge delay) the request is
        duplicated to the next replica and the first answer wins — a
        degraded-but-alive replica costs the hedge delay, not its full
        stall (reference secondRequestPool duplicate requests).
        Non-transport errors (wrong_shard_server, future_version, ...)
        raise through."""
        from ..core.futures import swallow, wait_any
        from ..core.knobs import client_knobs
        from ..core.scheduler import delay as _delay
        from ..core.scheduler import now as _now
        hedge_s = float(client_knobs().HEDGE_REQUEST_DELAY)
        ordered = self._order_replicas(list(ssis))
        last: Optional[BaseException] = None
        i = 0
        while i < len(ordered):
            ssi = ordered[i]
            t0 = _now()
            f = RequestStream.at(
                stream_of(ssi).endpoint).get_reply(make_request())
            hedge = None
            hedge_ssi = None
            hedge_t0 = 0.0
            demoted = False
            if i + 1 < len(ordered):
                # The losing hedge timer stays in the scheduler heap
                # until it fires: one (float, lambda) tuple living
                # hedge_s — a few hundred entries even at 10k reads/s,
                # not worth a cancellable-timer mechanism.
                idx, _ = await wait_any([swallow(f), _delay(hedge_s)])
                if idx == 1 and not f.is_ready():
                    hedge_ssi = ordered[i + 1]
                    hedge_t0 = _now()
                    hedge = RequestStream.at(
                        stream_of(hedge_ssi).endpoint).get_reply(
                        make_request())
                    await wait_any([swallow(f), swallow(hedge)])
                    if hedge.is_ready() and not f.is_ready():
                        # Hedge won: demote the laggard so later reads
                        # prefer the responsive replica.  Its own latency
                        # is measured from ITS send, not t0 — charging
                        # the hedge delay to the winner would misorder
                        # it below genuinely slower replicas.
                        self._note_latency(ssi, 1.0)
                        demoted = True
                        if not hedge.is_error():
                            self._note_latency(hedge_ssi,
                                               _now() - hedge_t0)
                            return hedge.get()
                        # Hedge errored while the preferred replica is
                        # STILL silent: if replicas remain beyond both,
                        # move on rather than waiting out the stall (the
                        # abandoned read is idempotent); with nothing
                        # left, the slow-but-alive replica is still the
                        # best bet — fall through and await it.
                        e2 = hedge.error
                        if getattr(e2, "name", "") not in \
                                self._FAILOVER_ERRORS:
                            raise e2
                        self._note_latency(hedge_ssi, 1.0)
                        last = e2
                        if i + 2 < len(ordered):
                            i += 2
                            continue
                        hedge = None       # spent; await f below
            try:
                reply = await f
                self._note_latency(ssi, _now() - t0)
                pair = getattr(ssi, "tss_pair", None)
                if pair is not None:
                    self._tss_compare(pair, stream_of, make_request, reply)
                return reply
            except FdbError as e:
                if e.name in self._FAILOVER_ERRORS:
                    if not demoted:
                        self._note_latency(ssi, 1.0)  # demote; decays back
                    last = e
                    # The hedge may still deliver: harvest it before
                    # moving on (it targeted the NEXT replica).
                    if hedge is not None:
                        try:
                            reply = await hedge
                            self._note_latency(hedge_ssi,
                                               _now() - hedge_t0)
                            return reply
                        except FdbError as e2:
                            if e2.name not in self._FAILOVER_ERRORS:
                                raise
                            self._note_latency(hedge_ssi, 1.0)
                            last = e2
                            i += 1      # both tried: skip the hedged one
                    i += 1
                    continue
                raise
        raise last or err("wrong_shard_server", "no replica answered")

    # -- proxies -------------------------------------------------------------
    async def _await_ready(self) -> None:
        waiter = getattr(self.cluster, "wait_ready", None)
        if waiter is not None:
            await waiter()

    def _grv_proxy(self):
        proxies = self.cluster.grv_proxies
        if not proxies:
            raise err("request_maybe_delivered", "no GRV proxies known yet")
        self._rr += 1
        return proxies[self._rr % len(proxies)]

    def _commit_proxy(self):
        proxies = self.cluster.commit_proxies
        if not proxies:
            raise err("request_maybe_delivered",
                      "no commit proxies known yet")
        self._rr += 1
        return proxies[self._rr % len(proxies)]

    # -- read-version acquisition (reference readVersionBatcher :2717) -------
    def _read_version_future(self, priority: int, debug_id: str,
                             tags: tuple, tenant_id: int) -> Future:
        """One transaction's read-version future.  Plain requests
        (DEFAULT priority, no tags/tenant/debug id) may be served from
        the lease or folded into the client-side batch; everything else
        — throttle tags, tenant identity, priorities, traced txns —
        keeps its own request so proxy-side enforcement and the
        scheduling predictor see the true identity."""
        from ..core.futures import Promise
        knobs = client_knobs()
        plain = (priority == TransactionPriority.DEFAULT and not tags
                 and tenant_id == -1 and not debug_id)
        if plain:
            reply = self._leased_read_version()
            if reply is not None:
                self.grv_stats["leased"] += 1
                self._grv_leases_unreported += 1
                p: Promise = Promise()
                p.send(reply)
                return p.get_future()
            if knobs.GRV_BATCH_ENABLED:
                p = Promise()
                if self._grv_batch is None:
                    self._grv_batch = [p]
                    from ..core.scheduler import spawn
                    spawn(self._flush_grv_batch(), "client.grvBatcher")
                else:
                    self.grv_stats["batched"] += 1
                    self._grv_batch.append(p)
                return p.get_future()
        self.grv_stats["requests"] += 1
        proxy = self._grv_proxy()
        count = 1
        if plain:
            count += self._take_unreported_leases()
        return RequestStream.at(
            proxy.get_consistent_read_version.endpoint).get_reply(
            GetReadVersionRequest(priority=priority, debug_id=debug_id,
                                  transaction_count=count,
                                  tags=tags, tenant_id=tenant_id))

    def _take_unreported_leases(self) -> int:
        n, self._grv_leases_unreported = self._grv_leases_unreported, 0
        return n

    async def _flush_grv_batch(self) -> None:
        """Close the batching window, issue ONE GRV carrying the whole
        batch's transaction_count (the ratekeeper budget charge stays
        exact), fan the reply out to every waiter."""
        from ..core.scheduler import delay
        await delay(float(client_knobs().GRV_BATCH_TIMEOUT))
        waiters, self._grv_batch = self._grv_batch or [], None  # flowlint: state -- owns the drained batch (swap pattern)
        self.grv_stats["requests"] += 1
        try:
            proxy = self._grv_proxy()
            reply = await RequestStream.at(
                proxy.get_consistent_read_version.endpoint).get_reply(
                GetReadVersionRequest(
                    transaction_count=(len(waiters) +
                                       self._take_unreported_leases())))
        except BaseException as e:  # noqa: BLE001 — waiters must never
            # hang: every promise gets the failure (retryable at each
            # transaction's own retry loop); cancellation keeps unwinding.
            for p in waiters:
                if not p.is_set():
                    p.send_error(err("request_maybe_delivered",
                                     f"batched GRV failed: {e!r}"))
            if not isinstance(e, Exception):
                raise
            return
        self._note_grv_reply(reply)
        for p in waiters:
            if not p.is_set():
                p.send(reply)

    def _leased_read_version(self):
        """The cached GRV reply while the lease is fresh, else None.
        A hit in the BACK HALF of the window kicks one background
        refresh, so under steady traffic the lease renews without any
        transaction ever blocking on the expiry round trip (the
        synchronous miss-burst — all committers stalling on one GRV at
        once — measurably costs ~25% e2e commits/s)."""
        lease_s = float(client_knobs().GRV_LEASE_S)
        if lease_s <= 0.0 or self._grv_lease is None:
            return None
        from ..core.scheduler import now
        expires, reply = self._grv_lease
        t = now()
        if t <= expires:
            if t > expires - lease_s / 2 and not self._grv_refreshing:
                self._grv_refreshing = True
                from ..core.scheduler import spawn
                spawn(self._refresh_lease(), "client.grvLeaseRefresh")
            return reply
        self._grv_lease = None
        return None

    async def _refresh_lease(self) -> None:
        """Background lease renewal: one plain GRV whose reply re-arms
        the window.  Failures are dropped — the next consumer then pays
        the round trip like any lease miss."""
        try:
            self.grv_stats["requests"] += 1
            self.grv_stats["refreshes"] += 1
            proxy = self._grv_proxy()
            reply = await RequestStream.at(
                proxy.get_consistent_read_version.endpoint).get_reply(
                GetReadVersionRequest(
                    transaction_count=(1 +
                                       self._take_unreported_leases())))
            self._note_grv_reply(reply)
        except FdbError:
            pass
        finally:
            self._grv_refreshing = False

    def _note_grv_reply(self, reply) -> None:
        """Fold a genuine proxy reply into the lease (never synthetic
        set_read_version futures — they lack the reply surface — and
        never locked-database replies); the lease version only moves
        forward.  Each reply object is folded AT MOST ONCE: a lease HIT
        re-observes the cached reply at consumption, and letting that
        refresh the expiry would slide the lease forever under
        continuous traffic — the GRV_LEASE_S staleness bound must be
        measured from a real proxy round trip."""
        lease_s = float(client_knobs().GRV_LEASE_S)
        if lease_s <= 0.0 or not hasattr(reply, "tag_throttles") or \
                getattr(reply, "locked", False):
            return
        if getattr(reply, "_lease_noted", False):
            return
        reply._lease_noted = True
        from ..core.scheduler import now
        if reply.version < self._grv_commit_floor:
            # Resolved at the proxy before our own latest commit:
            # arming the (possibly empty) lease with it would serve
            # later transactions a version below this client's writes.
            import dataclasses as _dc
            reply = _dc.replace(reply, version=self._grv_commit_floor)
            reply._lease_noted = True
        if self._grv_lease is not None and \
                self._grv_lease[1].version > reply.version:
            # The held version is newer (e.g. our own commit bumped the
            # floor), but this FRESH round trip still proves recency:
            # refresh the expiry on the newer held reply.
            self._grv_lease = (now() + lease_s, self._grv_lease[1])
            return
        self._grv_lease = (now() + lease_s, reply)

    def _note_commit_version(self, version: Version) -> None:
        """This client's own commit bumps the lease floor so a later
        leased transaction reads its writes (per-client causality; the
        proxies reported the version to the master before the commit
        reply, so `version` is a legal read version cluster-wide).  The
        floor is tracked even while no lease is armed: an in-flight GRV
        reply that RESOLVED before this commit may otherwise arm the
        lease below it."""
        if float(client_knobs().GRV_LEASE_S) <= 0.0:
            return
        if version > self._grv_commit_floor:
            self._grv_commit_floor = version
        if self._grv_lease is None:
            return
        expires, reply = self._grv_lease
        if version > reply.version:
            import dataclasses as _dc
            bumped = _dc.replace(reply, version=version)
            # The copy is lease-internal, not a fresh proxy round trip:
            # it must never re-enter _note_grv_reply as "new" (expiry
            # would slide; see there).
            bumped._lease_noted = True
            self._grv_lease = (expires, bumped)

    # -- location cache (reference getKeyLocation :2334) ---------------------
    async def get_key_location(self, key: bytes) -> List[Any]:
        cached = self._location_cache.lookup(key)
        if cached is not None:
            return cached
        proxy = self._commit_proxy()
        reply = await RequestStream.at(
            proxy.get_key_servers_locations.endpoint).get_reply(
            GetKeyServerLocationsRequest(begin=key, end=key_after(key)))
        for rng, ssis in reply.results:
            self._location_cache.set_range(rng.begin, rng.end, ssis)
        out = self._location_cache.lookup(key)
        if out is None:
            raise err("wrong_shard_server", f"no location for {key!r}")
        return out

    async def get_location_before(self, end: bytes
                                  ) -> Tuple[bytes, bytes, List[Any]]:
        """Shard containing the greatest key strictly below `end` (for
        reverse scans)."""
        b, e, ssis = self._location_cache.range_before(end)
        if ssis is not None:
            return b, e, ssis
        proxy = self._commit_proxy()
        reply = await RequestStream.at(
            proxy.get_key_servers_locations.endpoint).get_reply(
            GetKeyServerLocationsRequest(begin=b"", end=end, limit=1,
                                         reverse=True))
        for rng, team in reply.results:
            self._location_cache.set_range(rng.begin, rng.end, team)
        b, e, ssis = self._location_cache.range_before(end)
        if ssis is None:
            raise err("wrong_shard_server", f"no location before {end!r}")
        return b, e, ssis

    def invalidate_cache(self, key: bytes) -> None:
        self._location_cache.set_range(key, key_after(key), None)

    async def get_shard_location(self, key: bytes):
        """(shard_begin, shard_end, [StorageServerInterface]) for the shard
        containing `key` — the ConsistencyCheck/audit surface."""
        await self.get_key_location(key)
        return self._location_cache.range_containing(key)

    def create_transaction(self) -> "Transaction":
        return Transaction(self)

    async def open_tenant(self, name: bytes):
        """Open a Tenant handle by name (tenant/handle.py); raises
        tenant_not_found for unknown names."""
        from ..tenant.handle import open_tenant
        return await open_tenant(self, name)


class Transaction:
    """One transaction attempt chain (reference Transaction + RYW)."""

    def __init__(self, db: Database) -> None:
        self.db = db
        self._backoff = client_knobs().DEFAULT_BACKOFF
        self._reset()

    def _reset(self) -> None:
        """Clear per-attempt state (keeps backoff and options; see
        reset/on_error)."""
        self._read_version: Optional[Future] = None
        self.writes = WriteMap()
        self.read_conflict_ranges: List[Tuple[bytes, bytes]] = []
        self._extra_write_ranges: List[Tuple[bytes, bytes]] = []
        self.committed_version: Version = -1
        self.priority = TransactionPriority.DEFAULT
        # Per-attempt versionstamp future: dropped on reset, so waiters of
        # a failed attempt see broken_promise (reference: the versionstamp
        # future errors when the transaction is reset).
        self._versionstamp_promise = None
        self._committed_stamp = None
        self._committed_readonly = False
        # Reference ACCESS_SYSTEM_KEYS transaction option: \xff keys are
        # rejected unless explicitly enabled (management/DD transactions).
        if not hasattr(self, "access_system_keys"):
            self.access_system_keys = False
        # Reference LOCK_AWARE option: commits pass the database lock
        # fence (\xff/dbLocked); management/DR traffic only.
        if not hasattr(self, "lock_aware"):
            self.lock_aware = False
        # REPORT_CONFLICTING_KEYS option + the resulting ranges of the
        # last not_committed attempt, surfaced via
        # \xff\xff/transaction/conflicting_keys (reference RYW +
        # SpecialKeySpace ConflictingKeysImpl).  Both survive _reset so
        # the retry loop can read them before on_error clears state.
        if not hasattr(self, "report_conflicting_keys"):
            self.report_conflicting_keys = False
        if not hasattr(self, "_conflicting_keys"):
            # Survives attempt resets: the RETRY reads the previous
            # attempt's conflicts (reference: conflicting-keys special
            # keys are populated for the attempt after the conflict).
            self._conflicting_keys: List[Tuple[bytes, bytes]] = []
        # Throttling tag (reference TransactionOptions::tags /
        # fdbclient/TagThrottle): carried on GRVs (proxy-side throttle
        # enforcement) and on reads (storage busy-tag sampling).
        if not hasattr(self, "tag"):
            self.tag: str = ""
        # Tenant identity (reference TenantInfo on CommitTransactionRef):
        # set by tenant handles (tenant/handle.py); commit proxies
        # validate tenant-tagged commits against their tenant cache and
        # reject prefix escapes.  -1 = raw (tenant-less) transaction.
        if not hasattr(self, "tenant_id"):
            self.tenant_id: int = -1
        # DEBUG_TRANSACTION_IDENTIFIER (reference option): a non-empty id
        # rides the commit request and is correlated to the proxy's batch
        # span in CommitDebug trace events.
        if not hasattr(self, "debug_id"):
            self.debug_id: str = ""
        # Transaction-repair opt-in (sched/repair.py, ISSUE 12): the
        # client declares its mutations remain valid under re-read
        # (blind writes, atomic ops, existence guards), so the commit
        # proxy may re-stamp a staleness-only abort at a fresh read
        # version and re-resolve it server-side instead of bouncing.
        # NEVER set this on a transaction whose mutation VALUES were
        # computed from its reads — the server cannot re-run client
        # logic, so a repair would commit stale derivations.
        if not hasattr(self, "repairable"):
            self.repairable: bool = False

    def reset(self) -> None:
        self._conflicting_keys = []
        self._reset()
        self._backoff = client_knobs().DEFAULT_BACKOFF

    # -- read version --------------------------------------------------------
    def get_read_version(self) -> Future:
        if self._read_version is None:
            if self.debug_id:
                # GRV leg of the cross-role timeline (reference
                # g_traceBatch "TransactionDebug" NativeAPI points,
                # reassembled by tools/commit_debug.py).
                from ..core.trace import trace_batch_event
                trace_batch_event(
                    "TransactionDebug", self.debug_id,
                    "NativeAPI.getConsistentReadVersion.Before")
            self._read_version = self.db._read_version_future(
                priority=self.priority, debug_id=self.debug_id,
                tags=(self.tag,) if self.tag else (),
                tenant_id=self.tenant_id)
        return self._read_version

    GRV_TIMEOUT = 5.0
    COMMIT_TIMEOUT = 10.0

    def set_read_version(self, version: Version) -> None:
        """Read at a caller-chosen version (reference
        fdb_transaction_set_read_version): chunked backup snapshots read
        every chunk at ONE version for a consistent image."""
        from types import SimpleNamespace
        from ..core.futures import Promise
        p: Promise = Promise()
        p.send(SimpleNamespace(version=version))
        self._read_version = p.get_future()

    async def _ensure_read_version(self) -> Version:
        from ..core.futures import wait_any
        first_acquire = self._read_version is None  # flowlint: state -- remembers pre-GRV state for tracing
        if first_acquire:
            await self.db._await_ready()
        f = self.get_read_version()
        idx, _ = await wait_any([f, delay(self.GRV_TIMEOUT)])
        if idx == 1:
            # Recovery in flight: the proxy we asked is gone or wedged.
            self._read_version = None
            raise err("request_maybe_delivered", "GRV timed out")
        if self.debug_id and first_acquire:
            from ..core.trace import trace_batch_event
            trace_batch_event("TransactionDebug", self.debug_id,
                              "NativeAPI.getConsistentReadVersion.After")
        reply = f.get()
        self.db._note_grv_reply(reply)
        return reply.version

    # Special keyspace (reference SpecialKeySpace.actor.h ConflictingKeys
    # module): boundary keys under this prefix with \x01 = range begin,
    # \x00 = range end, populated after a not_committed attempt with
    # report_conflicting_keys set.
    CONFLICTING_KEYS_PREFIX = b"\xff\xff/transaction/conflicting_keys/"

    def _conflicting_key_rows(self) -> List[Tuple[bytes, bytes]]:
        # Coalesce first: per-resolver clipping can split one logical
        # range at resolver boundaries, and un-merged pieces would emit
        # the shared boundary twice with contradictory begin/end markers.
        merged: List[List[bytes]] = []
        for b, e in sorted(self._conflicting_keys):
            if merged and b <= merged[-1][1]:
                merged[-1][1] = max(merged[-1][1], e)
            else:
                merged.append([b, e])
        rows: List[Tuple[bytes, bytes]] = []
        p = self.CONFLICTING_KEYS_PREFIX
        for b, e in merged:
            rows.append((p + b, b"\x01"))
            rows.append((p + e, b"\x00"))
        return rows

    # SpecialKeySpace modules beyond conflicting_keys (reference
    # SpecialKeySpace.actor.cpp module registry): status json and the
    # management mirror — read-your-cluster through plain key reads.
    STATUS_JSON_KEY = b"\xff\xff/status/json"
    MANAGEMENT_EXCLUDED_PREFIX = b"\xff\xff/management/excluded/"
    # Read-only tenant-map mirror (reference SpecialKeySpace
    # TenantMapRangeImpl): \xff\xff/management/tenant/map/<name> = JSON
    # {id, prefix-hex} — tooling lists tenants without raw-\xff access.
    MANAGEMENT_TENANT_MAP_PREFIX = b"\xff\xff/management/tenant/map/"
    # Cluster heat telemetry mirrors (ISSUE 8; reference the
    # \xff\xff/metrics/ special-key module family): read-only rows
    # synthesized from status cluster.heat, so a plain client txn — and
    # the future conflict predictor at the GRV proxy — can consume the
    # hot-range tables without raw-\xff or status-RPC access.
    #   conflict_ranges/<resolver>/<begin-hex> = JSON row
    #   read_hot_ranges/<storage-tag>/<begin-hex> = JSON row
    METRICS_PREFIX = b"\xff\xff/metrics/"
    METRICS_CONFLICT_PREFIX = b"\xff\xff/metrics/conflict_ranges/"
    METRICS_READ_HOT_PREFIX = b"\xff\xff/metrics/read_hot_ranges/"
    # Conflict-aware scheduling plane (ISSUE 12):
    #   scheduler/grv/<proxy>  = JSON predictor/deferral row
    #   scheduler/proxy/<id>   = JSON reorder/repair row
    #   scheduler/totals       = JSON knob posture + cluster totals
    METRICS_SCHEDULER_PREFIX = b"\xff\xff/metrics/scheduler/"
    # Gray-failure plane (ISSUE 18), from status cluster.peer_health:
    #   peer_health/degraded/<address>       = JSON >= K-reporter verdict
    #   peer_health/link/<reporter>/<peer>   = JSON degraded-link row
    METRICS_PEER_HEALTH_PREFIX = b"\xff\xff/metrics/peer_health/"

    @staticmethod
    def _tenant_entry_json(entry) -> bytes:
        import json as _json
        return _json.dumps({"id": entry.id,
                            "prefix": entry.prefix.hex()}).encode()

    async def _tenant_sub_txn(self):
        """System-keys sub-transaction PINNED to this transaction's read
        version: tenant-mirror reads are repeatable within one attempt
        (a concurrent delete cannot flip a re-read) and cost no extra
        GRV — the reference SpecialKeySpace reads at the enclosing
        transaction's snapshot the same way."""
        sub = self.db.create_transaction()
        sub.access_system_keys = True
        sub.set_read_version(await self._ensure_read_version())
        return sub

    async def _tenant_map_rows(self, begin: bytes, end: bytes, limit: int,
                               reverse: bool = False
                               ) -> List[Tuple[bytes, bytes]]:
        """Rows of the tenant-map special-key module inside [begin, end)
        (both in \xff\xff space), in iteration order (descending when
        reverse), backed by a system-keys sub-read.  The raw read runs in
        the SAME direction so `limit` selects the correct end of a large
        tenant list."""
        from ..server.system_data import TENANT_MAP_END, TENANT_MAP_PREFIX
        from ..tenant.map import TenantMapEntry
        p = self.MANAGEMENT_TENANT_MAP_PREFIX
        lo = max(begin, p)
        if lo >= end:
            return []
        name_lo = lo[len(p):] if lo.startswith(p) else b""
        raw_end = (min(TENANT_MAP_PREFIX + end[len(p):], TENANT_MAP_END)
                   if end.startswith(p) else TENANT_MAP_END)
        sub = await self._tenant_sub_txn()
        raw = await sub.get_range(TENANT_MAP_PREFIX + name_lo, raw_end,
                                  limit=limit, reverse=reverse)
        return [(p + k[len(TENANT_MAP_PREFIX):],
                 self._tenant_entry_json(TenantMapEntry.decode(v)))
                for k, v in raw]

    def _heat_rows(self, heat: dict) -> List[Tuple[bytes, bytes]]:
        """All rows of both \xff\xff/metrics/ modules, key-sorted.
        Row keys embed the range-begin as HEX so they order like the raw
        keys; values are self-contained JSON rows."""
        import json as _json
        rows: List[Tuple[bytes, bytes]] = []
        conflict = heat.get("conflict_ranges", {}) or {}
        for rid in conflict:
            for row in conflict[rid].get("top_conflict_ranges", []):
                # begin AND end in the key: two hot ranges sharing a
                # begin ([a,b) and [a,c)) must stay distinct rows.
                rows.append((
                    self.METRICS_CONFLICT_PREFIX + rid.encode() + b"/" +
                    row["begin_hex"].encode() + b"-" +
                    row["end_hex"].encode(),
                    _json.dumps(dict(row, resolver=rid)).encode()))
        read_hot = heat.get("read_hot_ranges", {}) or {}
        for tag in read_hot:
            for row in read_hot[tag]:
                rows.append((
                    self.METRICS_READ_HOT_PREFIX + tag.encode() + b"/" +
                    row["begin_hex"].encode() + b"-" +
                    row["end_hex"].encode(),
                    _json.dumps(dict(row, tag=tag)).encode()))
        rows.sort()
        return rows

    def _sched_rows(self, sched: dict) -> List[Tuple[bytes, bytes]]:
        """Rows of the \xff\xff/metrics/scheduler/ module, key-sorted —
        rendered from the SAME status cluster.scheduler document fdbcli
        `metrics` prints, so the surfaces agree by construction."""
        import json as _json
        p = self.METRICS_SCHEDULER_PREFIX
        rows: List[Tuple[bytes, bytes]] = []
        for pid, doc in (sched.get("grv_proxies", {}) or {}).items():
            rows.append((p + b"grv/" + pid.encode(),
                         _json.dumps(dict(doc, proxy=pid)).encode()))
        for pid, doc in (sched.get("commit_proxies", {}) or {}).items():
            rows.append((p + b"proxy/" + pid.encode(),
                         _json.dumps(dict(doc, proxy=pid)).encode()))
        if sched:
            rows.append((p + b"totals", _json.dumps(
                dict(sched.get("totals") or {},
                     enabled=sched.get("enabled") or {})).encode()))
        rows.sort()
        return rows

    def _peer_health_rows(self, doc: dict) -> List[Tuple[bytes, bytes]]:
        """Rows of the \xff\xff/metrics/peer_health/ module, key-sorted —
        rendered from the SAME status cluster.peer_health document fdbcli
        `metrics` prints, so the surfaces agree by construction."""
        import json as _json
        p = self.METRICS_PEER_HEALTH_PREFIX
        rows: List[Tuple[bytes, bytes]] = []
        for row in doc.get("links", []) or []:
            rows.append((
                p + b"link/" + str(row.get("reporter", "")).encode() +
                b"/" + str(row.get("peer", "")).encode(),
                _json.dumps(row).encode()))
        for entry in doc.get("degraded_processes", []) or []:
            rows.append((
                p + b"degraded/" + str(entry.get("address", "")).encode(),
                _json.dumps(entry).encode()))
        rows.sort()
        return rows

    async def _all_metrics_rows(self) -> List[Tuple[bytes, bytes]]:
        """Every row of the \xff\xff/metrics/ module family (heat +
        scheduler + peer health), key-sorted, from ONE status fetch."""
        get_status = getattr(self.db.cluster, "get_status", None)
        if get_status is None:
            return []
        cl = (await get_status()).get("cluster", {})
        rows = self._heat_rows(cl.get("heat", {}) or {})
        rows += self._sched_rows(cl.get("scheduler", {}) or {})
        rows += self._peer_health_rows(cl.get("peer_health", {}) or {})
        rows.sort()
        return rows

    async def _metrics_module_rows(self, begin: bytes, end: bytes,
                                   limit: int, reverse: bool = False
                                   ) -> List[Tuple[bytes, bytes]]:
        rows = [(k, v) for k, v in await self._all_metrics_rows()
                if begin <= k < end]
        if reverse:
            rows.reverse()
        return rows[:limit]

    async def _special_key_get(self, key: bytes) -> Optional[bytes]:
        if key.startswith(self.METRICS_PREFIX):
            for k, v in await self._all_metrics_rows():
                if k == key:
                    return v
            return None
        if key.startswith(self.MANAGEMENT_TENANT_MAP_PREFIX):
            # Read-only mirror: a plain read of a nonexistent/odd name
            # (empty, NUL, overlong) is ABSENT, never a name-validation
            # error — GET and GETRANGE must agree on the same keys, so
            # read the raw map directly rather than via get_tenant().
            from ..tenant.map import TenantMapEntry, tenant_map_key
            name = key[len(self.MANAGEMENT_TENANT_MAP_PREFIX):]
            if not name:
                return None
            sub = await self._tenant_sub_txn()
            raw = await sub.get(tenant_map_key(name))
            return (self._tenant_entry_json(TenantMapEntry.decode(raw))
                    if raw is not None else None)
        if key == self.STATUS_JSON_KEY:
            import json as _json
            get_status = getattr(self.db.cluster, "get_status", None)
            if get_status is None:
                return None
            doc = await get_status()
            return _json.dumps(doc, default=str).encode()
        if key.startswith(self.MANAGEMENT_EXCLUDED_PREFIX):
            from ..server.system_data import excluded_key
            tag = key[len(self.MANAGEMENT_EXCLUDED_PREFIX):]
            sub = self.db.create_transaction()
            sub.access_system_keys = True
            try:
                raw = await sub.get(excluded_key(int(tag)))
            except ValueError:
                return None
            return raw
        return None

    # -- reads ---------------------------------------------------------------
    async def get(self, key: bytes, snapshot: bool = False
                  ) -> Optional[bytes]:
        if key.startswith(self.CONFLICTING_KEYS_PREFIX):
            for k, v in self._conflicting_key_rows():
                if k == key:
                    return v
            return None
        if key.startswith(b"\xff\xff/status/") or \
                key.startswith(b"\xff\xff/management/") or \
                key.startswith(self.METRICS_PREFIX):
            return await self._special_key_get(key)
        _check_key(key, self.access_system_keys)
        if not snapshot:
            self.read_conflict_ranges.append((key, key_after(key)))
        if self.writes.is_unreadable(key):
            raise err("accessed_unreadable")
        if self.writes.has_writes(key) and not self.writes.needs_base(key):
            return self.writes.merge(key, None)
        base = await self._storage_get(key)
        return self.writes.merge(key, base)

    async def _storage_get(self, key: bytes) -> Optional[bytes]:
        version = await self._ensure_read_version()
        ssis = await self.db.get_key_location(key)
        if not ssis:
            raise err("wrong_shard_server", f"no team for {key!r}")
        if self.debug_id:
            # Point-read leg of the cross-role timeline (reference
            # g_traceBatch NativeAPI.getValue points): the id rides the
            # request so storage can stamp its server-side points too.
            from ..core.trace import trace_batch_event
            trace_batch_event("TransactionDebug", self.debug_id,
                              "NativeAPI.getValue.Before")
        try:
            reply = await self.db.read_replica(
                ssis, lambda s: s.get_value,
                lambda: GetValueRequest(key=key, version=version,
                                        debug_id=self.debug_id,
                                        tag=self.tag))
        except FdbError as e:
            if e.name in ("broken_promise", "wrong_shard_server"):
                self.db.invalidate_cache(key)
            raise
        if self.debug_id:
            from ..core.trace import trace_batch_event
            trace_batch_event("TransactionDebug", self.debug_id,
                              "NativeAPI.getValue.After")
        return reply.value

    async def get_range(self, begin: bytes, end: bytes, limit: int = 1000,
                        reverse: bool = False, snapshot: bool = False,
                        limit_bytes: int = 0
                        ) -> List[Tuple[bytes, bytes]]:
        """Range read with RYW overlay (reference getRange :3311).

        The scan proceeds shard chunk by shard chunk from the iteration end
        (begin for forward, end for reverse); each chunk's snapshot data is
        complete for its covered span, so overlaying this transaction's
        writes per-span cannot leave gaps even when the storage reply was
        limit-truncated.

        `limit_bytes` > 0 bounds the TOTAL result bytes across chunks
        (reference GetRangeLimits.bytes): the scan stops once the budget
        is consumed, with the row that crossed it included — so large-
        value scans can stream in bounded slices instead of holding a
        whole shard's rows.  0 (default) keeps the per-chunk storage
        default, the pre-ISSUE-15 behavior."""
        if begin >= end:
            return []
        p = self.CONFLICTING_KEYS_PREFIX
        if begin.startswith(p) or (begin <= p and end > p):
            rows = [(k, v) for k, v in self._conflicting_key_rows()
                    if begin <= k < end]
            if reverse:
                rows.reverse()
            return rows[:limit]
        tp = self.MANAGEMENT_TENANT_MAP_PREFIX
        if begin.startswith(tp) or (begin <= tp and end > tp):
            return await self._tenant_map_rows(begin, end, limit, reverse)
        mp = self.METRICS_PREFIX
        if begin.startswith(mp) or (begin <= mp and end > mp):
            return await self._metrics_module_rows(begin, end, limit,
                                                   reverse)
        if not snapshot:
            self.read_conflict_ranges.append((begin, end))
        version = await self._ensure_read_version()
        out: List[Tuple[bytes, bytes]] = []
        nbytes = 0
        budget = limit_bytes if limit_bytes > 0 else 0
        # Per-chunk request bound: the remaining budget, capped at the
        # storage default — shipping a huge remaining budget as ONE
        # chunk's limit_bytes would ask storage to materialize and
        # encode it all in a single reply frame.
        def chunk_bytes() -> int:
            return min(budget - nbytes, 1 << 20) if budget else 0
        if not reverse:
            cursor = begin
            while cursor < end and len(out) < limit:
                data, covered_end = await self._fetch_chunk_forward(
                    cursor, end, version, limit - len(out), chunk_bytes())
                merged = self._merge_span(data, cursor, covered_end)
                out.extend(merged)
                cursor = covered_end
                if budget:
                    # Only the new span's bytes: re-summing `out` per
                    # chunk would make budgeted scans O(rows^2).
                    nbytes += sum(len(k) + len(v) for k, v in merged)
                    if nbytes >= budget:
                        break
        else:
            cursor = end
            while cursor > begin and len(out) < limit:
                data, covered_begin = await self._fetch_chunk_reverse(
                    begin, cursor, version, limit - len(out), chunk_bytes())
                merged = self._merge_span(sorted(data), covered_begin, cursor)
                out.extend(reversed(merged))
                cursor = covered_begin
                if budget:
                    nbytes += sum(len(k) + len(v) for k, v in merged)
                    if nbytes >= budget:
                        break
        return out[:limit]

    async def _fetch_chunk_forward(
            self, cursor: bytes, end: bytes, version: Version, limit: int,
            limit_bytes: int = 0
    ) -> Tuple[List[Tuple[bytes, bytes]], bytes]:
        """One storage fetch; returns (data, covered_end): the snapshot is
        complete over [cursor, covered_end)."""
        ssis = await self.db.get_key_location(cursor)
        _, rng_e, _ = self.db._location_cache.range_containing(cursor)
        shard_end = min(rng_e, end)
        if not ssis:
            raise err("wrong_shard_server")
        kwargs = {"limit_bytes": limit_bytes} if limit_bytes > 0 else {}
        if self.debug_id:
            # Per-chunk points (reference g_traceBatch NativeAPI.getRange):
            # a multi-shard scan shows one Before/After pair per storage
            # round-trip in the read waterfall.
            from ..core.trace import trace_batch_event
            trace_batch_event("TransactionDebug", self.debug_id,
                              "NativeAPI.getRange.Before")
        reply = await self.db.read_replica(
            ssis, lambda s: s.get_key_values,
            lambda: GetKeyValuesRequest(begin=cursor, end=shard_end,
                                        version=version, limit=limit,
                                        debug_id=self.debug_id,
                                        tag=self.tag, **kwargs))
        if self.debug_id:
            from ..core.trace import trace_batch_event
            trace_batch_event("TransactionDebug", self.debug_id,
                              "NativeAPI.getRange.After")
        if reply.more and reply.data:
            return reply.data, key_after(reply.data[-1][0])
        return reply.data, shard_end

    async def _fetch_chunk_reverse(
            self, begin: bytes, cursor: bytes, version: Version, limit: int,
            limit_bytes: int = 0
    ) -> Tuple[List[Tuple[bytes, bytes]], bytes]:
        """One reverse storage fetch; returns (data descending,
        covered_begin): complete over [covered_begin, cursor)."""
        rng_b, _, ssis = await self.db.get_location_before(cursor)
        shard_begin = max(rng_b, begin)
        if not ssis:
            raise err("wrong_shard_server")
        kwargs = {"limit_bytes": limit_bytes} if limit_bytes > 0 else {}
        if self.debug_id:
            from ..core.trace import trace_batch_event
            trace_batch_event("TransactionDebug", self.debug_id,
                              "NativeAPI.getRange.Before")
        reply = await self.db.read_replica(
            ssis, lambda s: s.get_key_values,
            lambda: GetKeyValuesRequest(begin=shard_begin, end=cursor,
                                        version=version, limit=limit,
                                        reverse=True, debug_id=self.debug_id,
                                        tag=self.tag, **kwargs))
        if self.debug_id:
            from ..core.trace import trace_batch_event
            trace_batch_event("TransactionDebug", self.debug_id,
                              "NativeAPI.getRange.After")
        if reply.more and reply.data:
            return reply.data, reply.data[-1][0]   # inclusive smallest key
        return reply.data, shard_begin

    def _merge_span(self, base: List[Tuple[bytes, bytes]], begin: bytes,
                    end: bytes) -> List[Tuple[bytes, bytes]]:
        """Overlay writes onto a snapshot that is COMPLETE over [begin, end);
        returns ascending merged items for exactly that span."""
        if not self.writes.mutations:
            return list(base)
        merged = dict(base)
        for _, cb, ce in self.writes.clears_in(begin, end):
            for k in [k for k in merged if cb <= k < ce]:
                del merged[k]
        for key in self.writes.touched_keys_in(begin, end):
            val = self.writes.merge(key, merged.get(key))
            if val is None:
                merged.pop(key, None)
            else:
                merged[key] = val
        return sorted(merged.items())

    async def watch(self, key: bytes) -> Future:
        """Returns a future that fires when `key`'s value changes from its
        value as of this transaction's read version (reference watches)."""
        version = await self._ensure_read_version()
        value = await self.get(key, snapshot=True)
        ssis = await self.db.get_key_location(key)
        ssi = ssis[0]
        return RequestStream.at(ssi.watch_value.endpoint).get_reply(
            WatchValueRequest(key=key, value=value, version=version))

    # -- writes --------------------------------------------------------------
    def set(self, key: bytes, value: bytes) -> None:
        _check_key(key, self.access_system_keys)
        _check_value(value)
        self.writes.set(key, value)

    def clear(self, key: bytes, end: Optional[bytes] = None) -> None:
        _check_key(key, self.access_system_keys)
        self.writes.clear(key, end if end is not None else key_after(key))

    def atomic_op(self, op: MutationType, key: bytes, operand: bytes) -> None:
        _check_key(key, self.access_system_keys)
        self.writes.atomic_op(op, key, operand)

    # -- versionstamped operations (reference CommitTransaction.h:55-96,
    # versionstamp future NativeAPI.actor.cpp:5094) -------------------------
    def set_versionstamped_key(self, key_template: bytes, offset: int,
                               value: bytes) -> None:
        """Set a key whose 10-byte slot at `offset` is replaced with the
        commit versionstamp (8B big-endian version + 2B batch index) by
        the commit proxy.  `key_template[offset:offset+10]` is the
        placeholder."""
        _check_key(key_template, self.access_system_keys)
        _check_value(value)
        if not 0 <= offset <= len(key_template) - 10:
            raise err("client_invalid_operation",
                      "versionstamp slot out of range")
        self.writes.atomic_op(
            MutationType.SetVersionstampedKey,
            key_template + offset.to_bytes(4, "little"), value)

    def set_versionstamped_value(self, key: bytes, value_template: bytes,
                                 offset: int = 0) -> None:
        """Set `key` to a value whose 10-byte slot at `offset` becomes the
        commit versionstamp."""
        _check_key(key, self.access_system_keys)
        _check_value(value_template)
        if not 0 <= offset <= len(value_template) - 10:
            raise err("client_invalid_operation",
                      "versionstamp slot out of range")
        self.writes.atomic_op(
            MutationType.SetVersionstampedValue, key,
            value_template + offset.to_bytes(4, "little"))

    def get_versionstamp(self) -> Future:
        """Future for this attempt's 10-byte versionstamp; resolves after
        a successful commit, errors on a read-only commit (no commit
        version exists), and breaks on reset."""
        if self._versionstamp_promise is None:
            from ..core.futures import Promise
            self._versionstamp_promise = Promise()
            if self._committed_stamp is not None:
                self._versionstamp_promise.send(self._committed_stamp)
            elif self.committed_version == -1 and \
                    self._committed_readonly:
                self._versionstamp_promise.send_error(
                    err("operation_failed",
                        "read-only transaction has no versionstamp"))
        return self._versionstamp_promise.get_future()

    def add_read_conflict_range(self, begin: bytes, end: bytes) -> None:
        self.read_conflict_ranges.append((begin, end))

    def add_write_conflict_range(self, begin: bytes, end: bytes) -> None:
        self._extra_write_ranges.append((begin, end))

    # -- commit (reference tryCommit :5018) ----------------------------------
    async def commit(self) -> Version:
        wcr = self.writes.write_conflict_ranges() + self._extra_write_ranges  # flowlint: state -- commit resolves the entry-time write set
        if not self.writes.mutations and not wcr:
            # Read-only: nothing to resolve (reference returns immediately).
            self.committed_version = -1
            self._committed_readonly = True
            if self._versionstamp_promise is not None and \
                    not self._versionstamp_promise.is_set():
                self._versionstamp_promise.send_error(
                    err("operation_failed",
                        "read-only transaction has no versionstamp"))
            return -1
        read_snapshot = 0
        if self.read_conflict_ranges:
            read_snapshot = await self._ensure_read_version()
        txn = CommitTransactionRef(  # flowlint: state -- one txn snapshot per commit attempt
            read_conflict_ranges=[KeyRange(b, e) for b, e in
                                  _coalesce(self.read_conflict_ranges)],
            write_conflict_ranges=[KeyRange(b, e) for b, e in
                                   _coalesce(wcr)],
            mutations=self.writes.mutations,
            read_snapshot=read_snapshot,
            report_conflicting_keys=self.report_conflicting_keys,
            lock_aware=self.lock_aware,
            tenant_id=self.tenant_id,
            tag=self.tag)
        if txn.expected_size() > client_knobs().TRANSACTION_SIZE_LIMIT:
            raise err("transaction_too_large")
        await self.db._await_ready()
        proxy = self.db._commit_proxy()
        from ..core.futures import wait_any
        if self.debug_id:
            from ..core.trace import trace_batch_event
            trace_batch_event("TransactionDebug", self.debug_id,
                              "NativeAPI.commit.Before")
        f = RequestStream.at(proxy.commit.endpoint).get_reply(  # flowlint: state -- the in-flight commit future
            CommitTransactionRequest(transaction=txn,
                                     debug_id=self.debug_id,
                                     repair_eligible=self.repairable))
        try:
            idx, _ = await wait_any([f, delay(self.COMMIT_TIMEOUT)])
        except FdbError as e:
            # The proxy may have logged the commit before dying: a lost
            # reply means the outcome is UNKNOWN, never "didn't happen" —
            # retrying as not-committed could double-apply (reference
            # tryCommit maps these to commit_unknown_result).
            if e.name in ("broken_promise", "connection_failed",
                          "request_maybe_delivered"):
                raise err("commit_unknown_result", f"commit lost: {e.name}")
            if e.name == "not_committed":
                # Conflicting read ranges ride the error reply; surface
                # them as \xff\xff/transaction/conflicting_keys to the
                # retry (reference NativeAPI :5118-5123).
                self._conflicting_keys = list(getattr(e, "details", []))
            raise
        if idx == 1:
            raise err("commit_unknown_result", "commit timed out")
        reply = f.get()
        if self.debug_id:
            from ..core.trace import trace_batch_event
            trace_batch_event("TransactionDebug", self.debug_id,
                              "NativeAPI.commit.After")
        self.committed_version = reply.version
        self.db._note_commit_version(reply.version)
        from ..txn.types import make_versionstamp
        self._committed_stamp = make_versionstamp(reply.version,
                                                  reply.txn_batch_index)
        if self._versionstamp_promise is not None and \
                not self._versionstamp_promise.is_set():
            self._versionstamp_promise.send(self._committed_stamp)
        return reply.version

    # -- retry loop (reference onError) --------------------------------------
    async def on_error(self, e: BaseException) -> None:
        if not (isinstance(e, FdbError) and e.name in RETRYABLE):
            raise e
        knobs = client_knobs()
        backoff = self._backoff
        self._reset()
        self._backoff = min(backoff * knobs.BACKOFF_GROWTH_RATE,
                            knobs.DEFAULT_MAX_BACKOFF)
        await delay(backoff)

    async def run(self, fn) -> Any:
        """Retry loop helper (reference runRYWTransaction): `fn(txn)` is an
        async callable; retried on retryable errors after reset."""
        while True:
            try:
                result = await fn(self)
                await self.commit()
                return result
            except BaseException as e:  # noqa: BLE001
                await self.on_error(e)


def _check_key(key: bytes, allow_system: bool = False) -> None:
    if len(key) > client_knobs().KEY_SIZE_LIMIT:
        raise err("key_too_large")
    if key >= (b"\xff\xff" if allow_system else b"\xff"):
        raise err("key_outside_legal_range")


def _check_value(value: bytes) -> None:
    if len(value) > client_knobs().VALUE_SIZE_LIMIT:
        raise err("value_too_large")


def _coalesce(ranges: List[Tuple[bytes, bytes]]
              ) -> List[Tuple[bytes, bytes]]:
    """Sort + merge overlapping conflict ranges."""
    if not ranges:
        return []
    rs = sorted(r for r in ranges if r[0] < r[1])
    out = [rs[0]] if rs else []
    for b, e in rs[1:]:
        if b <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((b, e))
    return out


def open_cluster(cluster_spec: str, ip: str = "127.0.0.1",
                 tls: Optional[dict] = None):
    """Real-mode client bootstrap (reference fdb_c fdb_setup_network +
    cluster-file open): installs a real-IO EventLoop and RealNetwork in
    this process and returns (loop, Database) connected to the
    coordinators in `cluster_spec` ("host:port,host:port,...").  Drive
    transactions with loop.run_until(loop.spawn(coro))."""
    from ..core.rng import DeterministicRandom, set_deterministic_random
    from ..core.scheduler import EventLoop, set_event_loop
    from ..rpc.network import set_network
    from ..rpc.real_network import RealNetwork
    from ..server.coordination import CoordinationClientInterface
    from ..server.fdbserver import parse_coordinators

    loop = EventLoop(sim=False)
    set_event_loop(loop)
    import os
    set_deterministic_random(DeterministicRandom(os.getpid() & 0x7FFFFFFF))
    net = RealNetwork(loop, ip, 0, tls=tls)
    set_network(net)
    coords = [CoordinationClientInterface.at_address(a)
              for a in parse_coordinators(cluster_spec)]
    return loop, Database(ClusterConnection(coords))
