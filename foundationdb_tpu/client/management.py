"""Management API: operator actions as ordinary transactions.

Reference: fdbclient/ManagementAPI.actor.cpp — excludeServers /
includeServers write the exclusion list under `\\xff/conf/`; the data
distributor reacts by draining data off excluded servers.  Everything
here is a plain serializable transaction: the operator surface has no
private channel into the cluster (the point of "configuration as data").
"""

from __future__ import annotations

from typing import Iterable, List

from ..core.error import FdbError, err
from ..server.system_data import (COORDINATORS_KEY, EXCLUDED_END,
                                  EXCLUDED_PREFIX, excluded_key)


async def _retrying(db, fn):
    t = db.create_transaction()
    t.access_system_keys = True
    while True:
        try:
            r = await fn(t)
            await t.commit()
            return r
        except FdbError as e:
            await t.on_error(e)


async def exclude_servers(db, tags: Iterable[int]) -> None:
    """Mark storage servers (by tag) excluded: the DD drains every shard
    off them; they stop being placement candidates immediately
    (reference excludeServers)."""
    async def go(t):
        for tag in tags:
            t.set(excluded_key(tag), b"1")
    await _retrying(db, go)


async def include_servers(db, tags: Iterable[int] = None) -> None:
    """Re-admit excluded servers (None = everyone; reference
    includeServers)."""
    async def go(t):
        if tags is None:
            t.clear(EXCLUDED_PREFIX, EXCLUDED_END)
        else:
            for tag in tags:
                t.clear(excluded_key(tag))
    await _retrying(db, go)


async def excluded_servers(db) -> List[int]:
    t = db.create_transaction()
    t.access_system_keys = True
    while True:
        try:
            rows = await t.get_range(EXCLUDED_PREFIX, EXCLUDED_END)
            return [int(k[len(EXCLUDED_PREFIX):]) for k, v in rows
                    if v == b"1"]
        except FdbError as e:
            await t.on_error(e)


async def change_configuration(db, **fields) -> None:
    """Change the database configuration transactionally (reference
    `fdbcli configure` -> ManagementAPI changeConfig writing \\xff/conf/
    keys): role counts and engine settings become ordinary committed
    state — they survive exactly what the database survives, and the
    transaction system recovers into the new shape."""
    from ..server.system_data import conf_key

    async def go(t):
        for name, value in fields.items():
            if value is None:
                t.clear(conf_key(name))
            else:
                t.set(conf_key(name), str(value).encode())
    await _retrying(db, go)


async def lock_database(db, uid: bytes = None) -> bytes:
    """Lock the database (reference ManagementAPI lockDatabase /
    `fdbcli lock`): commits a UID to \\xff/dbLocked; from that version
    on, proxies reject every non-LOCK_AWARE commit with database_locked.
    Returns the UID (needed to unlock).  Locking an already-locked
    database with a DIFFERENT uid raises database_locked."""
    from ..core.error import err
    from ..server.system_data import DB_LOCKED_KEY
    if uid is None:
        from ..core.rng import deterministic_random
        uid = deterministic_random().random_unique_id()[:16].encode()
    t = db.create_transaction()
    t.access_system_keys = True
    t.lock_aware = True
    while True:
        try:
            cur = await t.get(DB_LOCKED_KEY)
            if cur is not None and cur != uid:
                raise err("database_locked",
                          "already locked by another uid")
            if cur is None:
                t.set(DB_LOCKED_KEY, uid)
                await t.commit()
            return uid
        except FdbError as e:
            if e.name == "database_locked":
                raise
            await t.on_error(e)


async def unlock_database(db, uid: bytes) -> None:
    """Unlock (reference unlockDatabase / `fdbcli unlock`): the UID must
    match the one that locked, or database_locked is raised."""
    from ..core.error import err
    from ..server.system_data import DB_LOCKED_KEY
    t = db.create_transaction()
    t.access_system_keys = True
    t.lock_aware = True
    while True:
        try:
            cur = await t.get(DB_LOCKED_KEY)
            if cur is None:
                return
            if cur != uid:
                raise err("database_locked", "uid mismatch")
            t.clear(DB_LOCKED_KEY)
            await t.commit()
            return
        except FdbError as e:
            if e.name == "database_locked":
                raise
            await t.on_error(e)


async def change_coordinators(db, new_spec: str) -> None:
    """changeQuorum (reference fdbclient/ManagementAPI.actor.cpp
    changeQuorumChecker): verify the target quorum answers a coordinated
    read, then commit the new connection spec to \\xff/coordinators.  The
    master notices the divergence, seeds the new quorum with the current
    DBCoreState, forwards the old one, and ends its epoch; workers and
    clients follow the forward replies onto the new quorum
    (server/coordination.py move_coordinated_state)."""
    from ..server.coordination import (CoordinatedState, normalize_spec,
                                       parse_spec)
    new_spec = normalize_spec(new_spec)   # committed form is canonical
    coords = parse_spec(new_spec)
    if not coords:
        raise err("client_invalid_operation", "empty coordinator spec")
    cur_coords = getattr(db.cluster, "coordinators", None) or []
    cur_addrs = {(c.reg_read.address.ip, c.reg_read.address.port)
                 for c in cur_coords
                 if getattr(c.reg_read, "address", None) is not None}
    new_addrs = {(c.reg_read.address.ip, c.reg_read.address.port)
                 for c in coords}
    if cur_addrs & new_addrs:
        raise err("client_invalid_operation",
                  "new quorum must not share members with the current one "
                  "(single-register forward limitation; change in two "
                  "disjoint steps)")
    probe = CoordinatedState(coords)
    try:
        await probe.read()
    except FdbError as e:
        if e.name == "coordinators_changed":
            raise err("client_invalid_operation",
                      f"target quorum {new_spec} is itself forwarded")
        raise

    async def go(t):
        t.set(COORDINATORS_KEY, new_spec.encode())
    await _retrying(db, go)


async def get_coordinators(db) -> str:
    """The committed coordinator spec ("" before any changeQuorum)."""
    async def go(t):
        raw = await t.get(COORDINATORS_KEY)
        return raw.decode() if raw else ""
    return await _retrying(db, go)


async def set_knob(db, name: str, value, scope: str = "server") -> None:
    """Dynamic knob change (reference `fdbcli setknob` through the config
    DB): commits \\xff/knobs/<scope>/<name> and bumps the change marker;
    every worker's LocalConfiguration watch applies it live."""
    from ..server.system_data import KNOBS_CHANGED_KEY, knob_key
    if scope not in ("server", "client", "flow"):
        raise err("client_invalid_operation", f"unknown knob scope {scope}")

    async def go(t):
        if value is None:
            t.clear(knob_key(scope, name))
        else:
            t.set(knob_key(scope, name), str(value).encode())
        t.set(KNOBS_CHANGED_KEY, b"1")
    await _retrying(db, go)


async def get_knob_overrides(db) -> dict:
    """Committed dynamic-knob overrides: {'scope/NAME': raw}."""
    from ..server.system_data import KNOBS_END, KNOBS_PREFIX

    async def go(t):
        rows = await t.get_range(KNOBS_PREFIX, KNOBS_END)
        return {k[len(KNOBS_PREFIX):].decode(): v.decode()
                for k, v in rows}
    return await _retrying(db, go)


async def cache_range(db, begin: bytes, end: bytes) -> None:
    """Mark [begin, end) as cached (reference `fdbcli cache_range set`):
    commit proxies mirror its mutations onto CACHE_TAG and the
    StorageCache roles fetch + serve it (worker.py _storage_cache_watch)."""
    from ..server.system_data import (CACHE_RANGES_CHANGED_KEY,
                                      cache_range_key)
    if not begin < end:
        raise err("inverted_range", "cache_range begin >= end")

    async def go(t):
        t.set(cache_range_key(begin), end)
        t.set(CACHE_RANGES_CHANGED_KEY, b"1")
    await _retrying(db, go)


async def uncache_range(db, begin: bytes) -> None:
    from ..server.system_data import (CACHE_RANGES_CHANGED_KEY,
                                      cache_range_key)

    async def go(t):
        t.clear(cache_range_key(begin))
        t.set(CACHE_RANGES_CHANGED_KEY, b"1")
    await _retrying(db, go)


async def get_configuration(db) -> dict:
    """The committed \\xff/conf/ overrides (absent fields use static
    defaults)."""
    from ..server.system_data import CONF_END, CONF_PREFIX, EXCLUDED_PREFIX

    async def go(t):
        rows = await t.get_range(CONF_PREFIX, CONF_END)
        return {k[len(CONF_PREFIX):].decode(): v for k, v in rows
                if not k.startswith(EXCLUDED_PREFIX)}
    return await _retrying(db, go)
