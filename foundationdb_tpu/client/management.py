"""Management API: operator actions as ordinary transactions.

Reference: fdbclient/ManagementAPI.actor.cpp — excludeServers /
includeServers write the exclusion list under `\\xff/conf/`; the data
distributor reacts by draining data off excluded servers.  Everything
here is a plain serializable transaction: the operator surface has no
private channel into the cluster (the point of "configuration as data").
"""

from __future__ import annotations

from typing import Iterable, List

from ..core.error import FdbError
from ..server.system_data import EXCLUDED_END, EXCLUDED_PREFIX, excluded_key


async def _retrying(db, fn):
    t = db.create_transaction()
    t.access_system_keys = True
    while True:
        try:
            r = await fn(t)
            await t.commit()
            return r
        except FdbError as e:
            await t.on_error(e)


async def exclude_servers(db, tags: Iterable[int]) -> None:
    """Mark storage servers (by tag) excluded: the DD drains every shard
    off them; they stop being placement candidates immediately
    (reference excludeServers)."""
    async def go(t):
        for tag in tags:
            t.set(excluded_key(tag), b"1")
    await _retrying(db, go)


async def include_servers(db, tags: Iterable[int] = None) -> None:
    """Re-admit excluded servers (None = everyone; reference
    includeServers)."""
    async def go(t):
        if tags is None:
            t.clear(EXCLUDED_PREFIX, EXCLUDED_END)
        else:
            for tag in tags:
                t.clear(excluded_key(tag))
    await _retrying(db, go)


async def excluded_servers(db) -> List[int]:
    t = db.create_transaction()
    t.access_system_keys = True
    while True:
        try:
            rows = await t.get_range(EXCLUDED_PREFIX, EXCLUDED_END)
            return [int(k[len(EXCLUDED_PREFIX):]) for k, v in rows
                    if v == b"1"]
        except FdbError as e:
            await t.on_error(e)


async def change_configuration(db, **fields) -> None:
    """Change the database configuration transactionally (reference
    `fdbcli configure` -> ManagementAPI changeConfig writing \\xff/conf/
    keys): role counts and engine settings become ordinary committed
    state — they survive exactly what the database survives, and the
    transaction system recovers into the new shape."""
    from ..server.system_data import conf_key

    async def go(t):
        for name, value in fields.items():
            if value is None:
                t.clear(conf_key(name))
            else:
                t.set(conf_key(name), str(value).encode())
    await _retrying(db, go)


async def get_configuration(db) -> dict:
    """The committed \\xff/conf/ overrides (absent fields use static
    defaults)."""
    from ..server.system_data import CONF_END, CONF_PREFIX, EXCLUDED_PREFIX

    async def go(t):
        rows = await t.get_range(CONF_PREFIX, CONF_END)
        return {k[len(CONF_PREFIX):].decode(): v for k, v in rows
                if not k.startswith(EXCLUDED_PREFIX)}
    return await _retrying(db, go)
