"""Multi-version client (reference fdbclient/MultiVersionTransaction.actor.cpp
:596 MultiVersionDatabase + MultiVersionApi).

The reference ships every past client library inside the current one and
connects with whichever speaks the cluster's protocol version, so a
cluster upgrade never requires a lockstep client upgrade: the client
watches the protocol version through the coordinators, swaps the
underlying implementation when it changes, and in-flight transactions
fail with cluster_version_changed (retryable) so retry loops land on the
new implementation transparently.

Here each "client library" is a factory registered against a protocol
version; MultiVersionDatabase monitors ClientDBInfo.protocol_version and
delegates through the matching implementation.  With only one version in
the registry this degrades to a plain client — the machinery (version
watch, implementation swap, transparent transaction failover) is what an
upgrade needs.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ..core.error import err
from ..core.futures import AsyncVar
from ..core.scheduler import spawn
from ..core.trace import Severity, TraceEvent


class MultiVersionDatabase:
    """Database facade selecting the implementation by cluster protocol.

    `impls` maps protocol_version -> factory(cluster) returning an
    internal Database-compatible object; `cluster` is a ClusterConnection
    (its ClientDBInfo carries protocol_version)."""

    def __init__(self, cluster: Any,
                 impls: Dict[int, Callable[[Any], Any]]) -> None:
        if not impls:
            raise err("client_invalid_operation", "no client impls")
        self.cluster = cluster
        self.impls = impls
        self.active_protocol: Optional[int] = None
        self.active_db: Optional[Any] = None
        # Bumped on every swap; transactions created against an older
        # generation raise cluster_version_changed on use.
        self.generation = 0
        self.on_switch = AsyncVar(0)
        self._monitor = spawn(self._protocol_monitor(), "mv.protocolWatch")

    def _select(self, protocol: int) -> None:
        factory = self.impls.get(protocol)
        if factory is None:
            # Reference behavior: an unknown protocol leaves the database
            # unavailable (operations wait) until a matching library is
            # provided — surfaced loudly rather than misdecoding.
            TraceEvent("MultiVersionNoMatchingClient",
                       Severity.Warn).detail("Protocol", protocol).log()
            self.active_db = None
            self.active_protocol = protocol
            return
        self.active_db = factory(self.cluster)
        self.active_protocol = protocol
        self.generation += 1
        self.on_switch.set(self.generation)
        TraceEvent("MultiVersionClientSelected").detail(
            "Protocol", protocol).detail(
            "Generation", self.generation).log()

    async def _protocol_monitor(self) -> None:
        info_var = getattr(self.cluster, "client_info", None)
        while True:
            info = info_var.get() if info_var is not None else None
            protocol = getattr(info, "protocol_version", 0) if info else 0
            if protocol and protocol != self.active_protocol:
                self._select(protocol)
            if info_var is None:
                return
            await info_var.on_change()

    async def wait_ready(self) -> None:
        while self.active_db is None:
            await self.on_switch.on_change()

    def create_transaction(self) -> "MultiVersionTransaction":
        return MultiVersionTransaction(self)

    def close(self) -> None:
        if not self._monitor.is_ready():
            self._monitor.cancel()
        close = getattr(self.cluster, "close", None)
        if close is not None:
            close()


class MultiVersionTransaction:
    """Delegates to a transaction of the active implementation; an
    implementation swap mid-transaction surfaces as the retryable
    cluster_version_changed at the next operation (reference
    MultiVersionTransaction::updateTransaction)."""

    def __init__(self, mvdb: MultiVersionDatabase) -> None:
        self.mvdb = mvdb
        self._bind()

    def _bind(self) -> None:
        self._generation = self.mvdb.generation
        self._tr = (self.mvdb.active_db.create_transaction()
                    if self.mvdb.active_db is not None else None)

    def _check(self):
        if self._tr is None or self._generation != self.mvdb.generation:
            raise err("cluster_version_changed",
                      "client implementation switched")
        return self._tr

    # -- delegated surface ---------------------------------------------------
    async def get(self, key, **kw):
        return await self._check().get(key, **kw)

    async def get_range(self, begin, end, **kw):
        return await self._check().get_range(begin, end, **kw)

    def set(self, key, value):
        self._check().set(key, value)

    def clear(self, key, end=None):
        self._check().clear(key, end)

    def atomic_op(self, op, key, operand):
        self._check().atomic_op(op, key, operand)

    async def watch(self, key):
        return await self._check().watch(key)

    def get_read_version(self):
        return self._check().get_read_version()

    async def commit(self):
        return await self._check().commit()

    @property
    def committed_version(self):
        return self._tr.committed_version if self._tr else -1

    async def on_error(self, e) -> None:
        name = getattr(e, "name", "")
        if name == "cluster_version_changed" or self._tr is None or \
                self._generation != self.mvdb.generation:
            # Rebind onto the (possibly new) implementation and retry.
            await self.mvdb.wait_ready()
            self._bind()
            return
        await self._tr.on_error(e)

    def reset(self) -> None:
        self._bind()
        if self._tr is not None:
            self._tr.reset()
