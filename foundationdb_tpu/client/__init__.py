"""Client library: Database / Transaction (NativeAPI + RYW equivalents).

Reference layer: fdbclient/ (SURVEY.md §2.3)."""

from .database import Database, Transaction  # noqa: F401
