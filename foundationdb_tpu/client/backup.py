"""Backup and restore: range snapshot + mutation-log capture to a file
container, restore into an empty cluster.

Reference: fdbclient/FileBackupAgent.actor.cpp (snapshot + log files into a
BackupContainer; restore replays snapshot then logs) and
fdbserver/BackupWorker.actor.cpp:1033 (a worker pulling mutations from the
log system and writing partitioned log files).  The TPU-native shape:

  * Activation is a TRANSACTION: submit() sets `\\xff/backupStarted`, which
    every commit proxy applies as a metadata side effect — from that commit
    version on, all user mutations additionally ride BACKUP_TAG.
  * A backup worker peeks BACKUP_TAG from the log system, appends
    (version, mutations) records to the container's log file, and pops so
    the TLogs can trim.  One stream in exact batch order: no cross-replica
    dedup problems, and unresolved atomic ops replay correctly.
  * snapshot() reads the whole user keyspace in chunks at ONE read version
    (MVCC gives consistency); restore loads the snapshot then replays log
    records with snapshot_version < version <= end_version.

Container layout on a SimFileSystem: `<name>.meta` (versions),
`<name>.snapshot` (k/v records at snapshot_version), `<name>.log`
((version, mutations) records), all in core/wire.py framing.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..core.error import FdbError, err
from ..core.scheduler import delay
from ..core.trace import TraceEvent
from ..core.wire import Reader, Writer
from ..txn.types import Mutation, MutationType, Version
from ..server.system_data import BACKUP_STARTED_KEY, BACKUP_TAG


class BackupContainer:
    """One named backup in a simulated filesystem directory."""

    def __init__(self, fs, name: str) -> None:
        self.fs = fs
        self.name = name
        self._log_offset = 0

    # -- writing -------------------------------------------------------------
    async def write_meta(self, start: Version, snapshot: Version,
                         end: Version) -> None:
        f = self.fs.open(f"{self.name}.meta")
        await f.truncate(0)
        await f.write(0, Writer().i64(start).i64(snapshot).i64(end).done())
        await f.sync()

    async def read_meta(self) -> Tuple[Version, Version, Version]:
        f = self.fs.open(f"{self.name}.meta", create=False)
        r = Reader(await f.read(0, f.size()))
        return r.i64(), r.i64(), r.i64()

    async def write_snapshot(self, version: Version,
                             kvs: List[Tuple[bytes, bytes]]) -> None:
        w = Writer().i64(version).u32(len(kvs))
        for k, v in kvs:
            w.bytes_(k).bytes_(v)
        f = self.fs.open(f"{self.name}.snapshot")
        await f.truncate(0)
        await f.write(0, w.done())
        await f.sync()

    async def read_snapshot(self) -> Tuple[Version, List]:
        f = self.fs.open(f"{self.name}.snapshot", create=False)
        r = Reader(await f.read(0, f.size()))
        version = r.i64()
        kvs = [(r.bytes_(), r.bytes_()) for _ in range(r.u32())]
        return version, kvs

    async def append_log(self, version: Version,
                         mutations: List[Mutation]) -> None:
        w = Writer().i64(version).u32(len(mutations))
        for m in mutations:
            w.u8(int(m.type)).bytes_(m.param1).bytes_(m.param2)
        blob = w.done()
        f = self.fs.open(f"{self.name}.log")
        await f.write(self._log_offset, Writer().u32(len(blob)).done() + blob)
        self._log_offset += 4 + len(blob)
        await f.sync()

    async def read_log(self) -> List[Tuple[Version, List[Mutation]]]:
        f = self.fs.open(f"{self.name}.log", create=False)
        data = await f.read(0, f.size())
        out = []
        off = 0
        while off + 4 <= len(data):
            (n,) = (int.from_bytes(data[off:off + 4], "little"),)
            if off + 4 + n > len(data):
                break   # torn tail (backup stopped uncleanly)
            r = Reader(data[off + 4:off + 4 + n])
            version = r.i64()
            muts = [Mutation(MutationType(r.u8()), r.bytes_(), r.bytes_())
                    for _ in range(r.u32())]
            out.append((version, muts))
            off += 4 + n
        return out


class FileBackupAgent:
    """Drives one backup of a simulated cluster (reference BackupAgent)."""

    def __init__(self, cluster, db, fs, name: str = "backup") -> None:
        self.cluster = cluster
        self.db = db
        self.container = BackupContainer(fs, name)
        self.start_version: Version = 0
        self.snapshot_version: Version = 0
        self.end_version: Version = 0
        self._worker_f = None
        self._worker_stop = False
        self._frontier: Version = 0   # highest log-system version seen

    async def _set_backup_flag(self, on: bool) -> Version:
        t = self.db.create_transaction()
        t.access_system_keys = True
        while True:
            try:
                t.set(BACKUP_STARTED_KEY, b"1" if on else b"0")
                return await t.commit()
            except FdbError as e:
                await t.on_error(e)

    async def _backup_worker(self) -> None:
        """Pull BACKUP_TAG and append log records (reference
        BackupWorker.actor.cpp:1033 pull loop)."""
        fetch_from = self.start_version + 1
        while True:
            cc = self.cluster.current_cc()
            info = cc.db_info if cc is not None else None
            if info is None or not info.tlogs:
                await delay(0.2)
                continue
            from ..server.commit_proxy import LogSystemClient
            ls = LogSystemClient(info.tlogs, getattr(
                self.cluster.config, "log_replication", 1))
            try:
                reply = await ls.peek_tag(BACKUP_TAG, fetch_from)
            except FdbError:
                await delay(0.2)
                continue
            for version, msgs in reply.messages:
                if version >= fetch_from:
                    await self.container.append_log(version, msgs)
                    self.end_version = max(self.end_version, version)
            self._frontier = max(self._frontier, reply.max_known_version)
            if reply.messages:
                last = reply.messages[-1][0]
                fetch_from = max(fetch_from, last + 1)
                ls.pop(BACKUP_TAG, last)
            elif self._worker_stop:
                return
            else:
                await delay(0.05)

    async def submit(self) -> None:
        """Activate mutation capture, then write a consistent snapshot
        (ongoing writes land in the log stream meanwhile)."""
        self.start_version = await self._set_backup_flag(True)
        self.end_version = self.start_version
        self._worker_f = self.cluster.loop.spawn(
            self._backup_worker(), "backupWorker")
        # Chunked full-range snapshot at one read version.
        t = self.db.create_transaction()
        while True:
            try:
                kvs = []
                cursor = b""
                while True:
                    chunk = await t.get_range(cursor, b"\xff", limit=1000)
                    kvs.extend(chunk)
                    if len(chunk) < 1000:
                        break
                    cursor = chunk[-1][0] + b"\x00"
                self.snapshot_version = (await t.get_read_version()).version
                break
            except FdbError as e:
                await t.on_error(e)
        await self.container.write_snapshot(self.snapshot_version, kvs)
        TraceEvent("BackupSnapshotDone").detail(
            "Keys", len(kvs)).detail("Version", self.snapshot_version).log()

    async def stop(self) -> Version:
        """Deactivate capture and drain the worker; the backup restores to
        any state up to the returned end version."""
        stop_version = await self._set_backup_flag(False)
        # Drain: the worker's view of the log stream must pass the stop
        # commit (end_version only advances on captured mutations; the
        # frontier advances on every peek).
        while self._frontier < stop_version:
            await delay(0.05)
        # A user transaction batched AFTER the flag-off mutation shares
        # commit version stop_version but is not captured; the backup only
        # claims coverage through stop_version - 1.
        self.end_version = max(min(self.end_version, stop_version - 1),
                               self.snapshot_version)
        self._worker_stop = True
        await self._worker_f
        await self.container.write_meta(self.start_version,
                                        self.snapshot_version,
                                        self.end_version)
        TraceEvent("BackupComplete").detail(
            "Start", self.start_version).detail(
            "Snapshot", self.snapshot_version).detail(
            "End", self.end_version).log()
        return self.end_version


async def restore(db, fs, name: str = "backup") -> int:
    """Restore a container into an (empty) cluster: snapshot state, then
    log replay for versions after the snapshot (reference FileBackupAgent
    restore tasks).  Returns the number of restored mutations."""
    container = BackupContainer(fs, name)
    _start, snapshot_version, end_version = await container.read_meta()
    sv, kvs = await container.read_snapshot()
    applied = 0
    # Snapshot in chunked transactions.
    for i in range(0, len(kvs), 500):
        t = db.create_transaction()
        while True:
            try:
                for k, v in kvs[i:i + 500]:
                    t.set(k, v)
                await t.commit()
                applied += min(500, len(kvs) - i)
                break
            except FdbError as e:
                await t.on_error(e)
    # Log replay in version order, preserving intra-version mutation
    # order.  Each record's transaction also writes a progress marker so a
    # commit_unknown_result can be disambiguated instead of re-applying
    # (atomic ops are not idempotent).
    progress_key = b"\xff/restoreProgress/" + name.encode()
    for idx, (version, muts) in enumerate(await container.read_log()):
        if not sv < version <= end_version:
            continue
        marker = b"%020d" % idx
        t = db.create_transaction()
        t.access_system_keys = True
        while True:
            try:
                # Read the marker INSIDE this attempt: it both resolves a
                # prior commit_unknown_result AND adds a read conflict
                # range, so a late-landing earlier attempt forces
                # not_committed here instead of double-applying.
                seen = await t.get(progress_key)
                if seen == marker:
                    applied += len(muts)
                    break
                t.set(progress_key, marker)
                for m in muts:
                    if m.type == MutationType.SetValue:
                        t.set(m.param1, m.param2)
                    elif m.type == MutationType.ClearRange:
                        t.clear(m.param1, m.param2)
                    else:
                        t.atomic_op(m.type, m.param1, m.param2)
                await t.commit()
                applied += len(muts)
                break
            except FdbError as e:
                await t.on_error(e)
    # Drop the marker so the restored keyspace matches the source.
    t = db.create_transaction()
    t.access_system_keys = True
    while True:
        try:
            t.clear(progress_key)
            await t.commit()
            break
        except FdbError as e:
            await t.on_error(e)
    TraceEvent("RestoreComplete").detail("Snapshot", len(kvs)).detail(
        "Mutations", applied).log()
    return applied
