"""Backup and restore: range snapshot + mutation-log capture to a file
container, restore into an empty cluster.

Reference: fdbclient/FileBackupAgent.actor.cpp (snapshot + log files into a
BackupContainer; restore replays snapshot then logs) and
fdbserver/BackupWorker.actor.cpp:1033 (a worker pulling mutations from the
log system and writing partitioned log files).  The TPU-native shape:

  * Activation is a TRANSACTION: submit() sets `\\xff/backupStarted`, which
    every commit proxy applies as a metadata side effect — from that commit
    version on, all user mutations additionally ride BACKUP_TAG.
  * A backup worker peeks BACKUP_TAG from the log system, appends
    (version, mutations) records to the container's log file, and pops so
    the TLogs can trim.  One stream in exact batch order: no cross-replica
    dedup problems, and unresolved atomic ops replay correctly.
  * snapshot() reads the whole user keyspace in chunks at ONE read version
    (MVCC gives consistency); restore loads the snapshot then replays log
    records with snapshot_version < version <= end_version.

Container layout on a SimFileSystem: `<name>.meta` (versions),
`<name>.snapshot` (k/v records at snapshot_version), `<name>.log`
((version, mutations) records), all in core/wire.py framing.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..core.error import FdbError, err
from ..core.scheduler import delay
from ..core.trace import Severity, TraceEvent
from ..core.wire import Reader, Writer
from ..txn.types import Mutation, MutationType, Version
from ..server.system_data import (BACKUP_CONTAINER_KEY, BACKUP_STARTED_KEY,
                                  BACKUP_TAG)


# -- container URLs (reference BackupContainerFileSystem::openContainer:
# file:// and blobstore:// URLs resolve to IBackupContainer impls) ----------
# "sim://name" resolves against a process-global blob store (the sim's
# stand-in for remote object storage — one shared filesystem every role
# and agent can reach); "file:///path" resolves to a real directory.

_sim_blob_store = None


def set_sim_blob_store(fs) -> None:
    global _sim_blob_store
    _sim_blob_store = fs


def open_container(url: str) -> "BackupContainer":
    if url.startswith("sim://"):
        if _sim_blob_store is None:
            raise err("operation_failed", "no sim blob store registered")
        return BackupContainer(_sim_blob_store, url[len("sim://"):])
    if url.startswith("file://"):
        from ..server.real_fs import RealFileSystem
        path, _, name = url[len("file://"):].rpartition("/")
        return BackupContainer(RealFileSystem(path or "."), name)
    raise err("operation_failed", f"unknown container url {url!r}")


class BackupContainer:
    """One named backup in a (simulated or real) filesystem directory."""

    def __init__(self, fs, name: str) -> None:
        self.fs = fs
        self.name = name
        self._log_offset = 0

    # -- writing -------------------------------------------------------------
    async def write_meta(self, start: Version, snapshot: Version,
                         end: Version) -> None:
        f = self.fs.open(f"{self.name}.meta")
        await f.truncate(0)
        await f.write(0, Writer().i64(start).i64(snapshot).i64(end).done())
        await f.sync()

    async def read_meta(self) -> Tuple[Version, Version, Version]:
        f = self.fs.open(f"{self.name}.meta", create=False)
        r = Reader(await f.read(0, f.size()))
        return r.i64(), r.i64(), r.i64()

    async def write_snapshot(self, version: Version,
                             kvs: List[Tuple[bytes, bytes]]) -> None:
        w = Writer().i64(version).u32(len(kvs))
        for k, v in kvs:
            w.bytes_(k).bytes_(v)
        f = self.fs.open(f"{self.name}.snapshot")
        await f.truncate(0)
        await f.write(0, w.done())
        await f.sync()

    # Partitioned snapshot (reference RangeFile kvranges/): one part per
    # TaskBucket chunk task + a completion marker naming the part count.
    async def write_snapshot_part(self, part: int, version: Version,
                                  kvs: List[Tuple[bytes, bytes]]) -> None:
        w = Writer().i64(version).u32(len(kvs))
        for k, v in kvs:
            w.bytes_(k).bytes_(v)
        f = self.fs.open(f"{self.name}.snap.part{part}")
        await f.truncate(0)
        await f.write(0, w.done())
        await f.sync()

    async def write_snapshot_complete(self, n_parts: int,
                                      version: Version) -> None:
        f = self.fs.open(f"{self.name}.snap.done")
        await f.write(0, Writer().u32(n_parts).i64(version).done())
        await f.sync()

    async def snapshot_complete(self) -> bool:
        try:
            f = self.fs.open(f"{self.name}.snap.done", create=False)
            return f.size() >= 12
        except FdbError:
            return False

    async def snapshot_version(self) -> Version:
        """The version the completed snapshot was read at (0 if none) —
        the single parser of snap.done's u32(parts)+i64(version) header."""
        try:
            f = self.fs.open(f"{self.name}.snap.done", create=False)
            r = Reader(await f.read(0, 12))
            r.u32()
            return r.i64()
        except FdbError:
            return 0

    async def snapshot_parts(self) -> int:
        try:
            f = self.fs.open(f"{self.name}.snap.done", create=False)
            return Reader(await f.read(0, 4)).u32()
        except FdbError:
            return 0

    async def read_snapshot(self) -> Tuple[Version, List]:
        try:
            f = self.fs.open(f"{self.name}.snap.done", create=False)
            r = Reader(await f.read(0, f.size()))
            n_parts, version = r.u32(), r.i64()
            kvs: List[Tuple[bytes, bytes]] = []
            for part in range(n_parts):
                pf = self.fs.open(f"{self.name}.snap.part{part}",
                                  create=False)
                pr = Reader(await pf.read(0, pf.size()))
                pr.i64()
                kvs.extend((pr.bytes_(), pr.bytes_())
                           for _ in range(pr.u32()))
            return version, kvs
        except FdbError:
            pass
        # Legacy single-file snapshot layout.
        f = self.fs.open(f"{self.name}.snapshot", create=False)
        r = Reader(await f.read(0, f.size()))
        version = r.i64()
        kvs = [(r.bytes_(), r.bytes_()) for _ in range(r.u32())]
        return version, kvs

    async def append_log(self, version: Version,
                         mutations: List[Mutation]) -> None:
        w = Writer().i64(version).u32(len(mutations))
        for m in mutations:
            w.u8(int(m.type)).bytes_(m.param1).bytes_(m.param2)
        blob = w.done()
        f = self.fs.open(f"{self.name}.log")
        await f.write(self._log_offset, Writer().u32(len(blob)).done() + blob)
        self._log_offset += 4 + len(blob)
        await f.sync()

    async def log_tail(self) -> Tuple[int, Version]:
        """(byte_offset, last_version) of the intact log prefix — where a
        backup worker recruited after a recovery resumes appending.  One
        frame scan, no file creation for a fresh container."""
        try:
            f = self.fs.open(f"{self.name}.log", create=False)
        except FdbError:
            self._log_offset = 0
            return 0, 0
        data = await f.read(0, f.size())
        off = 0
        last_v: Version = 0
        while off + 4 <= len(data):
            n = int.from_bytes(data[off:off + 4], "little")
            if off + 4 + n > len(data):
                break          # torn tail (unclean stop): overwritten next
            last_v = Reader(data[off + 4:off + 12]).i64()
            off += 4 + n
        self._log_offset = off
        return off, last_v

    async def write_frontier(self, version: Version) -> None:
        """Durable capture frontier: versions <= this are fully captured
        (even when they carried no user mutations) — what stop-drain and
        restorability checks poll."""
        f = self.fs.open(f"{self.name}.frontier")
        await f.write(0, Writer().i64(version).done())
        await f.sync()

    async def read_frontier(self) -> Version:
        try:
            f = self.fs.open(f"{self.name}.frontier", create=False)
            return Reader(await f.read(0, 8)).i64()
        except FdbError:
            return 0

    async def read_log(self) -> List[Tuple[Version, List[Mutation]]]:
        try:
            f = self.fs.open(f"{self.name}.log", create=False)
        except FdbError:
            return []   # no user mutation was ever captured
        data = await f.read(0, f.size())
        out = []
        off = 0
        while off + 4 <= len(data):
            (n,) = (int.from_bytes(data[off:off + 4], "little"),)
            if off + 4 + n > len(data):
                break   # torn tail (backup stopped uncleanly)
            r = Reader(data[off + 4:off + 4 + n])
            version = r.i64()
            muts = [Mutation(MutationType(r.u8()), r.bytes_(), r.bytes_())
                    for _ in range(r.u32())]
            out.append((version, muts))
            off += 4 + n
        return out


SNAPSHOT_CHUNK = 500


async def _snapshot_chunk_task(db, bucket, task) -> None:
    """One TaskBucket snapshot task (reference FileBackupAgent's
    RangeFile tasks): read a chunk at the FIXED snapshot version, write
    it as a snapshot part, then — in the SAME transaction that finishes
    this task — either chain the next chunk's task or mark the snapshot
    complete.  Any agent can execute/resume any chunk."""
    url = task.params[b"url"].decode()
    cursor = task.params[b"cursor"]
    snap_v = int(task.params[b"snap_v"])
    part = int(task.params[b"part"])
    container = open_container(url)
    # The data read is a THROWAWAY snapshot transaction at the fixed
    # version — never committed, so its full-range read takes no conflict
    # ranges (a committed read at an old version would abort against
    # every concurrent write, forever).  The part file is idempotent
    # (same version -> same content), so re-execution after a reclaim is
    # safe; only the chain/finish transaction below commits.
    tr = db.create_transaction()
    while True:
        try:
            tr.set_read_version(snap_v)
            chunk = await tr.get_range(cursor, b"\xff",
                                       limit=SNAPSHOT_CHUNK)
            break
        except FdbError as e:
            await tr.on_error(e)
            tr = db.create_transaction()
    await container.write_snapshot_part(part, snap_v, chunk)
    done = len(chunk) < SNAPSHOT_CHUNK
    if done:
        await container.write_snapshot_complete(part + 1, snap_v)
    t = db.create_transaction()
    while True:
        try:
            if not done:
                bucket.add(t, "backup_snapshot_chunk", {
                    b"url": url.encode(),
                    b"cursor": chunk[-1][0] + b"\x00",
                    b"snap_v": b"%d" % snap_v,
                    b"part": b"%d" % (part + 1)})
            await bucket.finish(t, task)
            await t.commit()
            if done:
                TraceEvent("BackupSnapshotDone").detail(
                    "Parts", part + 1).detail("Version", snap_v).log()
            return
        except FdbError as e:
            await t.on_error(e)


BACKUP_TASK_HANDLERS = {"backup_snapshot_chunk": _snapshot_chunk_task}


class FileBackupAgent:
    """Drives one backup (reference FileBackupAgent + backup_agent):
    activation commits the container URL + capture flag (the recruited
    backup worker ROLE appends the log stream, server/backup_worker.py);
    the snapshot is a TaskBucket task chain any agent can resume."""

    def __init__(self, cluster, db, fs=None, name: str = "backup",
                 url: Optional[str] = None) -> None:
        from .taskbucket import TaskBucket
        self.cluster = cluster
        self.db = db
        if url is not None:
            # Real deployments pass a container URL (file://...); the
            # committed BACKUP_CONTAINER_KEY must be resolvable by the
            # server-side backup worker in ITS process, so sim:// only
            # works when every role shares this interpreter.
            self.url = url
            self.container = open_container(url)
        else:
            if fs is None:
                raise err("client_invalid_operation",
                          "FileBackupAgent needs either fs= or url=")
            # The fs acts as this test universe's shared blob store.
            set_sim_blob_store(fs)
            self.url = f"sim://{name}"
            self.container = BackupContainer(fs, name)
        self.bucket = TaskBucket(prefix=b"\xff/taskBucket/backup/")
        self.start_version: Version = 0
        self.snapshot_version: Version = 0
        self.end_version: Version = 0
        self._agent_f = None

    async def _set_backup_flag(self, on: bool) -> Version:
        t = self.db.create_transaction()
        t.access_system_keys = True
        while True:
            try:
                if on:
                    # Container URL FIRST: proxies apply mutations in
                    # order, and the flag's master nudge carries the url.
                    t.set(BACKUP_CONTAINER_KEY, self.url.encode())
                t.set(BACKUP_STARTED_KEY, b"1" if on else b"0")
                return await t.commit()
            except FdbError as e:
                await t.on_error(e)

    def run_agent(self, agent_id: str = "agent0"):
        """Start a task-executing agent loop (any number may run; each
        claims snapshot chunks from the shared bucket)."""
        from .taskbucket import run_tasks
        return self.cluster.loop.spawn(
            run_tasks(self.db, self.bucket, BACKUP_TASK_HANDLERS,
                      agent_id=agent_id),
            f"backupAgent.{agent_id}")

    async def submit(self) -> None:
        """Activate capture (worker role recruited via the proxies' master
        nudge) and enqueue the snapshot task chain."""
        self.start_version = await self._set_backup_flag(True)
        self.end_version = self.start_version
        t = self.db.create_transaction()
        while True:
            try:
                self.snapshot_version = (await t.get_read_version()).version
                break
            except FdbError as e:
                await t.on_error(e)
        await self.bucket.add_task(self.db, "backup_snapshot_chunk", {
            b"url": self.url.encode(), b"cursor": b"",
            b"snap_v": b"%d" % self.snapshot_version, b"part": b"0"})
        if self._agent_f is None:
            self._agent_f = self.run_agent()
        # Wait for the chunk chain to finish (the bucket drains).
        while not await self.container.snapshot_complete():
            await delay(0.1)

    async def stop(self) -> Version:
        """Deactivate capture and wait for the worker role's durable
        frontier to pass the stop commit; the backup restores to any
        state up to the returned end version."""
        stop_version = await self._set_backup_flag(False)
        stalls = 0
        while await self.container.read_frontier() < stop_version:
            await delay(0.1)
            stalls += 1
            if stalls % 50 == 0:
                # Self-heal a LOST recruitment (the proxy nudge is one-way
                # and master-side recruitment best-effort): re-touch the
                # container key so the metadata applier re-nudges and a
                # missing worker gets recruited instead of this drain
                # waiting forever.
                t = self.db.create_transaction()
                t.access_system_keys = True
                try:
                    t.set(BACKUP_CONTAINER_KEY, self.url.encode())
                    t.set(BACKUP_STARTED_KEY, b"0")
                    await t.commit()
                except FdbError:
                    pass
                TraceEvent("BackupStopDrainStalled").detail(
                    "Frontier", await self.container.read_frontier()).detail(
                    "StopVersion", stop_version).log()
        # The snapshot chunk chain may still be in flight (a discontinue
        # racing submit, or a fresh CLI process stopping someone else's
        # backup): sealing meta now would record snapshot=0 and restore
        # would double-apply the pre-snapshot log range.  Run an agent to
        # finish the chain — TaskBucket reclaim means abandoned chunks
        # get picked up too — and only then seal.
        if not await self.container.snapshot_complete():
            if self._agent_f is None:
                self._agent_f = self.run_agent("stopAgent")
            while not await self.container.snapshot_complete():
                if await self.bucket.is_empty(self.db):
                    # No chain to finish (submit never ran against this
                    # container): seal what exists rather than spin.
                    TraceEvent("BackupStopNoSnapshot",
                               Severity.Warn).detail(
                        "Url", self.url).log()
                    break
                await delay(0.1)
        # A fresh process has no in-object history; the container itself
        # records the snapshot version.
        if not self.snapshot_version:
            self.snapshot_version = await self.container.snapshot_version()
        records = await self.container.read_log()
        last_logged = records[-1][0] if records else self.snapshot_version
        # A user transaction batched AFTER the flag-off mutation shares
        # commit version stop_version but is not captured; the backup only
        # claims coverage through stop_version - 1.
        self.end_version = max(min(last_logged, stop_version - 1),
                               self.snapshot_version)
        await self.container.write_meta(self.start_version,
                                        self.snapshot_version,
                                        self.end_version)
        if self._agent_f is not None and not self._agent_f.is_ready():
            self._agent_f.cancel()
        TraceEvent("BackupComplete").detail(
            "Start", self.start_version).detail(
            "Snapshot", self.snapshot_version).detail(
            "End", self.end_version).log()
        return self.end_version


RESTORE_RANGES = 4


async def _restore_snapshot_task(db, bucket, task) -> None:
    """Fast-restore loader/applier for one snapshot PART (reference
    fdbserver/RestoreLoader + RestoreApplier roles): parts are disjoint
    key sets, so any number of agents apply them concurrently."""
    url = task.params[b"url"].decode()
    part = int(task.params[b"part"])
    container = open_container(url)
    pf = container.fs.open(f"{container.name}.snap.part{part}",
                           create=False)
    r = Reader(await pf.read(0, pf.size()))
    r.i64()
    kvs = [(r.bytes_(), r.bytes_()) for _ in range(r.u32())]
    for i in range(0, max(len(kvs), 1), 500):
        t = db.create_transaction()
        last = i + 500 >= len(kvs)
        while True:
            try:
                # Ownership guard: a reclaimed task's zombie must not
                # re-commit stale snapshot values over phase-2 replay.
                await bucket.check_owned(t, task)
                for k, v in kvs[i:i + 500]:
                    t.set(k, v)
                if last:
                    await bucket.finish(t, task)
                await t.commit()
                break
            except FdbError as e:
                await t.on_error(e)


async def _restore_logrange_task(db, bucket, task) -> None:
    """Fast-restore applier for one KEY RANGE of the log stream: each
    range's mutations are applied in version order, and disjoint ranges
    commute — so ranges parallelize across agents exactly like the
    reference's per-applier key partitions.  Progress markers make each
    version-batch exactly-once under retries."""
    url = task.params[b"url"].decode()
    begin = task.params[b"begin"]
    end = task.params[b"end"]
    snap_v = int(task.params[b"snap_v"])
    end_v = int(task.params[b"end_v"])
    container = open_container(url)
    progress_key = (b"\xff/restoreProgress/" + container.name.encode() +
                    b"/" + begin)

    def clip(m):
        if m.type == MutationType.ClearRange:
            b = max(m.param1, begin)
            e = min(m.param2, end)
            if b >= e:
                return None
            return Mutation(MutationType.ClearRange, b, e)
        if begin <= m.param1 < end:
            return m
        return None

    for idx, (version, muts) in enumerate(await container.read_log()):
        if not snap_v < version <= end_v:
            continue
        clipped = [c for c in (clip(m) for m in muts) if c is not None]
        if not clipped:
            continue
        marker = b"%020d" % idx
        t = db.create_transaction()
        t.access_system_keys = True
        while True:
            try:
                # Ownership guard per batch: without it a zombie whose
                # task was reclaimed (and whose progress marker the
                # reclaimer's finish cleared) would re-apply atomic ops
                # a second time.
                await bucket.check_owned(t, task)
                seen = await t.get(progress_key)
                if seen is not None and seen >= marker:
                    break
                t.set(progress_key, marker)
                for m in clipped:
                    if m.type == MutationType.SetValue:
                        t.set(m.param1, m.param2)
                    elif m.type == MutationType.ClearRange:
                        t.clear(m.param1, m.param2)
                    else:
                        t.atomic_op(m.type, m.param1, m.param2)
                await t.commit()
                break
            except FdbError as e:
                await t.on_error(e)
        if idx % 8 == 0:
            # Heartbeat so a long replay outlives the claim timeout
            # instead of churning through reclaims.
            if not await bucket.extend(db, task):
                raise err("operation_failed", "task reclaimed")
    t = db.create_transaction()
    t.access_system_keys = True
    while True:
        try:
            t.clear(progress_key)
            await bucket.finish(t, task)
            await t.commit()
            return
        except FdbError as e:
            await t.on_error(e)


RESTORE_TASK_HANDLERS = {
    "restore_snapshot_part": _restore_snapshot_task,
    "restore_log_range": _restore_logrange_task,
}


async def restore_distributed(cluster, db, fs, name: str = "backup",
                              n_agents: int = 3) -> None:
    """Fast restore (reference fdbserver/RestoreLoader/RestoreApplier/
    RestoreController roles): the restore is decomposed into TaskBucket
    tasks — one per snapshot part, one per log KEY RANGE — executed by a
    fleet of agents; any agent may die and another resumes its task.
    Phases are sequenced by the controller here: snapshot parts must all
    land before log ranges replay on top."""
    from .taskbucket import TaskBucket, run_tasks
    set_sim_blob_store(fs)
    url = f"sim://{name}"
    container = BackupContainer(fs, name)
    _start, snap_v, end_v = await container.read_meta()
    bucket = TaskBucket(prefix=b"\xff/taskBucket/restore/")

    # Phase 1: snapshot parts in parallel.
    n_parts = await container.snapshot_parts()
    for part in range(n_parts):
        await bucket.add_task(db, "restore_snapshot_part", {
            b"url": url.encode(), b"part": b"%d" % part})
    stop = {"flag": False}
    agents = [cluster.loop.spawn(
        run_tasks(db, bucket, RESTORE_TASK_HANDLERS,
                  agent_id=f"restore{i}", stop=lambda: stop["flag"]),
        f"restoreAgent{i}") for i in range(n_agents)]
    while not await bucket.is_empty(db):
        await delay(0.1)

    # Phase 2: log replay, partitioned by key range.
    bounds = [b""] + [bytes([(256 * i) // RESTORE_RANGES])
                      for i in range(1, RESTORE_RANGES)] + [b"\xff"]
    for i in range(RESTORE_RANGES):
        await bucket.add_task(db, "restore_log_range", {
            b"url": url.encode(), b"begin": bounds[i],
            b"end": bounds[i + 1], b"snap_v": b"%d" % snap_v,
            b"end_v": b"%d" % end_v})
    while not await bucket.is_empty(db):
        await delay(0.1)
    stop["flag"] = True
    for a in agents:
        if not a.is_ready():
            a.cancel()
    TraceEvent("FastRestoreComplete").detail("Parts", n_parts).detail(
        "Ranges", RESTORE_RANGES).log()


async def restore(db, fs, name: str = "backup", prefix: bytes = b"") -> int:
    """Restore a container into an (empty) cluster: snapshot state, then
    log replay for versions after the snapshot (reference FileBackupAgent
    restore tasks).  Returns the number of restored mutations.

    With `prefix` the whole restored keyspace is SHIFTED under it
    (reference fdbrestore -k/--add-prefix): key k lands at prefix+k,
    clear ranges shift both bounds.  A live cluster can then host the
    restored image next to its current data — how BackupAndRestore
    chaos runs consistency-check restored-vs-live without a second
    cluster."""
    container = BackupContainer(fs, name)
    _start, snapshot_version, end_version = await container.read_meta()
    sv, kvs = await container.read_snapshot()
    applied = 0
    # Snapshot in chunked transactions.
    for i in range(0, len(kvs), 500):
        t = db.create_transaction()
        while True:
            try:
                for k, v in kvs[i:i + 500]:
                    t.set(prefix + k, v)
                await t.commit()
                applied += min(500, len(kvs) - i)
                break
            except FdbError as e:
                await t.on_error(e)
    # Log replay in version order, preserving intra-version mutation
    # order.  Each record's transaction also writes a progress marker so a
    # commit_unknown_result can be disambiguated instead of re-applying
    # (atomic ops are not idempotent).  Prefix-shifted restores use a
    # DISTINCT marker key: a same-container unshifted restore must not
    # share progress with a shifted one.
    progress_key = (b"\xff/restoreProgress/" + name.encode() +
                    (b"/" + prefix if prefix else b""))
    for idx, (version, muts) in enumerate(await container.read_log()):
        if not sv < version <= end_version:
            continue
        marker = b"%020d" % idx
        t = db.create_transaction()
        t.access_system_keys = True
        while True:
            try:
                # Read the marker INSIDE this attempt: it both resolves a
                # prior commit_unknown_result AND adds a read conflict
                # range, so a late-landing earlier attempt forces
                # not_committed here instead of double-applying.
                seen = await t.get(progress_key)
                if seen == marker:
                    applied += len(muts)
                    break
                t.set(progress_key, marker)
                for m in muts:
                    if m.type == MutationType.SetValue:
                        t.set(prefix + m.param1, m.param2)
                    elif m.type == MutationType.ClearRange:
                        t.clear(prefix + m.param1, prefix + m.param2)
                    else:
                        t.atomic_op(m.type, prefix + m.param1, m.param2)
                await t.commit()
                applied += len(muts)
                break
            except FdbError as e:
                await t.on_error(e)
    # Drop the marker so the restored keyspace matches the source.
    t = db.create_transaction()
    t.access_system_keys = True
    while True:
        try:
            t.clear(progress_key)
            await t.commit()
            break
        except FdbError as e:
            await t.on_error(e)
    TraceEvent("RestoreComplete").detail("Snapshot", len(kvs)).detail(
        "Mutations", applied).log()
    return applied
