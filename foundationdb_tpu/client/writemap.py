"""WriteMap: a transaction's uncommitted writes, merged into its reads.

Reference: fdbclient/WriteMap.h + RYWIterator.cpp — the read-your-writes
cache.  Every mutation the transaction issues is kept in issue order; reads
replay the per-key suffix of operations on top of the snapshot value.  A
ClearRange acts as a barrier: operations after it apply on top of None.
"""

from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Tuple

from ..txn.atomic import apply_atomic
from ..txn.types import ATOMIC_OPS, Mutation, MutationType


class WriteMap:
    def __init__(self) -> None:
        # The ordered mutation log (what commit sends).
        self.mutations: List[Mutation] = []
        # key -> [(seq, type, param2)] point ops in issue order.
        self._key_ops: Dict[bytes, List[Tuple[int, MutationType, bytes]]] = {}
        # [(seq, begin, end)] clear ranges in issue order.
        self._clears: List[Tuple[int, bytes, bytes]] = []

    def __len__(self) -> int:
        return len(self.mutations)

    # -- recording -----------------------------------------------------------
    def set(self, key: bytes, value: bytes) -> None:
        self._add(Mutation(MutationType.SetValue, key, value))

    def clear(self, begin: bytes, end: bytes) -> None:
        self._add(Mutation(MutationType.ClearRange, begin, end))

    def atomic_op(self, op: MutationType, key: bytes, operand: bytes) -> None:
        assert op in ATOMIC_OPS, op
        self._add(Mutation(op, key, operand))

    def _add(self, m: Mutation) -> None:
        seq = len(self.mutations)
        self.mutations.append(m)
        if m.type == MutationType.ClearRange:
            self._clears.append((seq, m.param1, m.param2))
        else:
            self._key_ops.setdefault(m.param1, []).append(
                (seq, m.type, m.param2))

    # -- read merging --------------------------------------------------------
    def _last_clear_seq(self, key: bytes) -> int:
        last = -1
        for seq, b, e in self._clears:
            if b <= key < e:
                last = seq
        return last

    def has_writes(self, key: bytes) -> bool:
        return key in self._key_ops or self._last_clear_seq(key) >= 0

    def needs_base(self, key: bytes) -> bool:
        """True if merging this key's ops requires the snapshot value (an
        atomic-op chain with no Set/Clear barrier below it)."""
        clear_seq = self._last_clear_seq(key)
        ops = [o for o in self._key_ops.get(key, []) if o[0] > clear_seq]
        if clear_seq >= 0 and not ops:
            return False
        if not ops:
            return True       # no writes at all: value IS the base
        return ops[0][1] != MutationType.SetValue and clear_seq < 0

    def merge(self, key: bytes, base: Optional[bytes]) -> Optional[bytes]:
        """Value as seen by this transaction, given snapshot value `base`."""
        from ..core.error import err
        clear_seq = self._last_clear_seq(key)
        val = None if clear_seq >= 0 else base
        for seq, typ, param2 in self._key_ops.get(key, []):
            if seq <= clear_seq:
                continue
            if typ == MutationType.SetValue:
                val = param2
            elif typ in (MutationType.SetVersionstampedKey,
                         MutationType.SetVersionstampedValue):
                # The final key/value is unknown until commit (reference
                # RYW raises accessed_unreadable for these).
                raise err("accessed_unreadable")
            else:
                val = apply_atomic(typ, val, param2)
        return val

    def touched_keys_in(self, begin: bytes, end: bytes) -> List[bytes]:
        """All point-written keys within [begin, end)."""
        return sorted(k for k in self._key_ops if begin <= k < end)

    def clears_in(self, begin: bytes, end: bytes
                  ) -> List[Tuple[int, bytes, bytes]]:
        return [(s, max(b, begin), min(e, end))
                for s, b, e in self._clears if b < end and begin < e]

    def is_unreadable(self, key: bytes) -> bool:
        """True when this txn's ops make `key` unreadable (a versionstamped
        op whose result is unknown until commit) — checked before any
        storage round-trip."""
        clear_seq = self._last_clear_seq(key)
        return any(typ in (MutationType.SetVersionstampedKey,
                           MutationType.SetVersionstampedValue)
                   for seq, typ, _p in self._key_ops.get(key, [])
                   if seq > clear_seq)

    def write_conflict_ranges(self) -> List[Tuple[bytes, bytes]]:
        """Minimal covering ranges of all mutations (point -> [k, k+\\0)).

        A SetVersionstampedKey's final key is unknown until commit; its
        conflict range covers EVERY possible stamp in the 10-byte slot
        (reference getVersionstampKeyRange) — guarding the placeholder
        template instead would let a concurrent reader of the formed key
        commit without conflicting."""
        from ..txn.types import key_after
        out = []
        for m in self.mutations:
            if m.type == MutationType.ClearRange:
                if m.param1 < m.param2:
                    out.append((m.param1, m.param2))
            elif m.type == MutationType.SetVersionstampedKey:
                body = m.param1[:-4]
                off = int.from_bytes(m.param1[-4:], "little")
                lo = body[:off] + b"\x00" * 10 + body[off + 10:]
                hi = body[:off] + b"\xff" * 10 + body[off + 10:]
                out.append((lo, key_after(hi)))
            else:
                out.append((m.param1, key_after(m.param1)))
        return out
