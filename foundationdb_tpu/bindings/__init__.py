"""External binding surface (reference bindings/).

The reference's point of being a database is a STABLE external API:
bindings/c/fdb_c.cpp wraps the native client in a frozen C ABI and every
language binding (python/java/go/...) is a veneer over it, validated by
the cross-implementation stack-machine bindingtester
(bindings/bindingtester/spec/bindingApiTester.md).

This package is the analog for the TPU-native stack:

  fdb_api       the frozen `fdb`-style Python API (open/Database/
                Transaction surface mirroring the reference python
                binding's shapes, decoupled from internal client churn)
  tuple         the FDB tuple layer: order-preserving packing of typed
                tuples into keys (reference design/tuple.md encoding)
  stack_tester  the stack-machine tester: replays an op stream through
                the frozen API and diffs results against a direct
                in-process client run (tests/test_bindings.py)

The native C ABI half lives in conflict/native_src/conflict.cpp (cs_new/
cs_resolve/...): the hot engine is callable from any C FFI today; a full
client C ABI would wrap a network protocol and is tracked as a gap.
"""

from . import fdb_api as fdb  # noqa: F401
from . import tuple as fdb_tuple  # noqa: F401
