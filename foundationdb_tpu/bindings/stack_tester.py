"""Stack-machine binding tester (reference bindings/bindingtester/spec/
bindingApiTester.md + tests/api/ApiTester).

The reference validates every language binding by driving it with a
stream of stack-machine ops and diffing the resulting stack + database
against another binding's run of the same stream.  Here the two
"implementations" are (a) the frozen fdb_api surface and (b) direct
internal-client calls — the tester proves the veneer is semantically
transparent, so internal refactors that change behavior under the frozen
API fail tests/test_bindings.py instead of shipping.

Supported ops (a representative subset of the spec):
  PUSH v | DUP | SWAP | POP | SUB | CONCAT | EMPTY_STACK
  SET | GET | CLEAR | CLEAR_RANGE | GET_RANGE | ATOMIC_ADD | ATOMIC_MAX
  COMMIT | RESET | NEW_TRANSACTION | GET_READ_VERSION
  TUPLE_PACK n | TUPLE_UNPACK | TUPLE_RANGE n
Operands come from the stack (last pushed = first popped), mirroring the
spec's conventions; errors are pushed as (b"ERROR", code) so both
executors must fail identically too.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from . import tuple as fdb_tuple


class StackMachine:
    """Executes one op stream against an executor (below)."""

    def __init__(self, executor) -> None:
        self.ex = executor
        self.stack: List[Any] = []

    def _pop(self, n: int = 1):
        out = [self.stack.pop() for _ in range(n)]
        return out[0] if n == 1 else out

    async def run(self, ops: List[Tuple]) -> List[Any]:
        for op in ops:
            name, args = op[0], op[1:]
            await self._step(name, args)
        return self.stack

    async def _step(self, name: str, args) -> None:
        s = self.stack
        if name == "PUSH":
            s.append(args[0])
        elif name == "DUP":
            s.append(s[-1])
        elif name == "SWAP":
            i = args[0]
            s[-1], s[-1 - i] = s[-1 - i], s[-1]
        elif name == "POP":
            self._pop()
        elif name == "SUB":
            a, b = self._pop(2)
            s.append(a - b)
        elif name == "CONCAT":
            a, b = self._pop(2)
            s.append(a + b)
        elif name == "EMPTY_STACK":
            s.clear()
        elif name == "TUPLE_PACK":
            n = args[0]
            items = tuple(reversed(self._pop(n) if n > 1
                                   else [self._pop()]))
            s.append(fdb_tuple.pack(items))
        elif name == "TUPLE_UNPACK":
            packed = self._pop()
            for item in fdb_tuple.unpack(packed):
                s.append(fdb_tuple.pack((item,)))
        elif name == "TUPLE_RANGE":
            n = args[0]
            items = tuple(reversed(self._pop(n) if n > 1
                                   else [self._pop()]))
            b, e = fdb_tuple.range_of(items)
            s.append(b)
            s.append(e)
        else:
            await self._db_step(name)

    async def _db_step(self, name: str) -> None:
        s = self.stack
        try:
            if name == "NEW_TRANSACTION":
                self.ex.new_transaction()
            elif name == "SET":
                v, k = self._pop(2)
                self.ex.set(k, v)
            elif name == "GET":
                k = self._pop()
                r = await self.ex.get(k)
                s.append(b"RESULT_NOT_PRESENT" if r is None else r)
            elif name == "CLEAR":
                self.ex.clear(self._pop())
            elif name == "CLEAR_RANGE":
                e, b = self._pop(2)
                self.ex.clear_range(b, e)
            elif name == "GET_RANGE":
                limit, e, b = self._pop(3)
                rows = await self.ex.get_range(b, e, limit)
                out = []
                for k, v in rows:
                    out.append(k)
                    out.append(v)
                s.append(fdb_tuple.pack(tuple(out)))
            elif name == "ATOMIC_ADD":
                v, k = self._pop(2)
                self.ex.atomic_add(k, v)
            elif name == "ATOMIC_MAX":
                v, k = self._pop(2)
                self.ex.atomic_max(k, v)
            elif name == "GET_READ_VERSION":
                await self.ex.get_read_version()
                s.append(b"GOT_READ_VERSION")
            elif name == "COMMIT":
                await self.ex.commit()
                s.append(b"COMMITTED")
                self.ex.new_transaction()
            elif name == "RESET":
                self.ex.reset()
            else:
                raise ValueError(f"unknown op {name}")
        except Exception as e:  # noqa: BLE001 — errors are data here
            code = getattr(e, "code", None)
            if code is None:
                raise
            retried = await self.ex.on_error(e)
            s.append((b"ERROR", int(code), retried))


class FrozenApiExecutor:
    """Runs db ops through the frozen fdb_api surface."""

    def __init__(self, fdb_db) -> None:
        self.db = fdb_db
        self.tr = None
        self.new_transaction()

    def new_transaction(self) -> None:
        self.tr = self.db.create_transaction()

    def set(self, k, v):
        self.tr.set(k, v)

    def clear(self, k):
        self.tr.clear(k)

    def clear_range(self, b, e):
        self.tr.clear_range(b, e)

    async def get(self, k):
        return await self.tr.get(k)

    async def get_range(self, b, e, limit):
        return await self.tr.get_range(b, e, limit=limit)

    def atomic_add(self, k, v):
        self.tr.add(k, v)

    def atomic_max(self, k, v):
        self.tr.max(k, v)

    async def get_read_version(self):
        return await self.tr.get_read_version()

    async def commit(self):
        await self.tr.commit()

    def reset(self):
        self.tr.reset()

    async def on_error(self, e) -> bool:
        """Returns True if the error was retryable (transaction reset for
        retry) — part of the compared surface."""
        try:
            await self.tr.on_error(e)
            return True
        except Exception:  # noqa: BLE001
            self.new_transaction()
            return False


class DirectClientExecutor:
    """The same ops as raw internal-client calls (the comparison side)."""

    def __init__(self, db) -> None:
        self.db = db
        self.tr = None
        self.new_transaction()

    def new_transaction(self) -> None:
        self.tr = self.db.create_transaction()

    def set(self, k, v):
        self.tr.set(bytes(k), bytes(v))

    def clear(self, k):
        self.tr.clear(bytes(k))

    def clear_range(self, b, e):
        self.tr.clear(bytes(b), bytes(e))

    async def get(self, k):
        return await self.tr.get(bytes(k))

    async def get_range(self, b, e, limit):
        return await self.tr.get_range(bytes(b), bytes(e),
                                       limit=limit or 1_000_000)

    def atomic_add(self, k, v):
        from ..txn.types import MutationType
        self.tr.atomic_op(MutationType.AddValue, bytes(k), bytes(v))

    def atomic_max(self, k, v):
        from ..txn.types import MutationType
        self.tr.atomic_op(MutationType.Max, bytes(k), bytes(v))

    async def get_read_version(self):
        return await self.tr.get_read_version()

    async def commit(self):
        await self.tr.commit()

    def reset(self):
        self.tr.reset()

    async def on_error(self, e) -> bool:
        try:
            await self.tr.on_error(e)
            return True
        except Exception:  # noqa: BLE001
            self.new_transaction()
            return False


def generate_ops(rng, n_ops: int, keyspace: int = 40) -> List[Tuple]:
    """A random-but-valid op stream (the generator keeps a model stack
    depth so pops never underflow)."""
    ops: List[Tuple] = [("NEW_TRANSACTION",)]
    depth = 0
    for _ in range(n_ops):
        choices = ["PUSH", "SET", "GET", "CLEAR", "ATOMIC", "COMMIT",
                   "GET_RANGE", "CLEAR_RANGE", "READ_VERSION"]
        if depth >= 1:
            choices += ["DUP", "POP"]
        if depth >= 2:
            choices += ["CONCAT_B"]
        c = choices[int(rng.integers(0, len(choices)))]
        k = b"bt/%03d" % int(rng.integers(0, keyspace))
        v = b"v%05d" % int(rng.integers(0, 100000))
        if c == "PUSH":
            ops.append(("PUSH", v))
            depth += 1
        elif c == "DUP":
            ops.append(("DUP",))
            depth += 1
        elif c == "POP":
            ops.append(("POP",))
            depth -= 1
        elif c == "CONCAT_B":
            ops.append(("CONCAT",))
            depth -= 1
        elif c == "SET":
            ops.append(("PUSH", k))
            ops.append(("PUSH", v))
            ops.append(("SET",))
        elif c == "GET":
            ops.append(("PUSH", k))
            ops.append(("GET",))
            depth += 1
        elif c == "CLEAR":
            ops.append(("PUSH", k))
            ops.append(("CLEAR",))
        elif c == "CLEAR_RANGE":
            k2 = b"bt/%03d" % int(rng.integers(0, keyspace))
            b, e = sorted([k, k2])
            ops.append(("PUSH", b))
            ops.append(("PUSH", e + b"\x00"))
            ops.append(("CLEAR_RANGE",))
        elif c == "GET_RANGE":
            ops.append(("PUSH", b"bt/"))
            ops.append(("PUSH", b"bt0"))
            ops.append(("PUSH", 10))
            ops.append(("GET_RANGE",))
            depth += 1
        elif c == "ATOMIC":
            ops.append(("PUSH", k))
            ops.append(("PUSH", (int(rng.integers(0, 1000))
                                 ).to_bytes(8, "little")))
            ops.append(("ATOMIC_ADD" if rng.integers(0, 2) == 0
                        else "ATOMIC_MAX",))
        elif c == "READ_VERSION":
            ops.append(("GET_READ_VERSION",))
            depth += 1
        elif c == "COMMIT":
            ops.append(("COMMIT",))
            depth += 1
    return ops
