"""The FDB tuple layer: order-preserving typed-tuple key encoding.

Reference: design/tuple.md + bindings/python/fdb/tuple.py semantics —
pack() maps tuples of (None | bytes | str | int | float | bool | nested
tuple) to byte strings whose lexicographic order equals the natural
order of the tuples (None < bytes < str < int < float < bool < tuple),
and unpack() inverts it exactly.  This is the public wire format every
reference binding shares, so layers built on one binding interoperate
with all others; the encoding below follows the published spec:

  \\x00                      null (escaped as \\x00\\xff inside nests)
  \\x01 <bytes>  \\x00        byte string, \\x00 escaped as \\x00\\xff
  \\x02 <utf8>   \\x00        unicode string, same escape
  \\x05 ... \\x00             nested tuple
  \\x0c..\\x13                int, negative, 8..1 bytes (offset-complement)
  \\x14                      int zero
  \\x15..\\x1c                int, positive, 1..8 bytes
  \\x20 <8B IEEE>            double, sign-flipped for ordering
  \\x26 / \\x27               false / true
"""

from __future__ import annotations

import struct
from typing import Any, List, Tuple

NULL = 0x00
BYTES = 0x01
STRING = 0x02
NESTED = 0x05
INT_ZERO = 0x14
DOUBLE = 0x20
FALSE = 0x26
TRUE = 0x27

_ESCAPE = b"\x00\xff"


def _encode_bytes(code: int, data: bytes) -> bytes:
    return bytes([code]) + data.replace(b"\x00", _ESCAPE) + b"\x00"


def _encode_int(v: int) -> bytes:
    if v == 0:
        return bytes([INT_ZERO])
    if v > 0:
        n = (v.bit_length() + 7) // 8
        if n > 8:
            raise ValueError(f"int too large for tuple encoding: {v}")
        return bytes([INT_ZERO + n]) + v.to_bytes(n, "big")
    n = ((-v).bit_length() + 7) // 8
    if n > 8:
        raise ValueError(f"int too small for tuple encoding: {v}")
    # Offset complement: stored bytes are (2^(8n) - 1) + v, which orders
    # more-negative values first.
    return bytes([INT_ZERO - n]) + ((1 << (8 * n)) - 1 + v).to_bytes(n, "big")


def _encode_double(v: float) -> bytes:
    raw = bytearray(struct.pack(">d", v))
    # IEEE sign-flip transform: positive numbers get the sign bit set,
    # negatives are fully complemented — total order matches float order.
    if raw[0] & 0x80:
        raw = bytearray(b ^ 0xFF for b in raw)
    else:
        raw[0] ^= 0x80
    return bytes([DOUBLE]) + bytes(raw)


def _encode(value: Any, nested: bool) -> bytes:
    if value is None:
        return b"\x00\xff" if nested else b"\x00"
    if isinstance(value, bool):           # before int (bool is int)
        return bytes([TRUE if value else FALSE])
    if isinstance(value, (bytes, bytearray)):
        return _encode_bytes(BYTES, bytes(value))
    if isinstance(value, str):
        return _encode_bytes(STRING, value.encode("utf-8"))
    if isinstance(value, int):
        return _encode_int(value)
    if isinstance(value, float):
        return _encode_double(value)
    if isinstance(value, (tuple, list)):
        out = bytes([NESTED])
        for item in value:
            out += _encode(item, nested=True)
        return out + b"\x00"
    raise TypeError(f"unpackable tuple element {type(value).__name__}")


def pack(t: Tuple[Any, ...]) -> bytes:
    """Encode a tuple to an order-preserving byte string."""
    return b"".join(_encode(v, nested=False) for v in t)


def _decode_escaped(data: bytes, pos: int) -> Tuple[bytes, int]:
    out = bytearray()
    while True:
        i = data.index(b"\x00", pos)
        if i + 1 < len(data) and data[i + 1] == 0xFF:
            out += data[pos:i] + b"\x00"
            pos = i + 2
        else:
            out += data[pos:i]
            return bytes(out), i + 1


def _decode(data: bytes, pos: int, nested: bool) -> Tuple[Any, int]:
    code = data[pos]
    if code == NULL:
        if nested and pos + 1 < len(data) and data[pos + 1] == 0xFF:
            return None, pos + 2
        return None, pos + 1
    if code == BYTES:
        return _decode_escaped(data, pos + 1)
    if code == STRING:
        raw, p = _decode_escaped(data, pos + 1)
        return raw.decode("utf-8"), p
    if code == NESTED:
        items: List[Any] = []
        p = pos + 1
        while True:
            if data[p] == NULL and not (p + 1 < len(data)
                                        and data[p + 1] == 0xFF):
                return tuple(items), p + 1
            v, p = _decode(data, p, nested=True)
            items.append(v)
    if INT_ZERO - 8 <= code <= INT_ZERO + 8:
        n = code - INT_ZERO
        if n == 0:
            return 0, pos + 1
        if n > 0:
            return int.from_bytes(data[pos + 1:pos + 1 + n], "big"), \
                pos + 1 + n
        n = -n
        return int.from_bytes(data[pos + 1:pos + 1 + n], "big") - \
            ((1 << (8 * n)) - 1), pos + 1 + n
    if code == DOUBLE:
        raw = bytearray(data[pos + 1:pos + 9])
        if raw[0] & 0x80:
            raw[0] ^= 0x80
        else:
            raw = bytearray(b ^ 0xFF for b in raw)
        return struct.unpack(">d", bytes(raw))[0], pos + 9
    if code == FALSE:
        return False, pos + 1
    if code == TRUE:
        return True, pos + 1
    raise ValueError(f"unknown tuple type code 0x{code:02x} at {pos}")


def unpack(data: bytes) -> Tuple[Any, ...]:
    """Decode pack()'s output back to the original tuple."""
    items: List[Any] = []
    pos = 0
    while pos < len(data):
        v, pos = _decode(data, pos, nested=False)
        items.append(v)
    return tuple(items)


def range_of(t: Tuple[Any, ...]) -> Tuple[bytes, bytes]:
    """(begin, end) spanning every tuple that extends `t` (reference
    fdb.tuple.range): pack(t)+\\x00 <= x < pack(t)+\\xff."""
    p = pack(t)
    return p + b"\x00", p + b"\xff"
