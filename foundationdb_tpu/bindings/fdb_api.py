"""The frozen `fdb`-style Python API (reference bindings/python/fdb).

A STABLE veneer over the internal client, shaped like the reference's
python binding (which wraps fdb_c: bindings/c/fdb_c.cpp
fdb_transaction_get :210 / fdb_transaction_commit :272): `open()` a
database, `db[k]` sugar, `@transactional` retry decorator, transaction
objects with get/set/clear/get_range/atomic ops/watch/on_error.  Internal
client refactors must not change THIS surface — tests/test_bindings.py
replays a stack-machine op stream through it and diffs against direct
client calls (the reference's bindingtester role).
"""

from __future__ import annotations

import functools
from typing import Any, Iterable, List, Optional, Tuple

_API_VERSION: Optional[int] = None
MAX_API_VERSION = 710


class FDBError(Exception):
    """Stable error surface: .code matches the reference error codes
    (core/error.py mirrors flow/error_definitions.h)."""

    def __init__(self, code: int, description: str = "") -> None:
        self.code = code
        self.description = description
        super().__init__(f"{description or 'fdb error'} ({code})")


def api_version(version: int) -> None:
    """Select the API version (reference fdb.api_version): must be called
    before open(), at most once, with a supported version."""
    global _API_VERSION
    if _API_VERSION is not None and _API_VERSION != version:
        raise RuntimeError(f"API version already set to {_API_VERSION}")
    if not 14 <= version <= MAX_API_VERSION:
        raise RuntimeError(f"API version {version} not supported")
    _API_VERSION = version


def _require_api_version() -> None:
    if _API_VERSION is None:
        raise RuntimeError("Call fdb.api_version() before using the API")


def _wrap_error(e: BaseException) -> BaseException:
    from ..core.error import FdbError as _Internal
    if isinstance(e, _Internal):
        return FDBError(e.code, e.name)
    return e


def open(cluster_spec: Any = None, event_loop: Any = None) -> "FDBDatabase":
    """Open a database handle.

    `cluster_spec` is a "host:port,..." coordinator string (the content
    of an fdb.cluster file) for real clusters, or an internal Database
    object (sim harnesses pass SimFdbCluster.database())."""
    _require_api_version()
    from ..client.database import Database
    if isinstance(cluster_spec, Database):
        return FDBDatabase(cluster_spec)
    from ..client.database import open_cluster
    loop, db = open_cluster(cluster_spec)
    return FDBDatabase(db, loop=loop)


def transactional(func):
    """@fdb.transactional: the wrapped function's first argument may be a
    Database (a transaction is created and retried until commit) or an
    existing Transaction (caller owns commit) — reference semantics."""
    @functools.wraps(func)
    async def wrapper(db_or_tr, *args, **kwargs):
        if isinstance(db_or_tr, FDBTransaction):
            return await func(db_or_tr, *args, **kwargs)
        tr = db_or_tr.create_transaction()
        while True:
            try:
                result = await func(tr, *args, **kwargs)
                await tr.commit()
                return result
            except FDBError as e:
                await tr.on_error(e)
    return wrapper


class FDBDatabase:
    def __init__(self, db: Any, loop: Any = None) -> None:
        self._db = db
        self._loop = loop

    def create_transaction(self) -> "FDBTransaction":
        return FDBTransaction(self._db.create_transaction())

    # -- db-level conveniences (each one transaction, reference Database
    # auto-retry wrappers) ---------------------------------------------------
    async def get(self, key: bytes) -> Optional[bytes]:
        @transactional
        async def go(tr):
            return await tr.get(key)
        return await go(self)

    async def set(self, key: bytes, value: bytes) -> None:
        @transactional
        async def go(tr):
            tr.set(key, value)
        await go(self)

    async def clear(self, key: bytes) -> None:
        @transactional
        async def go(tr):
            tr.clear(key)
        await go(self)

    async def get_range(self, begin: bytes, end: bytes, limit: int = 0,
                        reverse: bool = False
                        ) -> List[Tuple[bytes, bytes]]:
        @transactional
        async def go(tr):
            return await tr.get_range(begin, end, limit=limit,
                                      reverse=reverse)
        return await go(self)


class FDBTransaction:
    """One transaction (reference fdb.Transaction over fdb_c handles)."""

    def __init__(self, tr: Any) -> None:
        self._tr = tr
        self._cancelled = False
        self.options = _TransactionOptions(tr)

    def _check_cancelled(self) -> None:
        if self._cancelled:
            raise FDBError(1025, "transaction_cancelled")

    # -- reads ---------------------------------------------------------------
    async def get(self, key: bytes) -> Optional[bytes]:
        self._check_cancelled()
        try:
            return await self._tr.get(bytes(key))
        except Exception as e:  # noqa: BLE001
            raise _wrap_error(e) from None

    async def get_key(self, sel: "KeySelector") -> bytes:
        """Resolve a key selector via range reads (the internal client
        has no native selector op; offsets beyond +-1 are unsupported)."""
        try:
            if sel.offset == 1:
                begin = (sel.key + b"\x00") if sel.or_equal else sel.key
                rows = await self._tr.get_range(begin, b"\xff", limit=1)
                return rows[0][0] if rows else b"\xff"
            if sel.offset == 0:
                end = (sel.key + b"\x00") if sel.or_equal else sel.key
                rows = await self._tr.get_range(b"", end, limit=1,
                                                reverse=True)
                return rows[0][0] if rows else b""
            raise FDBError(2000, "key selector offset unsupported")
        except FDBError:
            raise
        except Exception as e:  # noqa: BLE001
            raise _wrap_error(e) from None

    async def get_range(self, begin: bytes, end: bytes, limit: int = 0,
                        reverse: bool = False
                        ) -> List[Tuple[bytes, bytes]]:
        try:
            return await self._tr.get_range(bytes(begin), bytes(end),
                                            limit=limit or 1_000_000,
                                            reverse=reverse)
        except Exception as e:  # noqa: BLE001
            raise _wrap_error(e) from None

    async def get_read_version(self) -> int:
        try:
            return await self._tr.get_read_version()
        except Exception as e:  # noqa: BLE001
            raise _wrap_error(e) from None

    async def watch(self, key: bytes):
        try:
            return await self._tr.watch(bytes(key))
        except Exception as e:  # noqa: BLE001
            raise _wrap_error(e) from None

    # -- writes --------------------------------------------------------------
    def set(self, key: bytes, value: bytes) -> None:
        self._tr.set(bytes(key), bytes(value))

    def clear(self, key: bytes) -> None:
        self._tr.clear(bytes(key))

    def clear_range(self, begin: bytes, end: bytes) -> None:
        self._tr.clear(bytes(begin), bytes(end))

    def add_read_conflict_range(self, begin: bytes, end: bytes) -> None:
        self._tr.add_read_conflict_range(bytes(begin), bytes(end))

    def add_write_conflict_range(self, begin: bytes, end: bytes) -> None:
        self._tr.add_write_conflict_range(bytes(begin), bytes(end))

    # Atomic ops (reference fdb_transaction_atomic_op mutation types).
    def _atomic(self, op, key: bytes, param: bytes) -> None:
        self._tr.atomic_op(op, bytes(key), bytes(param))

    def add(self, key: bytes, param: bytes) -> None:
        from ..txn.types import MutationType
        self._atomic(MutationType.AddValue, key, param)

    def bit_and(self, key: bytes, param: bytes) -> None:
        from ..txn.types import MutationType
        self._atomic(MutationType.And, key, param)

    def bit_or(self, key: bytes, param: bytes) -> None:
        from ..txn.types import MutationType
        self._atomic(MutationType.Or, key, param)

    def bit_xor(self, key: bytes, param: bytes) -> None:
        from ..txn.types import MutationType
        self._atomic(MutationType.Xor, key, param)

    def max(self, key: bytes, param: bytes) -> None:
        from ..txn.types import MutationType
        self._atomic(MutationType.Max, key, param)

    def min(self, key: bytes, param: bytes) -> None:
        from ..txn.types import MutationType
        self._atomic(MutationType.Min, key, param)

    def byte_max(self, key: bytes, param: bytes) -> None:
        from ..txn.types import MutationType
        self._atomic(MutationType.ByteMax, key, param)

    def byte_min(self, key: bytes, param: bytes) -> None:
        from ..txn.types import MutationType
        self._atomic(MutationType.ByteMin, key, param)

    @staticmethod
    def _split_stamp_template(template: bytes) -> Tuple[bytes, int]:
        """Reference >=API 520 convention: the template's trailing 4
        little-endian bytes give the versionstamp offset."""
        if len(template) < 4:
            raise FDBError(2006, "versionstamp template too short")
        off = int.from_bytes(template[-4:], "little")
        body = template[:-4]
        if off + 10 > len(body):
            raise FDBError(2006, "versionstamp offset out of range")
        return body, off

    def set_versionstamped_key(self, key_template: bytes,
                               value: bytes) -> None:
        body, off = self._split_stamp_template(bytes(key_template))
        self._tr.set_versionstamped_key(body, off, bytes(value))

    def set_versionstamped_value(self, key: bytes,
                                 value_template: bytes) -> None:
        body, off = self._split_stamp_template(bytes(value_template))
        self._tr.set_versionstamped_value(bytes(key), body, off)

    # -- lifecycle -----------------------------------------------------------
    async def commit(self) -> None:
        self._check_cancelled()
        try:
            await self._tr.commit()
        except Exception as e:  # noqa: BLE001
            raise _wrap_error(e) from None

    def get_committed_version(self) -> int:
        return self._tr.committed_version

    async def get_versionstamp(self) -> bytes:
        try:
            return await self._tr.get_versionstamp()
        except Exception as e:  # noqa: BLE001
            raise _wrap_error(e) from None

    async def on_error(self, e: BaseException) -> None:
        from ..core.error import FdbError as _Internal
        if isinstance(e, FDBError):
            e = _Internal(e.code, e.description)
        try:
            await self._tr.on_error(e)
        except Exception as e2:  # noqa: BLE001
            raise _wrap_error(e2) from None

    def reset(self) -> None:
        self._tr.reset()
        self._cancelled = False

    def cancel(self) -> None:
        """Reference fdb_transaction_cancel: the transaction may never
        commit after this; reads/commit raise transaction_cancelled
        until reset()."""
        self._cancelled = True
        self._tr.reset()


class _TransactionOptions:
    """Option surface (reference fdb_transaction_set_option): only the
    options the internal client models; unknown setters raise."""

    def __init__(self, tr: Any) -> None:
        self._tr = tr

    def set_access_system_keys(self) -> None:
        self._tr.access_system_keys = True

    def set_report_conflicting_keys(self) -> None:
        self._tr.report_conflicting_keys = True

    def set_timeout(self, ms: int) -> None:
        self._tr.timeout = ms / 1000.0


class KeySelector:
    """first_greater_or_equal & friends (reference KeySelectorRef)."""

    def __init__(self, key: bytes, or_equal: bool, offset: int) -> None:
        self.key = bytes(key)
        self.or_equal = or_equal
        self.offset = offset

    @classmethod
    def last_less_than(cls, key):
        return cls(key, False, 0)

    @classmethod
    def last_less_or_equal(cls, key):
        return cls(key, True, 0)

    @classmethod
    def first_greater_than(cls, key):
        return cls(key, True, 1)

    @classmethod
    def first_greater_or_equal(cls, key):
        return cls(key, False, 1)
