"""Whole-lint-run call graph for flowlint (ISSUE 11).

The dataflow layer (dataflow.py) stops at function boundaries; this
module is the map between them: module naming, import resolution
(absolute AND relative — the codebase imports almost exclusively via
``from ..core.scheduler import delay``), and call-target resolution
from the syntactic shapes the package actually uses:

  * bare names (``helper()``), through ``from``-imports and local
    module-level defs;
  * module-attribute calls (``mod.helper()``) through ``import``
    aliases and ``from pkg import submodule``;
  * ``self.m()`` / ``cls.m()`` / ``super().m()`` method dispatch BY
    CLASS — the enclosing class's method table first, then an MRO walk
    over base classes resolved through the same import tables (in-
    package bases only);
  * ``ClassName(...)`` constructors (-> ``__init__``) and explicit
    ``ClassName.m(...)`` calls.

Everything else (``a.b.c()``, calls on arbitrary receivers, dynamic
dispatch) is an UNKNOWN callee: it resolves to nothing, contributes no
summary effects, and — for the caller-held-lockset seeding — its
terminal name joins a program-wide "unresolved names" set that
disqualifies any same-named function from claiming "I know all my
callers" (the conservative direction: an invisible caller might hold
no lock).

Function identity is ``<root-relative path>::<qualname>`` where
qualname is ``func`` or ``Class.method`` — the same identity
summaries.py keys its per-file fact cache on.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from .dataflow import lock_key

# JSON-safe call-target specs (stored in the per-file fact cache):
#   ["name", n]            bare call n(...)
#   ["attr", base, attr]   base.attr(...) with a Name receiver
#   ["self", m]            self.m(...)
#   ["cls", m]             cls.m(...)
#   ["super", m]           super().m(...)
#   ["typed", texpr, m]    receiver-typed call (ISSUE 13): the receiver's
#                          locally inferred type expression `texpr` —
#                          ["call", *spec] (constructor/factory value),
#                          ["ann", *base_spec] (annotation), or
#                          ["selfattr", attr] (`self.X.m()` through the
#                          class's attribute types) — resolved to a class
#                          at link time, then dispatched like self-calls
#   ["opaque", terminal]   anything else (unknown callee; terminal name
#                          feeds the conservative disqualification set)


def module_name_for(abspath: str) -> Tuple[str, bool]:
    """(dotted module name, is_package) for a source file, derived from
    the ``__init__.py`` chain above it — the name Python would import it
    under from the topmost package's parent.  Files outside any package
    are their own single-segment module."""
    abspath = os.path.abspath(abspath)
    d = os.path.dirname(abspath)
    parts: List[str] = []
    while os.path.exists(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        d = os.path.dirname(d)
    parts.reverse()
    base = os.path.basename(abspath)
    is_pkg = base == "__init__.py"
    if not is_pkg:
        parts.append(os.path.splitext(base)[0])
    if not parts:                   # no package anywhere: bare stem
        return os.path.splitext(base)[0], False
    return ".".join(parts), is_pkg


def build_import_tables(tree: ast.Module, module: str,
                        is_pkg: bool) -> Dict[str, Dict[str, str]]:
    """{'aliases': name -> absolute module, 'from': name -> absolute
    'module.attr'} with RELATIVE imports resolved against `module` —
    the part FileContext's tables skip (they only serve same-file
    rules, which never need it)."""
    aliases: Dict[str, str] = {}
    from_abs: Dict[str, str] = {}
    pkg_parts = module.split(".") if module else []
    if not is_pkg and pkg_parts:
        pkg_parts = pkg_parts[:-1]  # the file's own package
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom):
            if node.level == 0:
                base = node.module or ""
            else:
                anchor = pkg_parts[:len(pkg_parts) - (node.level - 1)] \
                    if node.level - 1 <= len(pkg_parts) else []
                if node.level - 1 > len(pkg_parts):
                    continue        # beyond the top: unresolvable
                base = ".".join(anchor + ([node.module]
                                          if node.module else []))
            if not base:
                continue
            for a in node.names:
                if a.name != "*":
                    from_abs[a.asname or a.name] = f"{base}.{a.name}"
    return {"aliases": aliases, "from": from_abs}


def resolve_external(tables: Dict[str, Dict[str, str]],
                     func: ast.expr) -> Optional[str]:
    """FileContext.resolve_call, but over the absolute import tables
    (so relative imports resolve too): dotted name of an out-of-scope
    call target, or None."""
    if isinstance(func, ast.Name):
        return tables["from"].get(func.id, func.id)
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        mod = tables["aliases"].get(func.value.id)
        if mod is not None:
            return f"{mod}.{func.attr}"
        mod = tables["from"].get(func.value.id)
        if mod is not None:
            return f"{mod}.{func.attr}"
    return None


def call_spec(call: ast.Call) -> List[str]:
    """The JSON-safe target spec for a call (see module docstring)."""
    f = call.func
    if isinstance(f, ast.Name):
        return ["name", f.id]
    if isinstance(f, ast.Attribute):
        v = f.value
        if isinstance(v, ast.Name):
            if v.id == "self":
                return ["self", f.attr]
            if v.id == "cls":
                return ["cls", f.attr]
            return ["attr", v.id, f.attr]
        if isinstance(v, ast.Call) and isinstance(v.func, ast.Name) and \
                v.func.id == "super":
            return ["super", f.attr]
        return ["opaque", f.attr]
    return ["opaque", ""]


def base_spec(expr: ast.expr) -> Optional[List[str]]:
    """Spec for a class-def base: ``Name`` or ``alias.Name``."""
    if isinstance(expr, ast.Name):
        return ["name", expr.id]
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        return ["attr", expr.value.id, expr.attr]
    return None


class CallGraph:
    """Resolution + edges over the per-file facts summaries.py extracts.

    ``facts`` is {rel path: file facts dict}; see summaries.py for the
    schema.  Resolution is purely syntactic over those tables — nothing
    is imported or executed."""

    _MRO_CAP = 10

    def __init__(self, facts: Dict[str, dict]) -> None:
        self.facts = facts
        # module name -> rel path (first wins on freak collisions)
        self.module_of: Dict[str, str] = {}
        for rel, f in facts.items():
            self.module_of.setdefault(f["module"], rel)
        # Terminal names of calls NOBODY could resolve: a function whose
        # name appears here cannot claim to know all its callers.
        self.unresolved_names: Set[str] = set()
        # fid -> list of (caller fid, call record) built by resolve_all.
        self.callers: Dict[str, List[Tuple[str, list]]] = {}
        # caller fid -> [(call record, callee fid or None)] — resolution
        # kept OUT of the fact records themselves (they round-trip
        # through the on-disk cache and must stay pristine).
        self.calls_of: Dict[str, List[Tuple[list, Optional[str]]]] = {}
        # (caller fid, line, callee fid or None, raw spec) for --dump.
        self.edges: List[Tuple[str, int, Optional[str], list]] = []
        # fid -> (rel, class name) the function always returns an
        # instance of — filled by ProgramIndex between the two
        # resolve_all passes; typed specs whose receiver came from a
        # factory call resolve through it on the second pass.
        self.returns_instance: Dict[str, Tuple[str, str]] = {}

    # -- identity ------------------------------------------------------------
    @staticmethod
    def fid(rel: str, qname: str) -> str:
        return f"{rel}::{qname}"

    def function(self, fid: str) -> Optional[dict]:
        rel, _, qname = fid.partition("::")
        f = self.facts.get(rel)
        return f["functions"].get(qname) if f else None

    # -- class-table helpers -------------------------------------------------
    def _class_at(self, rel: str,
                  name: str) -> Optional[Tuple[str, str, dict]]:
        f = self.facts.get(rel)
        if f and name in f["classes"]:
            return rel, name, f["classes"][name]
        return None

    def _resolve_class_spec(
            self, rel: str,
            spec: List[str]) -> Optional[Tuple[str, str, dict]]:
        """(defining rel, DEFINING class name, class facts) for a base/
        class spec seen from `rel` — the name is the class's own, not
        the import alias it was reached through."""
        f = self.facts.get(rel)
        if f is None:
            return None
        tables = f["imports"]
        if spec[0] == "name":
            local = self._class_at(rel, spec[1])
            if local is not None:
                return local
            target = tables["from"].get(spec[1])
            if target is not None:
                mod, _, cname = target.rpartition(".")
                rel2 = self.module_of.get(mod)
                if rel2 is not None:
                    return self._class_at(rel2, cname)
        elif spec[0] == "attr":
            mod = tables["aliases"].get(spec[1]) or \
                tables["from"].get(spec[1])
            rel2 = self.module_of.get(mod) if mod else None
            if rel2 is not None:
                return self._class_at(rel2, spec[2])
        return None

    def _method(self, rel: str, cls_name: str, method: str,
                skip_own: bool = False) -> Optional[str]:
        """fid of `method` on (rel, cls_name) or the nearest in-package
        base (BFS, depth-capped); ``skip_own`` starts at the bases
        (``super()`` dispatch)."""
        seen: Set[Tuple[str, str]] = set()
        queue: List[Tuple[str, str, dict, bool]] = []
        cls = self._class_at(rel, cls_name)
        if cls is None:
            return None
        queue.append((cls[0], cls[1], cls[2], skip_own))
        hops = 0
        while queue and hops < self._MRO_CAP:
            hops += 1
            crel, cname, cfacts, skip = queue.pop(0)
            if (crel, cname) in seen:
                continue
            seen.add((crel, cname))
            if not skip and method in cfacts["methods"]:
                return self.fid(crel, f"{cname}.{method}")
            for bspec in cfacts["bases"]:
                b = self._resolve_class_spec(crel, bspec)
                if b is not None:
                    queue.append((b[0], b[1], b[2], False))
        return None

    # -- local type inference resolution (ISSUE 13) --------------------------
    def resolve_type(self, rel: str, cls_name: Optional[str],
                     texpr, depth: int = 0) -> Optional[Tuple[str, str]]:
        """(defining rel, class name) the type expression denotes, or
        None when it cannot be pinned to ONE in-package class.  texpr:
        ``["call", *call_spec]`` — a constructor (`x = ClassName()`) or
        a factory whose returns-instance summary names a class;
        ``["ann", *base_spec]`` — an annotation; ``["selfattr", X]`` —
        the enclosing class's attribute-type table through the MRO;
        ``["selfelem", X]`` — the ELEMENT type of the container attr X
        (``Dict[K, C]`` values / ``List[C]`` elements, ISSUE 20)."""
        if not texpr or depth > 5:
            return None
        kind = texpr[0]
        if kind == "ann":
            c = self._resolve_class_spec(rel, list(texpr[1:]))
            return (c[0], c[1]) if c is not None else None
        if kind == "call":
            spec = list(texpr[1:])
            if spec and spec[0] in ("name", "attr"):
                c = self._resolve_class_spec(rel, spec)
                if c is not None:       # constructor call
                    return (c[0], c[1])
            fid = self.resolve(rel, cls_name, spec)
            if fid is not None:         # factory: its summary's class
                return self.returns_instance.get(fid)
            return None
        if kind == "selfattr":
            if cls_name is None:
                return None
            return self.attr_type(rel, cls_name, texpr[1], depth + 1)
        if kind == "selfelem":
            if cls_name is None:
                return None
            return self.attr_type(rel, cls_name, texpr[1], depth + 1,
                                  table="elem_types")
        return None

    def attr_type(self, rel: str, cls_name: str, attr: str,
                  depth: int = 0,
                  table: str = "attr_types") -> Optional[Tuple[str, str]]:
        """The class of ``self.<attr>`` on (rel, cls_name), looked up in
        the per-class attribute-type tables (constructor assignments /
        annotations recorded at extraction) through the MRO.  With
        ``table="elem_types"`` the lookup answers for the container's
        ELEMENTS instead (``self.<attr>[k]``)."""
        seen: Set[Tuple[str, str]] = set()
        queue = [(rel, cls_name)]
        hops = 0
        while queue and hops < self._MRO_CAP:
            hops += 1
            crel, cname = queue.pop(0)
            if (crel, cname) in seen:
                continue
            seen.add((crel, cname))
            cf = self.facts.get(crel, {}).get("classes", {}).get(cname)
            if cf is None:
                continue
            texpr = cf.get(table, {}).get(attr)
            if texpr is not None:
                return self.resolve_type(crel, cname, texpr, depth + 1)
            for bspec in cf["bases"]:
                b = self._resolve_class_spec(crel, bspec)
                if b is not None:
                    queue.append((b[0], b[1]))
        return None

    def attr_owner(self, rel: str, cls_name: str,
                   attr: str) -> Tuple[str, str]:
        """The base-MOST in-package ancestor of (rel, cls_name) that
        assigns ``self.<attr>`` — the attribute's allocation-site owner,
        the class component of an object-sensitive lock identity.  A
        Sub method and a Base method locking the inherited ``self._lock``
        must agree on ONE identity; defaults to the class itself when no
        ancestor assigns it."""
        best, best_depth = (rel, cls_name), -1
        seen: Set[Tuple[str, str]] = set()
        queue: List[Tuple[str, str, int]] = [(rel, cls_name, 0)]
        hops = 0
        while queue and hops < 2 * self._MRO_CAP:
            hops += 1
            crel, cname, d = queue.pop(0)
            if (crel, cname) in seen:
                continue
            seen.add((crel, cname))
            cf = self.facts.get(crel, {}).get("classes", {}).get(cname)
            if cf is None:
                continue
            if attr in cf.get("attrs", ()) and d > best_depth:
                best, best_depth = (crel, cname), d
            for bspec in cf["bases"]:
                b = self._resolve_class_spec(crel, bspec)
                if b is not None:
                    queue.append((b[0], b[1], d + 1))
        return best

    # -- call resolution -----------------------------------------------------
    def _module_member(self, rel: str, name: str) -> Optional[str]:
        """fid for a module-level function `name` in `rel`, or the
        ``__init__`` of a module-level class (constructor call)."""
        f = self.facts.get(rel)
        if f is None:
            return None
        if name in f["functions"]:          # top-level functions keyed bare
            return self.fid(rel, name)
        if name in f["classes"]:
            return self._method(rel, name, "__init__")
        return None

    def resolve(self, rel: str, cls_name: Optional[str],
                spec: List[str]) -> Optional[str]:
        """fid of a call target spec seen from (file `rel`, enclosing
        class `cls_name`), or None for unknown callees."""
        f = self.facts.get(rel)
        if f is None or not spec:
            return None
        kind = spec[0]
        if kind in ("self", "cls"):
            if cls_name is None:
                return None
            return self._method(rel, cls_name, spec[1])
        if kind == "super":
            if cls_name is None:
                return None
            return self._method(rel, cls_name, spec[1], skip_own=True)
        tables = f["imports"]
        if kind == "name":
            local = self._module_member(rel, spec[1])
            if local is not None:
                return local
            target = tables["from"].get(spec[1])
            if target is not None:
                mod, _, member = target.rpartition(".")
                rel2 = self.module_of.get(mod)
                if rel2 is not None:
                    return self._module_member(rel2, member)
            return None
        if kind == "attr":
            base, attr = spec[1], spec[2]
            mod = tables["aliases"].get(base)
            if mod is None and tables["from"].get(base) in self.module_of:
                mod = tables["from"][base]
            if mod is not None:
                rel2 = self.module_of.get(mod)
                return self._module_member(rel2, attr) if rel2 else None
            # ClassName.m(...) — a class in scope, explicit dispatch.
            c = self._resolve_class_spec(rel, ["name", base])
            if c is not None:
                return self._method(c[0], c[1], attr)
            return None
        if kind == "typed":
            # obj.m() through the local type-inference pass: resolve the
            # receiver's type expression to a class, then dispatch like
            # an explicit ClassName.m — an ambiguous/unknown receiver
            # never reaches this spec (it stays ["attr", ...] and feeds
            # the conservatism set as before).
            t = self.resolve_type(rel, cls_name, list(spec[1]))
            if t is None:
                return None
            return self._method(t[0], t[1], spec[2])
        return None

    # -- class hierarchy -----------------------------------------------------
    def _build_hierarchy(self) -> None:
        """Parent/child links between in-package classes.  A class with
        an UNRESOLVED base gets a ``None`` parent — an unknown ancestor
        may define (and internally call) anything, which matters for
        the virtual-dispatch conservatism below."""
        self._parents_of: Dict[Tuple[str, str], List] = {}
        self._children_of: Dict[Tuple[str, str], List] = {}
        for rel, f in self.facts.items():
            for cname, c in f["classes"].items():
                for bspec in c["bases"]:
                    b = self._resolve_class_spec(rel, bspec)
                    if b is None:
                        self._parents_of.setdefault((rel, cname),
                                                    []).append(None)
                    else:
                        self._parents_of.setdefault(
                            (rel, cname), []).append((b[0], b[1]))
                        self._children_of.setdefault(
                            (b[0], b[1]), []).append((rel, cname))

    def virtually_dispatched(self, rel: str, cls: str, name: str) -> bool:
        """True when a method's `self.`-callsites may dispatch SOMEWHERE
        ELSE at runtime: the method overrides an ancestor's (callsites
        in the ancestor reach the override, not the base impl — so the
        base's resolved callers are not ALL of this method's callers),
        is overridden by a descendant (this impl's resolved callers can
        actually land on the override), or sits under an unresolved
        base (unknown ancestor: anything goes).  Caller-held seeding
        and lock-param unification both require every caller known, so
        any of these disqualifies (the conservative direction)."""
        seen: Set[Tuple[str, str]] = set()
        queue = list(self._parents_of.get((rel, cls), ()))
        while queue:                # ancestors (and unknown bases)
            p = queue.pop()
            if p is None:
                return True
            if p in seen:
                continue
            seen.add(p)
            pf = self.facts.get(p[0])
            if pf and name in pf["classes"].get(p[1], {}).get(
                    "methods", {}):
                return True
            queue.extend(self._parents_of.get(p, ()))
        seen.clear()
        queue = list(self._children_of.get((rel, cls), ()))
        while queue:                # descendants
            c = queue.pop()
            if c in seen:
                continue
            seen.add(c)
            cf = self.facts.get(c[0])
            if cf and name in cf["classes"].get(c[1], {}).get(
                    "methods", {}):
                return True
            queue.extend(self._children_of.get(c, ()))
        return False

    # -- whole-graph pass ----------------------------------------------------
    def clear_resolution(self) -> None:
        """Drop every resolution artifact (edges, reverse edges, the
        conservatism set) so ``resolve_all`` can run again — the second
        pass after ``returns_instance`` is filled resolves the
        factory-typed receivers the first pass could not."""
        self.unresolved_names.clear()
        self.callers.clear()
        self.calls_of.clear()
        self.edges.clear()

    def resolve_all(self) -> None:
        """Resolve every recorded call once: fills ``edges``,
        ``callers`` (reverse edges), ``unresolved_names`` (the
        conservatism set for caller-held seeding), and the class
        hierarchy links."""
        self._build_hierarchy()
        for rel, f in self.facts.items():
            for qname, fn in f["functions"].items():
                caller = self.fid(rel, qname)
                resolved = self.calls_of.setdefault(caller, [])
                for call in fn["calls"]:
                    spec = call[1]
                    target = self.resolve(rel, fn.get("cls"), spec)
                    self.edges.append((caller, call[0], target, spec))
                    resolved.append((call, target))
                    if target is not None:
                        self.callers.setdefault(target, []).append(
                            (caller, call))
                    else:
                        name = spec[-1] if spec else ""
                        if name:
                            self.unresolved_names.add(name)

    def dump(self) -> List[Dict[str, object]]:
        """JSON rows for ``--dump-callgraph``."""
        return [{"caller": c, "line": line, "callee": t,
                 "target": ".".join(str(s) for s in spec)}
                for c, line, t, spec in
                sorted(self.edges,
                       key=lambda e: (e[0], e[1], e[2] or ""))]
