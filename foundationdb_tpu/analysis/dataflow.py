"""Per-function dataflow for flowlint (ISSUE 9).

The reference Flow ACTOR compiler *enforces* the state-across-wait
discipline at compile time: locals die at every ``wait()`` unless
declared ``state`` (PAPER.md).  Our Python port has no such compiler,
so flowlint grows a dataflow layer: a lightweight statement-level CFG
per function with await/yield points as BARRIER nodes, reaching
definitions whose facts carry a crossed-barrier bit (the def-use-chain
answer to "was this local's value computed before a suspension
point?"), and a forward lockset analysis over ``with <lock>:`` regions
and ``.acquire()``/``.release()`` pairs (meet = intersection: a lock
counts as held only when held on EVERY path into a node).  One
FunctionDataflow is built per function during the Analyzer's single
shared walk and handed to every rule via ``Rule.begin_function``.

Approximations (deliberate, documented):

  * statement granularity — uses inside a statement see the facts at
    statement ENTRY, so ``y = (await f()) + x`` treats ``x`` as read
    before the await; evaluation-order-exact tracking buys nothing for
    the hazard classes the rules target;
  * nested def/class/lambda bodies are EXCLUDED from the parent CFG —
    each nested function gets its own FunctionDataflow when the shared
    walk reaches it (a closure runs under its own control flow, often
    on another thread entirely);
  * exception edges use the standard conservative approximation: every
    statement of a ``try`` body may jump to every reachable handler
    (all frames of the enclosing try stack);
  * a ``with <lock>:`` region is treated as holding the lock on the
    exceptional paths out of its body too (``__exit__`` releases it in
    reality) — conservative for FTL011;
  * locks are keyed by their dotted source text (``self._lock``,
    ``self._cs._lock``).  A LOCAL name in lock position (``with lk:``,
    ``lk.acquire()``) is resolved through the reaching definitions at
    that statement (ISSUE 11): when every reaching def binds the name
    to the SAME lock-shaped attribute expression, the alias
    canonicalizes to that dotted key and participates in lockset
    join/meet like the attribute itself; when the defs disagree (two
    different locks, or a mix of lock and non-lock values) the alias
    is AMBIGUOUS — it contributes nothing to the lockset and is
    recorded in ``alias_ambiguities`` for FTL014.  A PARAMETER in lock
    position is kept under its own name (``lock_params`` records it)
    and unified with the concrete lock its callers pass by the
    interprocedural layer (summaries.py).  One name for two objects
    across FUNCTIONS is still invisible; README's FTL012 caveats spell
    out what this can and cannot prove.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

# Terminal names that make an expression "lock-shaped" for the lockset
# abstraction: self._lock, self._send_lock, some_mutex ...
_LOCK_NAME = re.compile(r"(?:^|_)(?:lock|mutex)$", re.IGNORECASE)

# Container-of-locks names for SUBSCRIPTED lock positions
# (``with self._locks[shard]:``): the plural/collection spellings of
# the same convention.  Every element of one container collapses to a
# single may-alias identity (``self._locks[*]``) — per allocation site,
# not per key expression, exactly like PR 13's instance roles.
_LOCK_CONTAINER_NAME = re.compile(
    r"(?:^|_)(?:locks?|mutex(?:es)?)$", re.IGNORECASE)

# Receiver-mutating container methods: `self.x.append(...)` counts as a
# WRITE access to attribute x for lockset-discipline purposes (FTL012).
MUTATOR_METHODS = frozenset({
    "add", "append", "appendleft", "clear", "discard", "extend",
    "insert", "pop", "popitem", "popleft", "remove", "setdefault",
    "sort", "update",
})

_NESTED_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                  ast.Lambda)

_COMPREHENSIONS = (ast.ListComp, ast.SetComp, ast.DictComp,
                   ast.GeneratorExp)


def _terminal_name(expr: ast.expr) -> Optional[str]:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def lock_key(expr: ast.expr) -> Optional[str]:
    """Dotted source text of `expr` when it is lock-shaped (its terminal
    name ends in lock/mutex), e.g. 'self._lock'; None otherwise.

    A SUBSCRIPT of a lock-container-named base (``self._locks[shard]``,
    ``mutexes[i]``) keys as ``<base>[*]`` — one may-alias element
    identity per container, so two different shards' locks unify.
    That is the may direction FTL011/013 want (holding ANY element
    counts as holding the container's element identity) and errs
    toward "protected" for FTL012."""
    if isinstance(expr, ast.Subscript):
        base = expr.value
        name = _terminal_name(base)
        if name is None or not _LOCK_CONTAINER_NAME.search(name):
            return None
        try:
            return ast.unparse(base) + "[*]"
        except Exception:           # pragma: no cover - defensive
            return None
    name = _terminal_name(expr)
    if name is None or not _LOCK_NAME.search(name):
        return None
    try:
        return ast.unparse(expr)
    except Exception:               # pragma: no cover - defensive
        return None


def lock_annotation(annot: Optional[ast.expr]) -> bool:
    """True when a parameter annotation names a lock type
    (``threading.Lock``/``RLock``/``Lock``)."""
    if annot is None:
        return False
    try:
        text = ast.unparse(annot)
    except Exception:               # pragma: no cover - defensive
        return False
    return bool(re.search(r"\bR?Lock\b", text))


def is_set_expr(node: ast.expr) -> bool:
    """Syntactically set-valued: set literal/comprehension or a
    ``set()``/``frozenset()`` call (shared by FTL005 and the
    interprocedural set-valued-return summaries)."""
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    return isinstance(node, ast.Call) and \
        isinstance(node.func, ast.Name) and \
        node.func.id in ("set", "frozenset")


class DefInfo:
    """One definition site of a local name.

    ``value`` is the RHS expression when the assignment binds the whole
    value to the name, None for opaque binds (params, except-as,
    imports).  ``unpacked`` marks defs where the name gets a PART or
    TRANSFORM of ``value`` (tuple unpack, for-target element, with-as
    enter result, augmented assignment) — value-shape predicates must
    not trust ``value`` for those."""

    __slots__ = ("idx", "name", "value", "annotation", "is_param",
                 "unpacked", "lineno")

    def __init__(self, idx: int, name: str, value: Optional[ast.expr],
                 lineno: int, annotation: Optional[ast.expr] = None,
                 is_param: bool = False, unpacked: bool = False) -> None:
        self.idx = idx
        self.name = name
        self.value = value
        self.annotation = annotation
        self.is_param = is_param
        self.unpacked = unpacked
        self.lineno = lineno

    def __repr__(self) -> str:      # pragma: no cover - debug aid
        return f"DefInfo({self.name}@{self.lineno})"


class CFGNode:
    """One statement-level node.  ``in_defs`` is the reaching-defs fact
    set at node ENTRY as an int bitmask — bit 2i = def i reaches
    uncrossed, bit 2i+1 = def i reaches having crossed an await/yield
    barrier (0 while unreachable); ``in_locks`` is the lockset held at
    node entry (None while unreachable)."""

    __slots__ = ("idx", "stmt", "succs", "exc_succs", "barrier", "defs",
                 "acquires", "releases", "in_defs", "in_locks")

    def __init__(self, idx: int, stmt: Optional[ast.AST]) -> None:
        self.idx = idx
        self.stmt = stmt
        self.succs: Set[int] = set()
        # The subset of succs that are conservative EXCEPTION edges
        # (mid-statement raise into a handler / finally junction) —
        # analyses modeling normal completion (FTL016's leak paths)
        # exclude them; reaching-defs/locksets keep the full set.
        self.exc_succs: Set[int] = set()
        self.barrier = False
        self.defs: List[DefInfo] = []
        self.acquires: FrozenSet[str] = frozenset()
        self.releases: FrozenSet[str] = frozenset()
        self.in_defs = 0
        self.in_locks: Optional[FrozenSet[str]] = None


class _Loop:
    __slots__ = ("header", "breaks")

    def __init__(self, header: int) -> None:
        self.header = header
        self.breaks: List[int] = []


class FunctionDataflow:
    """CFG + reaching definitions + locksets for ONE function body
    (nested functions excluded — they get their own instance)."""

    def __init__(self, func) -> None:
        self.func = func
        self.is_async = isinstance(func, ast.AsyncFunctionDef)
        self.nodes: List[CFGNode] = []
        self.defs: List[DefInfo] = []
        # id(sub-ast) -> CFGNode for every expression scanned into a node.
        self.node_of: Dict[int, CFGNode] = {}
        self.loads: List[Tuple[ast.Name, CFGNode]] = []
        self.calls: List[Tuple[ast.Call, CFGNode]] = []
        self.awaits: List[Tuple[ast.Await, CFGNode]] = []
        # (attr, ast node, 'read'|'write'|'call', cfg node) for every
        # `self.<attr>` access; container-mutator calls classify as write.
        self.self_accesses: List[Tuple[str, ast.AST, str, CFGNode]] = []
        self.acquired_locks: Set[str] = set()
        # Parameters used in lock position (`with p:` / `p.acquire()`):
        # name -> first use line.  Intraprocedurally they stay keyed by
        # their own name; summaries.py unifies them with the concrete
        # lock every caller passes (FTL014 flags callers that disagree).
        self.lock_params: Dict[str, int] = {}
        # (line, name, sorted lock keys) for each AMBIGUOUS lock alias:
        # a Name in lock position whose reaching defs bind it to more
        # than one lock (or a mix of lock and non-lock values).
        self.alias_ambiguities: List[Tuple[int, str, List[str]]] = []
        self._globals: Set[str] = set()
        self._loop_stack: List[_Loop] = []
        self._exc_stack: List[List[int]] = []
        # Bare-NAME lock positions, resolved through reaching defs
        # AFTER the defs fixpoint (aliases canonicalize to the dotted
        # attr key they were assigned from): (node, release node or
        # None, Name expr, 'with'|'acquire'|'release').
        self._pending_locks: List[Tuple[CFGNode, Optional[CFGNode],
                                        ast.Name, str]] = []

        entry = self._new_node(func)
        a = func.args
        for arg in (list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
                    + ([a.vararg] if a.vararg else [])
                    + ([a.kwarg] if a.kwarg else [])):
            self._add_def(entry, arg.arg, None, func.lineno,
                          annotation=arg.annotation, is_param=True)
        # Nodes whose FALL-THROUGH leaves the function (the implicit
        # `return None` off the end) — a branch test or loop header
        # here still has in-body successors, so "no successors" is NOT
        # the exit criterion; FTL016's leak exits need these.
        self.exit_preds: List[int] = \
            self._build_body(func.body, [entry.idx])
        del self._loop_stack, self._exc_stack
        self._analyze()

    # -- construction --------------------------------------------------------
    def _new_node(self, stmt: Optional[ast.AST]) -> CFGNode:
        n = CFGNode(len(self.nodes), stmt)
        self.nodes.append(n)
        # Any statement inside a try may raise into its handlers (every
        # enclosing frame: an unmatched except type propagates outward).
        for frame in self._exc_stack:
            n.succs.update(frame)
            n.exc_succs.update(frame)
        return n

    def _link(self, preds: List[int], node: CFGNode) -> None:
        for p in preds:
            self.nodes[p].succs.add(node.idx)

    def _add_def(self, node: CFGNode, name: str,
                 value: Optional[ast.expr], lineno: int,
                 annotation: Optional[ast.expr] = None,
                 is_param: bool = False, unpacked: bool = False) -> None:
        if name in self._globals:
            return                  # global/nonlocal: not a local def
        d = DefInfo(len(self.defs), name, value, lineno, annotation,
                    is_param, unpacked)
        self.defs.append(d)
        node.defs.append(d)

    def _bind_target(self, node: CFGNode, target: ast.expr,
                     value: Optional[ast.expr], lineno: int,
                     unpacked: bool = False) -> None:
        if isinstance(target, ast.Name):
            self._add_def(node, target.id, value, lineno,
                          unpacked=unpacked)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind_target(node, elt, value, lineno, unpacked=True)
        elif isinstance(target, ast.Starred):
            self._bind_target(node, target.value, value, lineno,
                              unpacked=True)
        # Attribute/Subscript targets: covered by the self-access scan.

    # One recursive expression scan per node: loads, calls, awaits,
    # walrus defs, and self-attribute accesses, with nested scopes
    # excluded and comprehension targets shadowed out.
    def _scan(self, node: CFGNode, tree: ast.AST,
              parent: Optional[ast.AST], grand: Optional[ast.AST],
              shadow: FrozenSet[str]) -> None:
        if isinstance(tree, _NESTED_SCOPES):
            return
        self.node_of[id(tree)] = node
        if isinstance(tree, _COMPREHENSIONS):
            names = {n.id for gen in tree.generators
                     for n in ast.walk(gen.target)
                     if isinstance(n, ast.Name)}
            shadow = shadow | names
        elif isinstance(tree, ast.Await):
            node.barrier = True
            self.awaits.append((tree, node))
        elif isinstance(tree, (ast.Yield, ast.YieldFrom)):
            node.barrier = True
        elif isinstance(tree, ast.Call):
            self.calls.append((tree, node))
        elif isinstance(tree, ast.NamedExpr):
            self._add_def(node, tree.target.id, tree.value,
                          getattr(tree, "lineno", 0))
        elif isinstance(tree, ast.Name):
            if isinstance(tree.ctx, ast.Load) and tree.id not in shadow:
                self.loads.append((tree, node))
        elif isinstance(tree, ast.Attribute) and \
                isinstance(tree.value, ast.Name) and \
                tree.value.id == "self":
            kind = self._classify_self_access(tree, parent, grand)
            self.self_accesses.append((tree.attr, tree, kind, node))
        for child in ast.iter_child_nodes(tree):
            if isinstance(tree, ast.NamedExpr) and child is tree.target:
                continue            # walrus target is a def, not a load
            self._scan(node, child, tree, parent, shadow)

    @staticmethod
    def _classify_self_access(attr: ast.Attribute,
                              parent: Optional[ast.AST],
                              grand: Optional[ast.AST]) -> str:
        if isinstance(attr.ctx, (ast.Store, ast.Del)):
            return "write"
        if isinstance(parent, ast.Call) and parent.func is attr:
            return "call"           # self.method(...): not data access
        if isinstance(parent, ast.Attribute) and parent.value is attr \
                and isinstance(grand, ast.Call) and grand.func is parent \
                and parent.attr in MUTATOR_METHODS:
            return "write"          # self.x.append(...): content write
        if isinstance(parent, ast.Subscript) and parent.value is attr \
                and isinstance(parent.ctx, (ast.Store, ast.Del)):
            return "write"          # self.x[k] = v / del self.x[k]
        return "read"

    def _scan_stmt(self, node: CFGNode, stmt: ast.AST) -> None:
        self._scan(node, stmt, None, None, frozenset())

    def _build_body(self, stmts, preds: List[int]) -> List[int]:
        for stmt in stmts:
            preds = self._build_stmt(stmt, preds)
        return preds

    def _build_stmt(self, stmt: ast.stmt, preds: List[int]) -> List[int]:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            node = self._new_node(stmt)
            self._link(preds, node)
            # Decorators/defaults evaluate in THIS scope; the body does
            # not (it gets its own FunctionDataflow).
            for dec in stmt.decorator_list:
                self._scan_stmt(node, dec)
            a = getattr(stmt, "args", None)
            if a is not None:
                for d in list(a.defaults) + [d for d in a.kw_defaults if d]:
                    self._scan_stmt(node, d)
            self._add_def(node, stmt.name, None, stmt.lineno)
            return [node.idx]

        if isinstance(stmt, ast.If):
            test = self._new_node(stmt)
            self._link(preds, test)
            self._scan_stmt(test, stmt.test)
            body_exits = self._build_body(stmt.body, [test.idx])
            if stmt.orelse:
                else_exits = self._build_body(stmt.orelse, [test.idx])
            else:
                else_exits = [test.idx]
            return body_exits + else_exits

        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            header = self._new_node(stmt)
            self._link(preds, header)
            loop = _Loop(header.idx)
            if isinstance(stmt, ast.While):
                self._scan_stmt(header, stmt.test)
            else:
                self._scan_stmt(header, stmt.iter)
                self._bind_target(header, stmt.target, stmt.iter,
                                  stmt.lineno, unpacked=True)
                if isinstance(stmt, ast.AsyncFor):
                    header.barrier = True   # each iteration suspends
            self._loop_stack.append(loop)
            body_exits = self._build_body(stmt.body, [header.idx])
            self._loop_stack.pop()
            for b in body_exits:
                self.nodes[b].succs.add(header.idx)
            if stmt.orelse:
                exits = self._build_body(stmt.orelse, [header.idx])
            else:
                exits = [header.idx]
            return exits + loop.breaks

        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            header = self._new_node(stmt)
            self._link(preds, header)
            acquires: Set[str] = set()
            deferred: List[ast.Name] = []
            for item in stmt.items:
                self._scan_stmt(header, item.context_expr)
                if item.optional_vars is not None:
                    self._bind_target(header, item.optional_vars,
                                      item.context_expr, stmt.lineno,
                                      unpacked=True)
                if isinstance(stmt, ast.With):
                    ce = item.context_expr
                    if isinstance(ce, ast.Name):
                        # `with lk:` — whether lk is a lock (and WHICH
                        # lock) depends on its reaching defs, known
                        # only after the defs fixpoint.
                        deferred.append(ce)
                    else:
                        key = lock_key(ce)
                        if key is not None:
                            acquires.add(key)
            if isinstance(stmt, ast.AsyncWith):
                header.barrier = True       # __aenter__/__aexit__ await;
                #                             async locks are reactor-safe,
                #                             NOT part of the lockset
            header.acquires = frozenset(acquires)
            self.acquired_locks |= acquires
            body_exits = self._build_body(stmt.body, [header.idx])
            if acquires or deferred:
                release = self._new_node(stmt)      # synthetic __exit__
                release.releases = frozenset(acquires)
                self._link(body_exits, release)
                for ce in deferred:
                    self._pending_locks.append((header, release, ce,
                                                "with"))
                return [release.idx]
            return body_exits

        if isinstance(stmt, ast.Try):
            # A synthetic finally JUNCTION joins every abrupt exit out
            # of the protected region (raise, return, break, an
            # exception mid-statement) into the finalbody — without it
            # a `try: return x finally: cleanup` leaves the finalbody
            # unreachable and its lockset/def facts empty.
            fin: Optional[CFGNode] = None
            if stmt.finalbody:
                fin = self._new_node(stmt)
                self._exc_stack.append([fin.idx])
            handler_entries: List[int] = []
            for h in stmt.handlers:
                hnode = self._new_node(h)
                if h.type is not None:
                    self._scan_stmt(hnode, h.type)
                if h.name:
                    self._add_def(hnode, h.name, None, h.lineno)
                handler_entries.append(hnode.idx)
            if handler_entries:
                self._exc_stack.append(handler_entries)
            body_exits = self._build_body(stmt.body, preds)
            if handler_entries:
                self._exc_stack.pop()
            if stmt.orelse:
                body_exits = self._build_body(stmt.orelse, body_exits)
            handler_exits: List[int] = []
            for h, entry in zip(stmt.handlers, handler_entries):
                handler_exits += self._build_body(h.body, [entry])
            exits = body_exits + handler_exits
            if stmt.finalbody:
                self._exc_stack.pop()
                exits = self._build_body(stmt.finalbody,
                                         exits + [fin.idx])
            return exits

        if isinstance(stmt, ast.Match):
            subject = self._new_node(stmt)
            self._link(preds, subject)
            self._scan_stmt(subject, stmt.subject)
            exits = [subject.idx]
            for case in stmt.cases:
                cnode = self._new_node(case)
                self._link([subject.idx], cnode)
                for n in ast.walk(case.pattern):
                    name = getattr(n, "name", None)
                    if isinstance(name, str):
                        self._add_def(cnode, name, None,
                                      getattr(n, "lineno", stmt.lineno),
                                      unpacked=True)
                if case.guard is not None:
                    self._scan_stmt(cnode, case.guard)
                exits += self._build_body(case.body, [cnode.idx])
            return exits

        # -- simple statements ------------------------------------------------
        if isinstance(stmt, (ast.Global, ast.Nonlocal)):
            self._globals.update(stmt.names)
            node = self._new_node(stmt)
            self._link(preds, node)
            return [node.idx]

        node = self._new_node(stmt)
        self._link(preds, node)
        self._scan_stmt(node, stmt)
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                self._bind_target(node, t, stmt.value, stmt.lineno)
        elif isinstance(stmt, ast.AugAssign):
            # x += v rebinds x from its OLD value: keep the def, mark it
            # unpacked so value-shape predicates don't trust the RHS.
            self._bind_target(node, stmt.target, stmt.value, stmt.lineno,
                              unpacked=True)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None and isinstance(stmt.target, ast.Name):
                self._add_def(node, stmt.target.id, stmt.value,
                              stmt.lineno, annotation=stmt.annotation)
        elif isinstance(stmt, (ast.Import, ast.ImportFrom)):
            for a in stmt.names:
                if a.name != "*":
                    self._add_def(node, a.asname or
                                  a.name.split(".")[0], None, stmt.lineno)
        elif isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            func = stmt.value.func
            if isinstance(func, ast.Attribute) and not stmt.value.args:
                # acquire(timeout=...)/acquire(blocking=False) may
                # FAIL and return False — a MUST analysis cannot
                # treat it as held (the unsound direction); only a
                # bare blocking acquire() enters the lockset.
                is_acquire = func.attr == "acquire" and \
                    not stmt.value.keywords
                is_release = func.attr == "release"
                if isinstance(func.value, ast.Name):
                    # `lk.acquire()` — alias/param, resolved after the
                    # defs fixpoint like a `with lk:` header.
                    if is_acquire or is_release:
                        self._pending_locks.append(
                            (node, None, func.value,
                             "acquire" if is_acquire else "release"))
                else:
                    key = lock_key(func.value)
                    if key is not None:
                        if is_acquire:
                            node.acquires = frozenset({key})
                            self.acquired_locks.add(key)
                        elif is_release:
                            node.releases = frozenset({key})

        if isinstance(stmt, (ast.Return, ast.Raise)):
            return []               # flows to function exit (or handlers,
            #                         which _new_node already wired up)
        if isinstance(stmt, ast.Break):
            if self._loop_stack:
                self._loop_stack[-1].breaks.append(node.idx)
            return []
        if isinstance(stmt, ast.Continue):
            if self._loop_stack:
                node.succs.add(self._loop_stack[-1].header)
            return []
        return [node.idx]

    # -- analyses ------------------------------------------------------------
    def _analyze(self) -> None:
        """Both fixpoints.  Reaching-defs facts are int bitmasks (bit 2i
        = def i uncrossed, bit 2i+1 = def i crossed-a-barrier): merge is
        OR, the barrier transfer is one shift (every uncrossed bit moves
        to its crossed twin), kill/gen are precomputed masks — so the
        fixpoint is a few big-int ops per node visit.  A def generated
        AT a barrier node stays uncrossed (``x = await f()`` is fresh
        after the await); only facts PASSING the barrier get marked."""
        nnodes = len(self.nodes)
        preds: List[List[int]] = [[] for _ in self.nodes]
        for n in self.nodes:
            for s in n.succs:
                preds[s].append(n.idx)

        # Per-name def-index lists + per-node kill/gen masks.
        by_name: Dict[str, List[int]] = {}
        for d in self.defs:
            by_name.setdefault(d.name, []).append(d.idx)
        self._defs_by_name = by_name
        even = 0
        for i in range(len(self.defs)):
            even |= 1 << (2 * i)
        kills = [0] * nnodes
        gens = [0] * nnodes
        for n in self.nodes:
            k = g = 0
            for d in n.defs:
                g |= 1 << (2 * d.idx)
                for j in by_name[d.name]:
                    k |= 3 << (2 * j)
            kills[n.idx] = k
            gens[n.idx] = g

        outs = [None] * nnodes      # None = not yet computed
        pending = [False] * nnodes
        work = [0]
        pending[0] = True
        while work:
            i = work.pop()
            pending[i] = False
            node = self.nodes[i]
            merged = 0
            for p in preds[i]:
                o = outs[p]
                if o is not None:
                    merged |= o
            node.in_defs = merged
            x = merged
            if node.barrier:
                x = ((x & even) << 1) | (x & ~even)
            out = (x & ~kills[i]) | gens[i]
            if out != outs[i]:
                outs[i] = out
                for s in node.succs:
                    if not pending[s]:
                        pending[s] = True
                        work.append(s)

        # Deferred Name-lock resolution sits BETWEEN the fixpoints: it
        # queries the reaching defs computed above and adds acquires/
        # releases the lockset fixpoint below then consumes.
        self._resolve_deferred_locks()

        # Locksets: forward MUST analysis, meet = intersection.
        lock_outs: List[Optional[FrozenSet[str]]] = [None] * nnodes
        work = [0]
        while work:
            i = work.pop()
            node = self.nodes[i]
            if i == 0:
                held: Optional[FrozenSet[str]] = frozenset()
            else:
                held = None
                for p in preds[i]:
                    o = lock_outs[p]
                    if o is None:
                        continue
                    held = o if held is None else (held & o)
                if held is None:
                    continue        # not yet reachable
            node.in_locks = held
            out = (held | node.acquires) - node.releases
            if out != lock_outs[i]:
                lock_outs[i] = out
                work.extend(node.succs)

    def _canonical_alias_key(self, node: CFGNode,
                             name_node: ast.Name) -> Optional[str]:
        """Lock key for a bare NAME in lock position, judged through
        its reaching defs at `node` (the FTL014 alias discipline):

          * every reaching def binds the name to the SAME lock-shaped
            attribute -> that attribute's dotted key (the alias
            PARTICIPATES in lockset join/meet);
          * the defs are all parameters -> the name itself, when the
            param is lock-named or Lock-annotated (recorded in
            ``lock_params`` for interprocedural unification);
          * the defs disagree (>=2 distinct locks, or lock + non-lock
            mix) -> None, with the ambiguity recorded for FTL014;
          * no def is lock-shaped -> the name itself when lock-named
            (``local_lock = threading.Lock()``), else None.
        """
        name = name_node.id
        infos = {d.idx: d for d, _ in self.reaching(node, name)}.values()
        params = [d for d in infos if d.is_param]
        keys: Set[str] = set()
        non_lock = False
        for d in infos:
            if d.is_param:
                continue
            if d.value is None or d.unpacked:
                non_lock = True
                continue
            # `lk = a if c else b` binds one of TWO values in one def.
            values = [d.value.body, d.value.orelse] \
                if isinstance(d.value, ast.IfExp) else [d.value]
            for v in values:
                k = lock_key(v)
                if k is not None:
                    keys.add(k)
                else:
                    non_lock = True
        if keys:
            if len(keys) == 1 and not non_lock and not params:
                return next(iter(keys))
            # The unsound shape: the name IS a lock on some path but
            # not provably ONE lock — drop it from the lockset and let
            # FTL014 say why.
            self.alias_ambiguities.append(
                (getattr(name_node, "lineno", 0), name, sorted(keys)))
            return None
        if params and len(params) == len(list(infos)):
            d = params[0]
            if _LOCK_NAME.search(name) or lock_annotation(d.annotation):
                self.lock_params.setdefault(
                    name, getattr(name_node, "lineno", d.lineno))
                return name
            return None
        if _LOCK_NAME.search(name):
            return name             # pre-alias behavior: lock-named local
        return None

    def alias_lock_key(self, node: CFGNode,
                       name_node: ast.Name) -> Optional[str]:
        """PURE alias resolution for a Name in lock-ARGUMENT position
        (``self._bump(lk)`` where ``lk = self._lock``): the single
        attribute key every reaching def binds it to, else None.
        Unlike ``_canonical_alias_key`` this records nothing (no
        lock-param registration, no FTL014 ambiguity — an ambiguous
        argument just stays unknown) and params resolve to None (a
        param-through-param chain needs a fixpoint the canonicalizer
        doesn't run; unknown is the silent direction)."""
        keys: Set[str] = set()
        infos = {d.idx: d for d, _ in
                 self.reaching(node, name_node.id)}.values()
        if not infos:
            return None
        for d in infos:
            if d.is_param or d.value is None or d.unpacked:
                return None
            values = [d.value.body, d.value.orelse] \
                if isinstance(d.value, ast.IfExp) else [d.value]
            for v in values:
                k = lock_key(v)
                if k is None:
                    return None
                keys.add(k)
        return next(iter(keys)) if len(keys) == 1 else None

    def _resolve_deferred_locks(self) -> None:
        for node, release, name_node, kind in self._pending_locks:
            key = self._canonical_alias_key(node, name_node)
            if key is None:
                continue
            if kind == "release":
                node.releases = node.releases | {key}
            else:                   # 'with' header or bare acquire()
                node.acquires = node.acquires | {key}
                self.acquired_locks.add(key)
                if release is not None:
                    release.releases = release.releases | {key}
        del self._pending_locks

    # -- queries -------------------------------------------------------------
    def reaching(self, node: Optional[CFGNode],
                 name: str) -> List[Tuple[DefInfo, bool]]:
        """Definitions of `name` reaching `node`'s entry, each with its
        crossed-an-await/yield-barrier bit; [] for unreachable nodes."""
        if node is None or not node.in_defs:
            return []
        facts = node.in_defs
        out = []
        for i in self._defs_by_name.get(name, ()):
            if facts & (1 << (2 * i)):
                out.append((self.defs[i], False))
            if facts & (2 << (2 * i)):
                out.append((self.defs[i], True))
        return out

    def lockset(self, node: Optional[CFGNode]) -> FrozenSet[str]:
        """Locks held on every path into `node` (empty if unreachable)."""
        if node is None or node.in_locks is None:
            return frozenset()
        return node.in_locks

    def node_for(self, tree: ast.AST) -> Optional[CFGNode]:
        return self.node_of.get(id(tree))
