"""flowlint rule-engine core.

One AST pass per file: the Analyzer parses each ``.py`` once, builds a
parent map + import-alias tables, and walks every node exactly once,
dispatching to each registered Rule's ``visit``.  Rules are stateless
between files except through their own attributes (cross-file rules use
``finish`` — see FTL007's schema comparison).

Suppression syntax (both forms take a comma list or ``all``):

  x = time.time()        # flowlint: disable=FTL001  -- <why>
  # flowlint: disable-file=FTL005  -- <why>          (anywhere in file)

Baseline: a committed JSON list of ``{"rule", "path", "message"}``
entries (no line numbers — findings must survive unrelated edits).
Matching consumes entries with multiplicity; anything not covered is a
NEW finding.  Exit codes (CLI): 0 clean / all-baselined, 1 new
findings, 2 internal error.  Unparseable files are reported as FTL000,
never silently skipped.
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

_SUPPRESS_LINE = re.compile(r"#\s*flowlint:\s*disable=([A-Za-z0-9_,\s]+)")
_SUPPRESS_FILE = re.compile(r"#\s*flowlint:\s*disable-file=([A-Za-z0-9_,\s]+)")


class Finding:
    """One violation.  Identity for baseline purposes is (rule, path,
    message) — deliberately line-free, so a baselined finding does not
    resurface when unrelated lines shift."""

    __slots__ = ("rule", "path", "line", "message")

    def __init__(self, rule: str, path: str, line: int, message: str) -> None:
        self.rule = rule
        self.path = path
        self.line = line
        self.message = message

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.message)

    def sort_key(self):
        return (self.path, self.line, self.rule, self.message)

    def to_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"Finding({self.rule}, {self.path}:{self.line})"


class Rule:
    """Base class.  Subclasses set ``id`` (FTL0NN) and ``title`` and
    override any of the four hooks.  ``visit`` is called for EVERY node
    of every scanned file (one shared walk — a rule must not walk the
    tree itself); per-file state belongs in ``begin_file``."""

    id = "FTL000"
    title = "base rule"

    def begin_file(self, ctx: "FileContext") -> None:  # noqa: B027
        pass

    def visit(self, node: ast.AST, ctx: "FileContext") -> None:  # noqa: B027
        pass

    def end_file(self, ctx: "FileContext") -> None:  # noqa: B027
        pass

    def finish(self, report: Callable[[Finding], None]) -> None:  # noqa: B027
        """Cross-file checks, called once after every file was walked."""
        pass


class FileContext:
    """Per-file state shared by all rules during the single walk."""

    def __init__(self, path: str, tree: ast.Module, source: str) -> None:
        self.path = path                # root-relative, '/'-separated
        self.tree = tree
        self.source = source
        self.findings: List[Finding] = []
        # Suppression tables, visible to rules DURING the walk: a
        # cross-file rule (FTL007) must drop suppressed callsites from
        # its own state, or its finish()-time findings would bypass the
        # suppression mechanism entirely.
        self.suppress_line, self.suppress_file = _suppressions(source)
        # Lexical stacks maintained by the Analyzer's walk.
        self.func_stack: List[ast.AST] = []
        self.class_stack: List[ast.ClassDef] = []
        # Parent map: id(child) -> parent node (one pre-pass).
        self._parents: Dict[int, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[id(child)] = parent
        # Import alias tables (collected file-wide, including imports
        # inside function bodies — the codebase uses `import time as
        # _time` at both levels): alias -> module for `import m [as a]`,
        # local name -> "module.orig" for `from m import orig [as a]`.
        self.aliases: Dict[str, str] = {}
        self.from_imports: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    if a.name != "*":
                        self.from_imports[a.asname or a.name] = \
                            f"{node.module}.{a.name}"

    # -- helpers for rules ---------------------------------------------------
    @property
    def in_async(self) -> bool:
        """True when the CLOSEST enclosing function is an actor
        (``async def``); a sync helper nested in an actor is not 'in'
        the actor for lexical-rule purposes."""
        return bool(self.func_stack) and \
            isinstance(self.func_stack[-1], ast.AsyncFunctionDef)

    @property
    def at_module_level(self) -> bool:
        return not self.func_stack and not self.class_stack

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(id(node))

    def is_suppressed(self, rule_id: str, line: int) -> bool:
        ids = self.suppress_line.get(line, set()) | self.suppress_file
        return rule_id in ids or "all" in ids

    def resolve_call(self, func: ast.expr) -> Optional[str]:
        """Dotted name of a call target through import aliases:
        ``_time.monotonic(...)`` -> 'time.monotonic',
        ``monotonic(...)`` after `from time import monotonic` ->
        'time.monotonic', bare builtins -> their own name."""
        if isinstance(func, ast.Name):
            return self.from_imports.get(func.id, func.id)
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name):
            mod = self.aliases.get(func.value.id)
            if mod is not None:
                return f"{mod}.{func.attr}"
        return None

    def report(self, rule: Rule, where, message: str) -> None:
        line = where if isinstance(where, int) else \
            getattr(where, "lineno", 0)
        self.findings.append(Finding(rule.id, self.path, line, message))


def _suppressions(source: str) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """(per-line suppressed ids, file-wide suppressed ids).  'all' in a
    set suppresses every rule."""
    per_line: Dict[int, Set[str]] = {}
    file_wide: Set[str] = set()
    for lineno, text in enumerate(source.splitlines(), 1):
        m = _SUPPRESS_FILE.search(text)
        if m:
            file_wide.update(
                t.strip() for t in m.group(1).split(",") if t.strip())
            continue
        m = _SUPPRESS_LINE.search(text)
        if m:
            per_line.setdefault(lineno, set()).update(
                t.strip() for t in m.group(1).split(",") if t.strip())
    return per_line, file_wide


class LintResult:
    """Outcome of one analyzer run."""

    def __init__(self) -> None:
        self.new: List[Finding] = []
        self.baselined: List[Finding] = []
        self.suppressed: int = 0
        self.files_scanned: int = 0

    @property
    def exit_code(self) -> int:
        return 1 if self.new else 0

    def to_dict(self) -> Dict[str, object]:
        return {
            "version": 1,
            "files_scanned": self.files_scanned,
            "counts": {"new": len(self.new),
                       "baselined": len(self.baselined),
                       "suppressed": self.suppressed},
            "findings": [f.to_dict() for f in self.new],
            "baselined": [f.to_dict() for f in self.baselined],
        }


class Analyzer:
    """Runs a rule set over one or more roots (directories or files)."""

    def __init__(self, rules: Sequence[Rule]) -> None:
        self.rules = list(rules)

    # -- file discovery ------------------------------------------------------
    @staticmethod
    def _iter_files(root: str):
        """Yield (abspath, root-relative path) for every .py under root.
        A single-FILE root is rel-ified against its topmost enclosing
        PACKAGE (the dir the default directory scan uses as root), so a
        directly-linted core/scheduler.py gets path 'core/scheduler.py'
        — identical to the directory-scan finding: module exemptions
        ('core/scheduler.py', 'server/') keep matching AND baseline
        entries written by a full scan still cover it.  Outside any
        package, fall back to cwd-relative (portable), then absolute."""
        root = os.path.abspath(root)
        if os.path.isfile(root):
            pkg, top = os.path.dirname(root), None
            while os.path.exists(os.path.join(pkg, "__init__.py")):
                top = pkg
                pkg = os.path.dirname(pkg)
            rel = os.path.relpath(root, top or os.getcwd())
            if top is None and rel.startswith(".."):
                rel = root
            yield root, rel.replace(os.sep, "/")
            return
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    path = os.path.join(dirpath, fn)
                    yield path, os.path.relpath(path, root).replace(
                        os.sep, "/")

    # -- the single shared walk ----------------------------------------------
    def _walk(self, node: ast.AST, ctx: FileContext) -> None:
        for rule in self.rules:
            rule.visit(node, ctx)
        scoped = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef))
        if scoped:
            stack = ctx.class_stack if isinstance(node, ast.ClassDef) \
                else ctx.func_stack
            stack.append(node)
        for child in ast.iter_child_nodes(node):
            self._walk(child, ctx)
        if scoped:
            stack.pop()

    def run(self, roots: Sequence[str],
            baseline: Optional[List[Dict[str, str]]] = None) -> LintResult:
        result = LintResult()
        raw: List[Finding] = []
        for root in roots:
            for path, rel in self._iter_files(root):
                result.files_scanned += 1
                try:
                    with open(path, "r", encoding="utf-8") as f:
                        source = f.read()
                    tree = ast.parse(source, filename=path)
                except (SyntaxError, ValueError, OSError) as e:
                    raw.append(Finding("FTL000", rel,
                                       getattr(e, "lineno", 0) or 0,
                                       f"unparseable file: {e}"))
                    continue
                ctx = FileContext(rel, tree, source)
                for rule in self.rules:
                    rule.begin_file(ctx)
                self._walk(tree, ctx)
                for rule in self.rules:
                    rule.end_file(ctx)
                for f in ctx.findings:
                    if ctx.is_suppressed(f.rule, f.line):
                        result.suppressed += 1
                    else:
                        raw.append(f)
        for rule in self.rules:
            rule.finish(raw.append)
        # Baseline matching: consume entries with multiplicity.
        remaining: Dict[Tuple[str, str, str], int] = {}
        for entry in baseline or []:
            k = (entry.get("rule", ""), entry.get("path", ""),
                 entry.get("message", ""))
            remaining[k] = remaining.get(k, 0) + 1
        for f in sorted(raw, key=Finding.sort_key):
            k = f.key()
            if remaining.get(k, 0) > 0:
                remaining[k] -= 1
                result.baselined.append(f)
            else:
                result.new.append(f)
        return result


# -- baseline persistence ----------------------------------------------------

def load_baseline(path: str) -> List[Dict[str, str]]:
    """Load a baseline file; a missing file is an empty baseline."""
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, list):
        raise ValueError(f"baseline {path}: expected a JSON list")
    return data


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    entries = [{"rule": f.rule, "path": f.path, "message": f.message}
               for f in sorted(findings, key=Finding.sort_key)]
    with open(path, "w", encoding="utf-8") as f:
        json.dump(entries, f, indent=2, sort_keys=True)
        f.write("\n")


# -- output ------------------------------------------------------------------

def format_text(result: LintResult) -> str:
    lines = []
    for f in result.new:
        where = f"{f.path}:{f.line}: " if f.line else (
            f"{f.path}: " if f.path else "")
        lines.append(f"{where}{f.rule} {f.message}")
    lines.append(
        f"flowlint: {len(result.new)} new finding(s), "
        f"{len(result.baselined)} baselined, "
        f"{result.suppressed} suppressed, "
        f"{result.files_scanned} file(s) scanned")
    return "\n".join(lines)


def run_flowlint(roots: Sequence[str], rules: Optional[Sequence[Rule]] = None,
                 baseline_path: Optional[str] = None) -> LintResult:
    """Programmatic entry point (fresh rule instances per run — rules
    carry cross-file state)."""
    from .rules import make_rules
    baseline = load_baseline(baseline_path) if baseline_path else []
    return Analyzer(list(rules) if rules is not None
                    else make_rules()).run(roots, baseline)
