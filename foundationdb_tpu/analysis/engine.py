"""flowlint rule-engine core.

One AST pass per file: the Analyzer parses each ``.py`` once, builds a
parent map + by-type node index + import-alias tables, and walks every
node exactly once, dispatching to each registered Rule's ``visit``;
entering a function additionally builds that function's dataflow
(dataflow.py: CFG, reaching defs, locksets) and hands it to each
rule's ``begin_function``.  Rules are stateless between files except
through their own attributes (cross-file rules use ``finish`` — see
FTL007's schema comparison).

Suppression syntax (both forms take a comma list or ``all``):

  x = time.time()        # flowlint: disable=FTL001  -- <why>
  # flowlint: disable-file=FTL005  -- <why>          (anywhere in file)

Baseline: a committed JSON list of ``{"rule", "path", "message"}``
entries (no line numbers — findings must survive unrelated edits).
Matching consumes entries with multiplicity; anything not covered is a
NEW finding.  Exit codes (CLI): 0 clean / all-baselined, 1 new
findings, 2 internal error.  Unparseable files are reported as FTL000,
never silently skipped.
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from .dataflow import FunctionDataflow

_SUPPRESS_LINE = re.compile(r"#\s*flowlint:\s*disable=([A-Za-z0-9_,\s]+)")
_SUPPRESS_FILE = re.compile(r"#\s*flowlint:\s*disable-file=([A-Za-z0-9_,\s]+)")
# The Python port of Flow's `state` keyword: an assignment marked
# `# flowlint: state` declares "this local is MEANT to survive awaits"
# — FTL010 treats it the way the ACTOR compiler treats a state var.
_STATE_ANNOT = re.compile(r"#\s*flowlint:\s*state\b")
# The FTL017 justified-escape hatch: `# flowlint: owned -- <why>` on a
# promise's CREATION line declares its registry is drained outside the
# package's sight (C extension, test harness).  Kept separate from
# disable= so the sanction travels with the FACTS (summaries.py) and
# keeps applying when the file is read from the summary cache.
_OWNED_ANNOT = re.compile(r"#\s*flowlint:\s*owned\b")


def owned_lines(source: str) -> List[int]:
    """Lines carrying the ``# flowlint: owned`` annotation."""
    return [lineno for lineno, text in
            enumerate(source.splitlines(), 1)
            if _OWNED_ANNOT.search(text)]


def is_actor(node: ast.AST) -> bool:
    """The ONE 'is this an actor' predicate, shared by every rule that
    reasons about actors (FTL003's cancellation handling, FTL010's
    await barriers, FTL011's lock-holding awaits): in this port an
    actor is exactly an ``async def`` — the unit the reference's ACTOR
    compiler generates, scheduled by core/scheduler.py's reactor."""
    return isinstance(node, ast.AsyncFunctionDef)


class Finding:
    """One violation.  Identity for baseline purposes is (rule, path,
    message) — deliberately line-free, so a baselined finding does not
    resurface when unrelated lines shift."""

    __slots__ = ("rule", "path", "line", "message")

    def __init__(self, rule: str, path: str, line: int, message: str) -> None:
        self.rule = rule
        self.path = path
        self.line = line
        self.message = message

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.message)

    def sort_key(self):
        return (self.path, self.line, self.rule, self.message)

    def to_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"Finding({self.rule}, {self.path}:{self.line})"


class Rule:
    """Base class.  Subclasses set ``id`` (FTL0NN) and ``title`` and
    override any of the four hooks.  ``visit`` is called for EVERY node
    of every scanned file (one shared walk — a rule must not walk the
    tree itself); per-file state belongs in ``begin_file``."""

    id = "FTL000"
    title = "base rule"
    # Set True on rules that read ``ctx.cfg`` from visit() WITHOUT
    # overriding begin_function (FTL005's widened check): the Analyzer
    # builds per-function dataflow only when some registered rule
    # consumes it, so single-rule runs (the check_trace_events shim)
    # don't pay the two fixpoints per function for nothing.
    uses_dataflow = False

    def begin_file(self, ctx: "FileContext") -> None:  # noqa: B027
        pass

    def begin_function(self, cfg, ctx: "FileContext") -> None:  # noqa: B027
        """Called once per (possibly nested) function, right after the
        walk enters it, with that function's FunctionDataflow (CFG +
        reaching defs + locksets, dataflow.py).  The cfg covers only
        the function's own body — nested defs get their own call.  The
        same object is also visible as ``ctx.cfg`` while the walk is
        inside the function."""
        pass

    def visit(self, node: ast.AST, ctx: "FileContext") -> None:  # noqa: B027
        pass

    def end_file(self, ctx: "FileContext") -> None:  # noqa: B027
        pass

    def finish_program(self, program,
                       report: Callable[[Finding], None]) -> None:  # noqa: B027
        """Interprocedural checks (ISSUE 11), called once after every
        file was walked AND the ProgramIndex (summaries.py: call graph,
        function summaries, caller-held locksets) was linked.  Any rule
        overriding this makes the Analyzer build the program context.
        ``report`` honors per-line suppressions for findings located in
        scanned files — unlike ``finish``'s raw callback."""
        pass

    def finish(self, report: Callable[[Finding], None]) -> None:  # noqa: B027
        """Cross-file checks, called once after every file was walked."""
        pass


class FileContext:
    """Per-file state shared by all rules during the single walk."""

    def __init__(self, path: str, tree: ast.Module, source: str) -> None:
        self.path = path                # root-relative, '/'-separated
        self.tree = tree
        self.source = source
        self.findings: List[Finding] = []
        # Suppression tables, visible to rules DURING the walk: a
        # cross-file rule (FTL007) must drop suppressed callsites from
        # its own state, or its finish()-time findings would bypass the
        # suppression mechanism entirely.
        self.suppress_line, self.suppress_file = _suppressions(source)
        # Lexical stacks maintained by the Analyzer's walk.
        self.func_stack: List[ast.AST] = []
        self.class_stack: List[ast.ClassDef] = []
        # Dataflow stack: one FunctionDataflow per enclosing function,
        # innermost last (pushed/popped by the Analyzer's walk).
        self.cfg_stack: List[object] = []
        # Every (function node, FunctionDataflow, enclosing class name,
        # nested?) the walk built — the interprocedural layer extracts
        # its per-file facts from these instead of re-analyzing.
        self.cfg_records: List[tuple] = []
        # Lines carrying the `# flowlint: state` annotation (the Flow
        # `state`-keyword port, consumed by FTL010).
        self.state_lines: Set[int] = {
            lineno for lineno, text in
            enumerate(source.splitlines(), 1) if _STATE_ANNOT.search(text)}
        # ONE pre-pass over the tree: parent map (id(child) -> parent)
        # plus a by-type node index — rules MUST use ``nodes_of``/
        # ``enclosing`` for their begin_file prescans instead of
        # re-walking the tree themselves (the per-rule ast.walk passes
        # dominated the lint runtime before ISSUE 9 centralized them).
        self._parents: Dict[int, ast.AST] = {}
        self._by_type: Dict[type, List[ast.AST]] = {}
        for parent in ast.walk(tree):
            self._by_type.setdefault(type(parent), []).append(parent)
            for child in ast.iter_child_nodes(parent):
                self._parents[id(child)] = parent
        # Import alias tables (collected file-wide, including imports
        # inside function bodies — the codebase uses `import time as
        # _time` at both levels): alias -> module for `import m [as a]`,
        # local name -> "module.orig" for `from m import orig [as a]`.
        self.aliases: Dict[str, str] = {}
        self.from_imports: Dict[str, str] = {}
        for node in self._by_type.get(ast.Import, ()):
            for a in node.names:
                self.aliases[a.asname or a.name.split(".")[0]] = a.name
        for node in self._by_type.get(ast.ImportFrom, ()):
            if node.module and node.level == 0:
                for a in node.names:
                    if a.name != "*":
                        self.from_imports[a.asname or a.name] = \
                            f"{node.module}.{a.name}"

    # -- helpers for rules ---------------------------------------------------
    @property
    def in_async(self) -> bool:
        """True when the CLOSEST enclosing function is an actor
        (``async def``); a sync helper nested in an actor is not 'in'
        the actor for lexical-rule purposes."""
        return bool(self.func_stack) and is_actor(self.func_stack[-1])

    @property
    def cfg(self):
        """The innermost enclosing function's FunctionDataflow, or None
        at module/class level."""
        return self.cfg_stack[-1] if self.cfg_stack else None

    @property
    def at_module_level(self) -> bool:
        return not self.func_stack and not self.class_stack

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(id(node))

    def nodes_of(self, *types: type) -> List[ast.AST]:
        """Every node of the given exact AST types, in walk order (the
        shared pre-pass index — cheaper than any per-rule ast.walk)."""
        if len(types) == 1:
            return list(self._by_type.get(types[0], ()))
        out: List[ast.AST] = []
        for t in types:
            out.extend(self._by_type.get(t, ()))
        return out

    def enclosing(self, node: ast.AST, kinds) -> Optional[ast.AST]:
        """Nearest ancestor of `node` whose type is in `kinds`."""
        n = self._parents.get(id(node))
        while n is not None and not isinstance(n, kinds):
            n = self._parents.get(id(n))
        return n

    def is_suppressed(self, rule_id: str, line: int) -> bool:
        ids = self.suppress_line.get(line, set()) | self.suppress_file
        return rule_id in ids or "all" in ids

    def resolve_call(self, func: ast.expr) -> Optional[str]:
        """Dotted name of a call target through import aliases:
        ``_time.monotonic(...)`` -> 'time.monotonic',
        ``monotonic(...)`` after `from time import monotonic` ->
        'time.monotonic', bare builtins -> their own name."""
        if isinstance(func, ast.Name):
            return self.from_imports.get(func.id, func.id)
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name):
            mod = self.aliases.get(func.value.id)
            if mod is not None:
                return f"{mod}.{func.attr}"
        return None

    def report(self, rule: Rule, where, message: str) -> None:
        line = where if isinstance(where, int) else \
            getattr(where, "lineno", 0)
        self.findings.append(Finding(rule.id, self.path, line, message))


def topmost_package(path: str) -> Optional[str]:
    """The outermost directory above `path` that is part of the same
    package chain (consecutive ``__init__.py``), or None when the file
    sits outside any package."""
    pkg, top = os.path.dirname(os.path.abspath(path)), None
    while os.path.exists(os.path.join(pkg, "__init__.py")):
        top = pkg
        pkg = os.path.dirname(pkg)
    return top


def iter_py_files(root: str):
    """Yield (abspath, root-relative path) for every .py under root.
    A single-FILE root is rel-ified against its topmost enclosing
    PACKAGE (the dir the default directory scan uses as root), so a
    directly-linted core/scheduler.py gets path 'core/scheduler.py'
    — identical to the directory-scan finding: module exemptions
    ('core/scheduler.py', 'server/') keep matching AND baseline
    entries written by a full scan still cover it.  Outside any
    package, fall back to cwd-relative (portable), then absolute.
    Shared by the Analyzer's scan and the interprocedural layer's
    program enumeration (summaries.py) so both see the SAME rel-path
    identity for every file."""
    root = os.path.abspath(root)
    if os.path.isfile(root):
        top = topmost_package(root)
        rel = os.path.relpath(root, top or os.getcwd())
        if top is None and rel.startswith(".."):
            rel = root
        yield root, rel.replace(os.sep, "/")
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                path = os.path.join(dirpath, fn)
                yield path, os.path.relpath(path, root).replace(
                    os.sep, "/")


def _suppressions(source: str) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """(per-line suppressed ids, file-wide suppressed ids).  'all' in a
    set suppresses every rule."""
    per_line: Dict[int, Set[str]] = {}
    file_wide: Set[str] = set()
    for lineno, text in enumerate(source.splitlines(), 1):
        m = _SUPPRESS_FILE.search(text)
        if m:
            file_wide.update(
                t.strip() for t in m.group(1).split(",") if t.strip())
            continue
        m = _SUPPRESS_LINE.search(text)
        if m:
            per_line.setdefault(lineno, set()).update(
                t.strip() for t in m.group(1).split(",") if t.strip())
    return per_line, file_wide


class LintResult:
    """Outcome of one analyzer run."""

    def __init__(self) -> None:
        self.new: List[Finding] = []
        self.baselined: List[Finding] = []
        self.suppressed: int = 0
        self.files_scanned: int = 0
        # --stats instrumentation (ISSUE 20): per-rule finding (new +
        # baselined) and suppression counts, and wall-clock per phase
        # (populated only when the Analyzer was given a clock).
        self.rule_stats: Dict[str, Dict[str, int]] = {}
        self.timings: Dict[str, float] = {}

    @property
    def exit_code(self) -> int:
        return 1 if self.new else 0

    def to_dict(self) -> Dict[str, object]:
        return {
            "version": 1,
            "files_scanned": self.files_scanned,
            "counts": {"new": len(self.new),
                       "baselined": len(self.baselined),
                       "suppressed": self.suppressed},
            "findings": [f.to_dict() for f in self.new],
            "baselined": [f.to_dict() for f in self.baselined],
        }

    def stats_dict(self) -> Dict[str, object]:
        """The ``--stats`` document: per-rule finding/suppression
        counts (every registered rule listed, zeros included — a
        stable shape CI can diff) + phase timings in seconds."""
        return {
            "version": 1,
            "files_scanned": self.files_scanned,
            "counts": {"new": len(self.new),
                       "baselined": len(self.baselined),
                       "suppressed": self.suppressed},
            "rules": {k: dict(v)
                      for k, v in sorted(self.rule_stats.items())},
            "phases": {k: round(v, 6)
                       for k, v in self.timings.items()},
        }


class Analyzer:
    """Runs a rule set over one or more roots (directories or files)."""

    def __init__(self, rules: Sequence[Rule],
                 summary_cache: Optional[str] = None,
                 clock: Optional[Callable[[], float]] = None) -> None:
        self.rules = list(rules)
        self.summary_cache = summary_cache
        # Injected by the CLI for --stats (time.perf_counter there) —
        # the analysis package itself never reads a clock, so FTL001
        # stays clean over its own source.
        self._clock = clock
        # Per-node dispatch dominates the lint runtime (PERF.md): only
        # call the hooks a rule actually overrides.  Dataflow-only
        # rules (FTL010-012) never pay the per-node visit fan-out.
        self._visitors = [r for r in self.rules
                          if type(r).visit is not Rule.visit]
        self._fn_rules = [r for r in self.rules
                          if type(r).begin_function is not
                          Rule.begin_function]
        # Rules with interprocedural checks make the run build and link
        # a ProgramIndex; single-rule runs (the check_trace_events
        # shim) pay for neither the dataflow nor the program context.
        self._ip_rules = [r for r in self.rules
                          if type(r).finish_program is not
                          Rule.finish_program]
        self._needs_dataflow = bool(self._fn_rules) or \
            bool(self._ip_rules) or \
            any(r.uses_dataflow for r in self.rules)

    # -- file discovery ------------------------------------------------------
    _iter_files = staticmethod(iter_py_files)

    # -- the single shared walk ----------------------------------------------
    def _walk(self, node: ast.AST, ctx: FileContext) -> None:
        for rule in self._visitors:
            rule.visit(node, ctx)
        scoped = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef))
        is_func = scoped and not isinstance(node, ast.ClassDef)
        if scoped:
            stack = ctx.class_stack if isinstance(node, ast.ClassDef) \
                else ctx.func_stack
            stack.append(node)
            if is_func and self._needs_dataflow:
                # Build this function's dataflow ONCE, during the one
                # shared walk, and fan it out to every rule — rules
                # must query it, never re-walk or re-analyze.
                cfg = FunctionDataflow(node)
                ctx.cfg_records.append(
                    (node, cfg,
                     ctx.class_stack[-1].name if ctx.class_stack else None,
                     len(ctx.func_stack) > 1))
                ctx.cfg_stack.append(cfg)
                for rule in self._fn_rules:
                    rule.begin_function(cfg, ctx)
        for child in ast.iter_child_nodes(node):
            self._walk(child, ctx)
        if scoped:
            stack.pop()
            if is_func and self._needs_dataflow:
                ctx.cfg_stack.pop()

    def run(self, roots: Sequence[str],
            baseline: Optional[List[Dict[str, str]]] = None) -> LintResult:
        result = LintResult()
        raw: List[Finding] = []
        stats = result.rule_stats
        for r in self.rules:
            stats[r.id] = {"findings": 0, "suppressed": 0}

        def _bump(rule_id: str, kind: str) -> None:
            stats.setdefault(
                rule_id, {"findings": 0, "suppressed": 0})[kind] += 1

        clock = self._clock
        t0 = clock() if clock else 0.0
        program = None
        if self._ip_rules:
            from .summaries import ProgramIndex
            program = ProgramIndex.for_roots(
                roots, cache_path=self.summary_cache)
        for root in roots:
            for path, rel in self._iter_files(root):
                result.files_scanned += 1
                try:
                    with open(path, "r", encoding="utf-8") as f:
                        source = f.read()
                    tree = ast.parse(source, filename=path)
                except (SyntaxError, ValueError, OSError) as e:
                    raw.append(Finding("FTL000", rel,
                                       getattr(e, "lineno", 0) or 0,
                                       f"unparseable file: {e}"))
                    continue
                ctx = FileContext(rel, tree, source)
                for rule in self.rules:
                    rule.begin_file(ctx)
                self._walk(tree, ctx)
                for rule in self.rules:
                    rule.end_file(ctx)
                for f in ctx.findings:
                    if ctx.is_suppressed(f.rule, f.line):
                        result.suppressed += 1
                        _bump(f.rule, "suppressed")
                    else:
                        raw.append(f)
                if program is not None:
                    program.add_scanned(ctx, path)
        t1 = clock() if clock else 0.0
        if program is not None:
            # Link the whole program (cache/standalone facts for files
            # outside the scanned set), then run the interprocedural
            # checks — their reports honor per-line suppressions, which
            # finish()-time findings otherwise bypass.
            program.link()

            def _report_ip(f: Finding) -> None:
                if program.is_suppressed(f.rule, f.path, f.line):
                    result.suppressed += 1
                    _bump(f.rule, "suppressed")
                else:
                    raw.append(f)

            for rule in self._ip_rules:
                rule.finish_program(program, _report_ip)
            program.save_cache()
        for rule in self.rules:
            rule.finish(raw.append)
        # Baseline matching: consume entries with multiplicity.
        remaining: Dict[Tuple[str, str, str], int] = {}
        for entry in baseline or []:
            k = (entry.get("rule", ""), entry.get("path", ""),
                 entry.get("message", ""))
            remaining[k] = remaining.get(k, 0) + 1
        for f in sorted(raw, key=Finding.sort_key):
            _bump(f.rule, "findings")
            k = f.key()
            if remaining.get(k, 0) > 0:
                remaining[k] -= 1
                result.baselined.append(f)
            else:
                result.new.append(f)
        t2 = clock() if clock else 0.0
        if clock:
            result.timings = {"scan": t1 - t0, "link": t2 - t1,
                              "total": t2 - t0}
        return result


# -- baseline persistence ----------------------------------------------------

def load_baseline(path: str) -> List[Dict[str, str]]:
    """Load a baseline file; a missing file is an empty baseline."""
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, list):
        raise ValueError(f"baseline {path}: expected a JSON list")
    return data


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    entries = [{"rule": f.rule, "path": f.path, "message": f.message}
               for f in sorted(findings, key=Finding.sort_key)]
    with open(path, "w", encoding="utf-8") as f:
        json.dump(entries, f, indent=2, sort_keys=True)
        f.write("\n")


# -- output ------------------------------------------------------------------

def format_sarif(result: LintResult, rules: Sequence[Rule]) -> str:
    """SARIF 2.1.0 for PR annotation (ISSUE 13): one run, the rule
    registry as tool metadata, one ``error``-level result per NEW
    finding with its repo-relative location — witness chains (FTL013's
    blocking chain, FTL015's acquisition orders) ride in the message
    text, where code-scanning UIs render them verbatim."""
    rule_meta = [{"id": r.id,
                  "name": type(r).__name__,
                  "shortDescription": {"text": r.title}}
                 for r in rules]
    rule_index = {r.id: i for i, r in enumerate(rules)}
    results = []
    for f in result.new:
        entry = {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{"physicalLocation": {
                "artifactLocation": {"uri": f.path,
                                     "uriBaseId": "SRCROOT"},
                "region": {"startLine": max(1, f.line)}}}],
        }
        if f.rule in rule_index:
            entry["ruleIndex"] = rule_index[f.rule]
        results.append(entry)
    doc = {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "flowlint",
                "rules": rule_meta}},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2)


def format_text(result: LintResult) -> str:
    lines = []
    for f in result.new:
        where = f"{f.path}:{f.line}: " if f.line else (
            f"{f.path}: " if f.path else "")
        lines.append(f"{where}{f.rule} {f.message}")
    lines.append(
        f"flowlint: {len(result.new)} new finding(s), "
        f"{len(result.baselined)} baselined, "
        f"{result.suppressed} suppressed, "
        f"{result.files_scanned} file(s) scanned")
    return "\n".join(lines)


def run_flowlint(roots: Sequence[str], rules: Optional[Sequence[Rule]] = None,
                 baseline_path: Optional[str] = None,
                 summary_cache: Optional[str] = None) -> LintResult:
    """Programmatic entry point (fresh rule instances per run — rules
    carry cross-file state).  ``summary_cache`` is the interprocedural
    fact cache path (None = extract everything live, the default for
    programmatic runs so tests never write cache files)."""
    from .rules import make_rules
    baseline = load_baseline(baseline_path) if baseline_path else []
    return Analyzer(list(rules) if rules is not None else make_rules(),
                    summary_cache=summary_cache).run(roots, baseline)
