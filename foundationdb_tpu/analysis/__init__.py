"""flowlint: repo-wide static analysis for actor, determinism, and
key-type hazards.

The reference's actor compiler enforces a whole class of rules at
COMPILE time (no ``this`` after ``wait()``, no uninitialized ``state``,
no stray returns); this package is our Python analog: an AST pass over
the package that pins down hazards the runtime machinery can only catch
per-seed (testing/tester.py NondeterminismAudit sees the code paths one
seed happens to execute — flowlint sees every line).

Layout:
  engine.py   -- rule-engine core: one visitor pass per file, pluggable
                 Rule classes, per-line ``# flowlint: disable=FTL0NN``
                 suppressions, committed-baseline support, text + JSON
                 output, stable exit codes.
  dataflow.py -- per-function dataflow (ISSUE 9): statement-level CFG
                 with await/yield barrier nodes, reaching-definition
                 def-use chains carrying a crossed-await bit, and a
                 lockset abstraction; built once per function on the
                 shared walk, handed to rules via begin_function().
  callgraph.py-- whole-lint-run call graph (ISSUE 11): module naming,
                 absolute + relative import resolution, self/cls/super
                 method dispatch by class, conservative unknown-callee
                 handling; the map between the per-function dataflows.
  summaries.py-- bottom-up function summaries composed over the call
                 graph's SCCs (may-block w/ chain witnesses, set-valued
                 returns, real-only clock reads) plus the top-down
                 caller-held entry locksets and lock-param unification;
                 per-file facts cached by content hash so --changed
                 links the whole program without re-parsing it.
  rules.py    -- the shipped rules (FTL001..FTL014), each grounded in a
                 bug class this repo has actually hit.

Entry points: ``scripts/flowlint.py`` (CLI; scripts/run_chaos.py shells
its ``--format json`` output to link static findings into chaos
summaries), ``run_flowlint()`` (programmatic), and the shim kept at
``scripts/check_trace_events.py`` (FTL007's old standalone home).
"""

from .callgraph import CallGraph
from .dataflow import FunctionDataflow
from .engine import (Analyzer, Finding, LintResult, Rule, format_text,
                     is_actor, load_baseline, run_flowlint, write_baseline)
from .rules import make_rules
from .summaries import ProgramIndex

__all__ = [
    "Analyzer", "CallGraph", "Finding", "FunctionDataflow", "LintResult",
    "ProgramIndex", "Rule", "format_text", "is_actor", "load_baseline",
    "make_rules", "run_flowlint", "write_baseline",
]
