"""flowlint: repo-wide static analysis for actor, determinism, and
key-type hazards.

The reference's actor compiler enforces a whole class of rules at
COMPILE time (no ``this`` after ``wait()``, no uninitialized ``state``,
no stray returns); this package is our Python analog: an AST pass over
the package that pins down hazards the runtime machinery can only catch
per-seed (testing/tester.py NondeterminismAudit sees the code paths one
seed happens to execute — flowlint sees every line).

Layout:
  engine.py   -- rule-engine core: one visitor pass per file, pluggable
                 Rule classes, per-line ``# flowlint: disable=FTL0NN``
                 suppressions, committed-baseline support, text + JSON
                 output, stable exit codes.
  dataflow.py -- per-function dataflow (ISSUE 9): statement-level CFG
                 with await/yield barrier nodes, reaching-definition
                 def-use chains carrying a crossed-await bit, and a
                 lockset abstraction; built once per function on the
                 shared walk, handed to rules via begin_function().
  rules.py    -- the shipped rules (FTL001..FTL012), each grounded in a
                 bug class this repo has actually hit.

Entry points: ``scripts/flowlint.py`` (CLI; scripts/run_chaos.py shells
its ``--format json`` output to link static findings into chaos
summaries), ``run_flowlint()`` (programmatic), and the shim kept at
``scripts/check_trace_events.py`` (FTL007's old standalone home).
"""

from .dataflow import FunctionDataflow
from .engine import (Analyzer, Finding, LintResult, Rule, format_text,
                     is_actor, load_baseline, run_flowlint, write_baseline)
from .rules import make_rules

__all__ = [
    "Analyzer", "Finding", "FunctionDataflow", "LintResult", "Rule",
    "format_text", "is_actor", "load_baseline", "make_rules",
    "run_flowlint", "write_baseline",
]
