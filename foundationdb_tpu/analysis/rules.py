"""flowlint rules FTL001..FTL018.

Every rule is grounded in a bug class this repo has actually hit (see
ISSUE/PR history): wall-clock reads that break unseed reproduction,
str keys that crashed ``_pack_end``, broad excepts that can swallow
``ActorCancelled``, tunables hardcoded outside core/knobs.py, the
caller-holds-the-lock contracts review used to police by hand.

Adding a rule: subclass ``engine.Rule``, set ``id``/``title``, implement
``visit`` (called once per AST node — never walk the tree yourself;
per-file prep goes in ``begin_file``, cross-file checks in ``finish``),
``begin_function`` (handed each function's FunctionDataflow — CFG,
reaching-defs/def-use chains, locksets; dataflow.py), and/or
``finish_program`` (handed the linked ProgramIndex — call graph,
bottom-up function summaries, caller-held locksets; summaries.py),
append it in ``make_rules()``, document it in README's rule table, and
add a known-bad fixture under tests/fixtures/flowlint/ with
``# expect: FTLnNN:<line>`` markers.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from .dataflow import lock_key
from .engine import Finding, Rule, is_actor

# Modules that are real-mode-only BY CONSTRUCTION: never imported on a
# simulation code path, so wall-clock/entropy/set-order hazards in them
# cannot perturb a seeded run.  Mirrors (and extends, for the
# process-supervisor tool) testing/tester.py NondeterminismAudit
# ALLOWED_FILES — the runtime audit and the static pass must agree on
# what counts as sanctioned.
REAL_ONLY_MODULES = (
    "core/rng.py",          # seeds the nondeterministic id gen by design
    "core/scheduler.py",    # real-mode epoch reads the monotonic clock
    "core/threadpool.py",   # real threads only
    "core/profiler.py",     # wall-time slow-task detection
    "rpc/real_network.py",  # real sockets
    "server/real_fs.py",    # real disk
    "server/fdbserver.py",  # real-mode process entry (EventLoop(sim=False));
                            # per-incarnation entropy seeding is its job
    "tools/fdbmonitor.py",  # process supervisor: spawns real fdbservers
)


def _sim_reachable(path: str) -> bool:
    return not path.endswith(REAL_ONLY_MODULES)


class WallClockRule(Rule):
    """FTL001: wall-clock / OS-entropy calls in sim-reachable modules.

    The static complement of testing/tester.py's NondeterminismAudit:
    the audit only sees code paths a given seed executes; this rule sees
    every line.  ``random.Random(seed)`` is allowed (a seeded instance
    is deterministic); module-level ``random.*`` draws shared
    interpreter state and is not."""

    id = "FTL001"
    title = "wall-clock/entropy call in sim-reachable module"

    CLOCKS = {"time.time", "time.time_ns", "time.monotonic",
              "time.monotonic_ns", "time.perf_counter",
              "time.perf_counter_ns"}

    @classmethod
    def is_nondeterministic(cls, name: Optional[str]) -> bool:
        """The ONE wall-clock/entropy predicate — the direct rule and
        summaries.py's clock roots must agree on what counts as a
        read, so both call this."""
        if name is None:
            return False
        if name in cls.CLOCKS or name == "os.urandom" or \
                name == "random.SystemRandom":
            return True
        return name.startswith("random.") and name != "random.Random"

    def visit(self, node: ast.AST, ctx) -> None:
        if not isinstance(node, ast.Call) or not _sim_reachable(ctx.path):
            return
        name = ctx.resolve_call(node.func)
        if name is None:
            return
        if name in self.CLOCKS:
            ctx.report(self, node,
                       f"{name}() in sim-reachable code: route through "
                       "core.scheduler.now() (virtual time) so seeded "
                       "runs replay identically")
        elif name == "os.urandom" or name == "random.SystemRandom":
            ctx.report(self, node,
                       f"{name} is OS entropy: draw from "
                       "core.rng.deterministic_random() instead")
        elif name.startswith("random.") and name != "random.Random":
            ctx.report(self, node,
                       f"module-level {name}() draws shared interpreter "
                       "RNG state: use core.rng.deterministic_random() "
                       "or a seeded random.Random instance")

    def finish_program(self, program, report) -> None:
        """ISSUE 11: clock reads reached VIA HELPERS.  REAL_ONLY
        modules are exempt from the direct check because they are
        'never imported on a sim path by construction' — this pass
        verifies the construction: a sim-reachable callsite whose
        resolved callee chain lands on an unguarded wall-clock/entropy
        read inside a real-only module is exactly such an import.
        Mode-guarded functions (a ``sim`` branch, EventLoop.now()'s
        shape) and suppressed read sites never propagate."""
        for rel, qname, fn, fid in program.iter_scanned_functions():
            if not _sim_reachable(rel):
                continue
            for call, target in program.calls_with_targets(fid):
                if target is None or not program.may_clock(target):
                    continue
                tfn = program.graph.function(target)
                if tfn is not None and tfn["async"] and not call[3]:
                    continue        # coroutine built, never run
                chain = " -> ".join(program.clock_chain(target))
                report(Finding(
                    self.id, rel, call[0],
                    f"call into {target} reaches a wall-clock/entropy "
                    f"read sanctioned only for real-only modules "
                    f"({chain}): sim-reachable code must route through "
                    "core.scheduler.now() / core.rng"))


class UnawaitedCoroutineRule(Rule):
    """FTL002: a coroutine created and immediately discarded.

    ``foo()`` as a bare statement where ``foo`` is an ``async def`` in
    the same file builds a coroutine object that never runs (Python only
    warns at GC time, and only if the warning isn't swallowed).  The
    call must be awaited or handed to ``spawn()``.  A name defined BOTH
    async and sync in the file (e.g. fdb_api.py's FDBDatabase.set
    convenience vs FDBTransaction.set) is ambiguous at a callsite and
    not flagged."""

    id = "FTL002"
    title = "un-awaited coroutine call"

    def begin_file(self, ctx) -> None:
        self._async_defs = \
            {n.name for n in ctx.nodes_of(ast.AsyncFunctionDef)} - \
            {n.name for n in ctx.nodes_of(ast.FunctionDef)}

    def visit(self, node: ast.AST, ctx) -> None:
        if not isinstance(node, ast.Expr) or \
                not isinstance(node.value, ast.Call):
            return
        func = node.value.func
        name = None
        if isinstance(func, ast.Name):
            name = func.id
        elif isinstance(func, ast.Attribute):
            name = func.attr
        if name in self._async_defs:
            ctx.report(self, node,
                       f"coroutine {name}() is created but never awaited "
                       "(await it, or hand it to spawn())")


class BroadExceptInActorRule(Rule):
    """FTL003: a handler inside an actor that can swallow cancellation.

    ``ActorCancelled`` derives from ``BaseException`` (core/error.py) —
    exactly so that ``except Exception`` is cancellation-safe, which is
    why this rule does NOT flag it.  What it flags, inside ``async
    def``: bare ``except:`` and ``except BaseException`` handlers that
    neither re-raise nor delegate to an ``on_error()`` retry helper
    (whose contract is to re-raise non-retryables, incl. cancellation)."""

    id = "FTL003"
    title = "broad except in actor can swallow ActorCancelled"

    @staticmethod
    def _catches_base(h: ast.ExceptHandler) -> bool:
        if h.type is None:
            return True
        names = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
        return any(isinstance(e, ast.Name) and e.id == "BaseException"
                   for e in names)

    @staticmethod
    def _handles_cancellation(h: ast.ExceptHandler) -> bool:
        for n in ast.walk(h):
            if isinstance(n, ast.Raise):
                return True
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "on_error":
                return True
        return False

    def visit(self, node: ast.AST, ctx) -> None:
        if not isinstance(node, ast.ExceptHandler) or not ctx.in_async:
            return
        if self._catches_base(node) and not self._handles_cancellation(node):
            what = "bare except:" if node.type is None \
                else "except BaseException"
            ctx.report(self, node,
                       f"{what} in an actor swallows ActorCancelled: "
                       "re-raise (bare `raise`), narrow to Exception, or "
                       "delegate to on_error()")


class StrKeyRule(Rule):
    """FTL004: a str literal flowing into a bytes-key API.

    The ``_pack_end`` bug class (PR 2/4): FDB keys and values are bytes;
    a str slips through dynamic paths until pack time, sometimes only on
    rarely-taken branches.  Flags str literals (incl. f-strings and
    ``"a" + x`` concatenations) at key/value positions of transaction
    methods and pack helpers.  Plain ``.get()`` is deliberately NOT
    checked: dict.get with str keys is pervasive and the noise would
    drown the signal; ``.set()`` is only checked at arity >= 2 or with
    a kv-style keyword (key=/value=/...) — signal objects like
    ``shutdown_signal.set("kill")`` are unary and keyword-free."""

    id = "FTL004"
    title = "str literal flows into bytes-key API"

    # method -> positional arg indices that must be bytes
    KEY_POSITIONS = {"set": (0, 1), "clear": (0, 1), "clear_range": (0, 1),
                     "get_range": (0, 1), "get_key": (0,), "watch": (0,),
                     "add_read_conflict_range": (0, 1),
                     "add_write_conflict_range": (0, 1),
                     "atomic_op": (1,)}
    KEY_KEYWORDS = ("key", "begin", "end", "value")
    PACK_HELPERS = ("_pack", "_pack_end")

    @classmethod
    def _strish(cls, node: ast.expr) -> bool:
        if isinstance(node, ast.Constant):
            return isinstance(node.value, str)
        if isinstance(node, ast.JoinedStr):
            return True
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            return cls._strish(node.left) or cls._strish(node.right)
        return False

    def visit(self, node: ast.AST, ctx) -> None:
        if not isinstance(node, ast.Call):
            return
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None)
        if name in self.PACK_HELPERS:
            positions = (0,)
        elif name in self.KEY_POSITIONS:
            positions = self.KEY_POSITIONS[name]
            if name == "set" and len(node.args) < 2 and not any(
                    kw.arg in self.KEY_KEYWORDS for kw in node.keywords):
                return      # unary .set() is a signal/flag, not a kv
                #             write; a kv-ish keyword (key=/value=)
                #             re-qualifies it as one
        else:
            return
        for i in positions:
            if i < len(node.args) and self._strish(node.args[i]):
                ctx.report(self, node,
                           f"str literal passed to {name}() arg {i}: keys "
                           "and values are bytes (b'...', or .encode())")
                return
        for kw in node.keywords:
            if kw.arg in self.KEY_KEYWORDS and self._strish(kw.value):
                ctx.report(self, node,
                           f"str literal passed to {name}({kw.arg}=...): "
                           "keys and values are bytes (b'...', or "
                           ".encode())")
                return


class SetIterationRule(Rule):
    """FTL005: iterating a set in sim-reachable code.

    str hashing is salted by PYTHONHASHSEED, so set iteration order is
    process-dependent — the exact hazard that breaks cross-process
    unseed reproduction (ROADMAP chaos follow-up).  Flags ``for``
    loops / comprehensions whose iterable is syntactically a set (set
    literal, set comprehension, ``set(...)``/``frozenset(...)`` call)
    — and, through the dataflow layer's def-use chains (ISSUE 9), a
    NAME whose reaching definition is set-valued: assigned a set
    expression, a set-operator combination (``a | b``), a call to a
    same-file helper whose every return is a set, or a parameter
    annotated ``set``/``Set[...]``/``frozenset``.  Re-binding kills the
    taint (``s = sorted(s)`` is the fix and is not flagged).  Dict
    iteration is NOT flagged: Python dicts are insertion-ordered,
    hence deterministic."""

    id = "FTL005"
    title = "set iteration order is PYTHONHASHSEED-dependent"
    uses_dataflow = True            # reads ctx.cfg from visit()

    def __init__(self) -> None:
        # Iteration sites whose set-valuedness hinges on calls the
        # per-file pass cannot resolve (cross-file imports, same-file
        # chains deeper than one hop): decided against the linked
        # summaries in finish_program (ISSUE 11).
        self._deferred: List[tuple] = []

    _SET_ANNOT = re.compile(
        r"^(typing\.)?(set|frozenset|Set|FrozenSet|AbstractSet|"
        r"MutableSet)\b")
    _SET_OPS = (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)
    _SET_METHODS = ("union", "intersection", "difference",
                    "symmetric_difference", "copy")

    @staticmethod
    def _is_set_expr(node: ast.expr) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        return isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Name) and \
            node.func.id in ("set", "frozenset")

    def begin_file(self, ctx) -> None:
        # Set-returning helpers defined in THIS file: every `return` of
        # the NEAREST enclosing function is syntactically a set
        # expression.  One level deep on purpose — a fixpoint over
        # helper-calling-helper chains buys noise, not signal.
        _FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        all_set: Dict[ast.AST, bool] = {}
        for r in ctx.nodes_of(ast.Return):
            fn = ctx.enclosing(r, _FUNCS)
            if not isinstance(fn, ast.FunctionDef):
                continue
            ok = r.value is not None and self._is_set_expr(r.value)
            all_set[fn] = all_set.get(fn, True) and ok
        # A name is a helper only when EVERY same-named function in the
        # file qualifies — two classes defining `make()` differently
        # must not cross-taint (the FTL002 same-name ambiguity rule).
        bad = {fn.name for fn, ok in all_set.items() if not ok} | \
              {n.name for n in ctx.nodes_of(ast.FunctionDef)
               if n not in all_set}
        self._set_helpers: Set[str] = \
            {fn.name for fn, ok in all_set.items() if ok} - bad

    def _set_annotation(self, annot: Optional[ast.expr]) -> bool:
        if annot is None:
            return False
        try:
            text = ast.unparse(annot)
        except Exception:           # pragma: no cover - defensive
            return False
        return bool(self._SET_ANNOT.match(text))

    def _set_valued(self, expr: ast.expr, ctx, targets: List[list],
                    depth: int = 0) -> bool:
        """Is `expr` a set, judging through the current function's
        def-use chains?  Depth-bounded; unpacked/augmented defs are
        opaque (never set-valued).  Calls this file-local pass cannot
        judge append their target spec to `targets` — the ISSUE-11
        deferral: if the linked summaries later prove ANY of them
        set-valued, the iteration is flagged from finish_program."""
        if depth > 4:
            return False
        if self._is_set_expr(expr):
            return True
        if isinstance(expr, ast.BinOp) and isinstance(expr.op,
                                                      self._SET_OPS):
            return self._set_valued(expr.left, ctx, targets, depth + 1) or \
                self._set_valued(expr.right, ctx, targets, depth + 1)
        if isinstance(expr, ast.Call):
            f = expr.func
            if isinstance(f, ast.Name):
                if f.id in self._set_helpers:
                    return True
                targets.append(["name", f.id])
                return False
            if isinstance(f, ast.Attribute):
                if f.attr in self._SET_METHODS:
                    return self._set_valued(f.value, ctx, targets,
                                            depth + 1)
                if isinstance(f.value, ast.Name):
                    if f.value.id == "self":
                        if f.attr in self._set_helpers:
                            return True  # set-returning method, one hop
                        targets.append(["self", f.attr])
                    else:
                        targets.append(["attr", f.value.id, f.attr])
            return False
        if isinstance(expr, ast.Name):
            cfg = ctx.cfg
            if cfg is None:
                return False
            node = cfg.node_for(expr)
            for dinfo, _crossed in cfg.reaching(node, expr.id):
                if dinfo.is_param:
                    if self._set_annotation(dinfo.annotation):
                        return True
                elif not dinfo.unpacked and dinfo.value is not None and \
                        self._set_valued(dinfo.value, ctx, targets,
                                         depth + 1):
                    return True
            return False
        return False

    def _check_iter(self, it: ast.expr, ctx) -> None:
        if self._is_set_expr(it):
            ctx.report(self, it,
                       "iteration over a set: order depends on "
                       "PYTHONHASHSEED for str elements — wrap in "
                       "sorted() (deterministic) before iterating")
        elif isinstance(it, (ast.Name, ast.Call)):
            targets: List[list] = []
            if isinstance(it, ast.Name) and self._set_valued(it, ctx,
                                                             targets):
                ctx.report(self, it,
                           f"iteration over set-valued '{it.id}': order "
                           "depends on PYTHONHASHSEED for str elements — "
                           "wrap in sorted() (deterministic) before "
                           "iterating")
                return
            if isinstance(it, ast.Call):
                # `for x in helper():` — one-hop same-file helpers flag
                # here; everything else defers to the summaries.
                if self._set_valued(it, ctx, targets):
                    ctx.report(self, it,
                               "iteration over a set-returning call: "
                               "order depends on PYTHONHASHSEED for str "
                               "elements — wrap in sorted() "
                               "(deterministic) before iterating")
                    return
            if targets:
                cls = ctx.class_stack[-1].name if ctx.class_stack else None
                name = it.id if isinstance(it, ast.Name) else \
                    "the iterated call"
                self._deferred.append(
                    (ctx.path, getattr(it, "lineno", 0), name, cls,
                     targets))

    def finish_program(self, program, report) -> None:
        """Resolve the deferred candidates against the set-valued-return
        summaries (cross-file helpers, same-file chains deeper than the
        one-hop ``begin_file`` table, recursion through SCCs)."""
        for path, line, name, cls, targets in self._deferred:
            hit = None
            for spec in targets:
                fid = program.resolve(path, cls, spec)
                if program.set_valued(fid):
                    hit = fid
                    break
            if hit is not None:
                report(Finding(
                    self.id, path, line,
                    f"iteration over set-valued '{name}': {hit} "
                    "returns a set on every path (judged through the "
                    "interprocedural summaries) — order depends on "
                    "PYTHONHASHSEED for str elements; wrap in sorted() "
                    "before iterating"))

    def visit(self, node: ast.AST, ctx) -> None:
        if not _sim_reachable(ctx.path):
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            self._check_iter(node.iter, ctx)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                self._check_iter(gen.iter, ctx)


class BlockingInActorRule(Rule):
    """FTL006: a blocking call inside an actor.

    Actors interleave only at awaits on ONE reactor thread: a
    ``time.sleep`` stalls every other actor (and under sim stalls
    *virtual* time for wall time), and direct ``open()``/``os.open``
    bypasses sim_fs — the simulated power-loss/fault machinery never
    sees that file.  Use ``core.scheduler.delay()`` and the worker's
    filesystem handle (``sim_fs``/``real_fs``)."""

    id = "FTL006"
    title = "blocking call inside actor"

    BLOCKING = {"time.sleep": "core.scheduler.delay() (non-blocking, "
                              "virtual under sim)",
                "open": "the role's filesystem handle (sim_fs/real_fs)",
                "os.open": "the role's filesystem handle (sim_fs/real_fs)"}

    def visit(self, node: ast.AST, ctx) -> None:
        if not isinstance(node, ast.Call) or not ctx.in_async or \
                not _sim_reachable(ctx.path):
            return
        name = ctx.resolve_call(node.func)
        if name in self.BLOCKING:
            ctx.report(self, node,
                       f"blocking {name}() inside an actor: use "
                       f"{self.BLOCKING[name]}")


class TraceEventRule(Rule):
    """FTL007: TraceEvent naming + cross-module schema drift (absorbed
    from scripts/check_trace_events.py, which remains as a thin shim).

    1. every ``TraceEvent("Name")`` literal must be UpperCamelCase;
    2. no two modules may emit the same Type with different *chained*
       detail schemas — a Type is a contract for trace consumers.
       Details added through a variable are invisible statically and
       make that callsite "open" (exempt from the comparison);
    3. every ``trace_batch_event(type, id, location)`` span point must
       carry a dotted CamelCase-headed Location (ISSUE 20) — the
       commit-debug waterfall keys hops on the ``Role.point`` prefix,
       so a free-form location silently drops out of the timeline.
       F-string locations (``f"Rpc.encode.{name}"``, the PR-14 codec
       span points; ``f"TLog.{self.id}.commit"``) are validated on
       their static prefix, which must reach a separator on the same
       grammar; a fully-dynamic location with no static head is a
       finding."""

    id = "FTL007"
    title = "TraceEvent naming / schema drift"

    CAMEL = re.compile(r"^[A-Z][A-Za-z0-9]*$")
    # Established cross-role correlation events whose Location field IS
    # the schema discriminator (emitted via trace_batch_event).
    SCHEMA_ALLOWLIST = {"CommitDebug", "TransactionDebug"}
    # Span-point Location grammar: CamelCase role head + >=1 dotted
    # point segments.  The PREFIX form additionally accepts ':' (the
    # ``CommitProxy.batch:{span}`` key spelling) and a trailing
    # separator with the segment still to come from the f-string.
    SPAN_POINT = re.compile(r"^[A-Z][A-Za-z0-9]*(\.[A-Za-z0-9_]+)+$")
    SPAN_PREFIX = re.compile(r"^[A-Z][A-Za-z0-9]*([.:][A-Za-z0-9_]*)*$")

    def __init__(self) -> None:
        # type -> {module: [keyset or None per callsite]}
        self._by_type: Dict[str, Dict[str, List[Optional[frozenset]]]] = {}

    @staticmethod
    def _chain(call: ast.Call):
        """For the OUTERMOST call of a TraceEvent(...).detail(...)...
        chain, return (type_name, chained detail keys or None when a key
        is not a literal); None for calls that are not such a chain."""
        keys: Set[str] = set()
        opaque = False
        node = call
        while True:
            f = node.func
            if isinstance(f, ast.Attribute):
                if f.attr == "detail":
                    if node.args and isinstance(node.args[0], ast.Constant) \
                            and isinstance(node.args[0].value, str):
                        keys.add(node.args[0].value)
                    else:
                        opaque = True
                elif f.attr not in ("error", "log"):
                    return None
                if not isinstance(f.value, ast.Call):
                    return None
                node = f.value
                continue
            if isinstance(f, ast.Name) and f.id == "TraceEvent":
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    return node.args[0].value, \
                        (None if opaque else frozenset(keys))
                return None
            return None

    def _check_span_point(self, call: ast.Call, ctx) -> None:
        loc = call.args[2]
        if isinstance(loc, ast.Constant) and isinstance(loc.value, str):
            if not self.SPAN_POINT.match(loc.value):
                ctx.report(
                    self, call,
                    f"trace_batch_event location {loc.value!r} is not "
                    "a dotted CamelCase-headed span point "
                    "('Role.point', e.g. 'CommitProxy.batchStart', "
                    "'Rpc.encode.<name>') — the commit-debug waterfall "
                    "drops it")
        elif isinstance(loc, ast.JoinedStr):
            vals = loc.values
            head = vals[0] if vals else None
            if not (isinstance(head, ast.Constant) and
                    isinstance(head.value, str)):
                ctx.report(
                    self, call,
                    "trace_batch_event f-string location has no static "
                    "CamelCase head — trace consumers key hops on the "
                    "'Role.point' prefix; start the location with the "
                    "literal role name")
            elif not self.SPAN_PREFIX.match(head.value):
                ctx.report(
                    self, call,
                    f"trace_batch_event location prefix {head.value!r} "
                    "does not follow the 'Role.point' span-point "
                    "grammar (CamelCase head, dotted segments)")
        # A location built from a plain variable is invisible
        # statically: an open callsite, same as opaque detail keys.

    def visit(self, node: ast.AST, ctx) -> None:
        if not isinstance(node, ast.Call):
            return
        if isinstance(node.func, ast.Name) and \
                node.func.id == "trace_batch_event" and \
                len(node.args) >= 3:
            self._check_span_point(node, ctx)
        # Only the outermost call of each chain: skip a Call that is the
        # receiver of another attribute call.
        parent = ctx.parent(node)
        if isinstance(parent, ast.Attribute):
            grand = ctx.parent(parent)
            if isinstance(grand, ast.Call) and grand.func is parent:
                return
        got = self._chain(node)
        if got is None:
            return
        type_name, keys = got
        if not self.CAMEL.match(type_name):
            ctx.report(self, node,
                       f"TraceEvent type {type_name!r} is not "
                       "UpperCamelCase")
        # A suppressed callsite (per-line or disable-file) must not
        # join the cross-file schema comparison either — finish()-time
        # findings have no line of their own, so this is the only place
        # the suppression can take effect for drift.
        if ctx.is_suppressed(self.id, getattr(node, "lineno", 0)):
            return
        self._by_type.setdefault(type_name, {}).setdefault(
            ctx.path, []).append(keys)

    def finish(self, report) -> None:
        for type_name, modules in sorted(self._by_type.items()):
            if len(modules) < 2 or type_name in self.SCHEMA_ALLOWLIST:
                continue
            schemas = {}
            for mod, keysets in modules.items():
                if any(k is None for k in keysets):
                    continue        # opaque callsite: module is "open"
                schemas[mod] = frozenset().union(*keysets)
            if len(set(schemas.values())) > 1:
                detail = "; ".join(
                    f"{m}: {sorted(s) or ['<none>']}"
                    for m, s in sorted(schemas.items()))
                report(Finding(
                    self.id, sorted(modules)[0], 0,
                    f"TraceEvent type {type_name!r} emitted from "
                    f"{len(modules)} modules with different detail "
                    f"schemas: {detail}"))


class HardcodedTunableRule(Rule):
    """FTL008: a hardcoded float tunable in a server/conflict hot path.

    Timeouts, cadences, and latency magnitudes belong in core/knobs.py:
    knobs are overridable at startup, BUGGIFY-randomizable per seed, and
    dynamically updatable through the config DB — a module-level float
    constant is none of those.  Int constants are NOT flagged: in this
    codebase they are format/protocol constants (magics, page sizes,
    opcode ids, lane counts), not tunables."""

    id = "FTL008"
    title = "hardcoded tunable should route through core/knobs.py"

    NAME = re.compile(r"^_?[A-Z][A-Z0-9_]*$")
    HOT_PATHS = ("server/", "conflict/")

    @staticmethod
    def _float_value(node: ast.expr):
        if isinstance(node, ast.UnaryOp) and \
                isinstance(node.op, ast.USub):
            node = node.operand
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, float):
            return node.value
        return None

    def visit(self, node: ast.AST, ctx) -> None:
        if not isinstance(node, ast.Assign) or not ctx.at_module_level:
            return
        if not any(h in ctx.path for h in self.HOT_PATHS):
            return
        if len(node.targets) != 1 or \
                not isinstance(node.targets[0], ast.Name):
            return
        name = node.targets[0].id
        value = self._float_value(node.value)
        if self.NAME.match(name) and value is not None:
            ctx.report(self, node,
                       f"hardcoded tunable {name} = {value!r}: move it "
                       "to core/knobs.py (overridable, "
                       "BUGGIFY-randomizable, dynamic-knob updatable)")


class KnobNameRule(Rule):
    """FTL009: a knob attribute name that does not exist on its knob
    class — the typo class dynamic knob plumbing makes silent.

    ``knobs.CONFLICT_DEVICE_TIMEOUT_SEC`` raises AttributeError only on
    the (possibly rare) path that reads it, and ``getattr(knobs, "NAME",
    default)`` never raises at all — a misspelled knob quietly pins the
    default forever.  The rule audits every ALL-CAPS attribute access
    (and getattr with a literal name) on values produced by the knob
    factories (``server_knobs()`` / ``client_knobs()``) against the
    field set statically extracted from core/knobs.py's ``self.NAME =``
    assignments, so the check needs no import of the linted code."""

    id = "FTL009"
    title = "unknown knob name (typo against the knob class field set)"

    FACTORIES = {"server_knobs": "ServerKnobs",
                 "client_knobs": "ClientKnobs"}
    NAME = re.compile(r"^[A-Z][A-Z0-9_]*$")

    def __init__(self, knobs_source: Optional[str] = None) -> None:
        self._fields = self._load_fields(knobs_source)
        self._vars: Dict[str, str] = {}

    @staticmethod
    def _load_fields(src_path: Optional[str] = None) -> Dict[str, Set[str]]:
        """{knob class -> field names} from core/knobs.py's AST (every
        ``self.NAME = ...`` in each class body)."""
        import os
        if src_path is None:
            src_path = os.path.join(os.path.dirname(__file__), os.pardir,
                                    "core", "knobs.py")
        fields: Dict[str, Set[str]] = {}
        try:
            with open(src_path, "r", encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError, ValueError):
            return fields          # no knob source: rule reports nothing
        for node in tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            names: Set[str] = set()
            for n in ast.walk(node):
                if isinstance(n, ast.Assign):
                    for t in n.targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self":
                            names.add(t.attr)
            fields[node.name] = names
        return fields

    def _factory_class(self, node: ast.expr, ctx) -> Optional[str]:
        """Knob class name when `node` is a knob-factory call."""
        if not isinstance(node, ast.Call):
            return None
        name = ctx.resolve_call(node.func)
        if name is None and isinstance(node.func, ast.Attribute):
            name = node.func.attr
        if name is None:
            return None
        return self.FACTORIES.get(name.rsplit(".", 1)[-1])

    _SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
               ast.Module)

    @classmethod
    def _scope(cls, node: ast.AST, ctx) -> Optional[ast.AST]:
        """Nearest enclosing function (or Module) of `node`."""
        n = ctx.parent(node)
        while n is not None and not isinstance(n, cls._SCOPES):
            n = ctx.parent(n)
        return n

    def begin_file(self, ctx) -> None:
        # Variables assigned from a factory call (`knobs =
        # server_knobs()`), keyed by ENCLOSING SCOPE: two functions may
        # bind the same name to different knob classes, so a file-wide
        # name map would resolve one of them wrongly (false FTL009 on a
        # valid knob read, or a masked real typo).
        self._vars = {}
        for n in ctx.nodes_of(ast.Assign):
            if len(n.targets) == 1 and isinstance(n.targets[0], ast.Name):
                cls = self._factory_class(n.value, ctx)
                if cls is not None:
                    scope = self._scope(n, ctx)
                    self._vars[(id(scope), n.targets[0].id)] = cls

    def _receiver_class(self, node: ast.expr, ctx) -> Optional[str]:
        if isinstance(node, ast.Name):
            # Same-scope binding first, then a module-level one (the
            # common shared `knobs = server_knobs()` constant).  No
            # other-function fallback: that is exactly the wrong-class
            # hazard the scoping exists to avoid.
            scope = self._scope(node, ctx)
            cls = self._vars.get((id(scope), node.id))
            if cls is None and not isinstance(scope, ast.Module):
                cls = self._vars.get((id(ctx.tree), node.id))
            return cls
        return self._factory_class(node, ctx)

    def _check(self, cls: str, attr: str, node: ast.AST, ctx) -> None:
        known = self._fields.get(cls)
        if not known or not self.NAME.match(attr) or attr in known:
            return
        ctx.report(self, node,
                   f"unknown knob {cls}.{attr}: no such field in "
                   "core/knobs.py (typo? getattr defaults would mask it "
                   "silently)")

    def visit(self, node: ast.AST, ctx) -> None:
        if isinstance(node, ast.Attribute):
            cls = self._receiver_class(node.value, ctx)
            if cls is not None:
                self._check(cls, node.attr, node, ctx)
            return
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id == "getattr" and len(node.args) >= 2:
            cls = self._receiver_class(node.args[0], ctx)
            arg = node.args[1]
            if cls is not None and isinstance(arg, ast.Constant) and \
                    isinstance(arg.value, str):
                self._check(cls, arg.value, node, ctx)


class StaleStateAcrossAwaitRule(Rule):
    """FTL010: a local snapshot of shared mutable state read after an
    await without re-binding — the exact hazard class Flow's ACTOR
    compiler makes a COMPILE ERROR (locals die at every ``wait()``
    unless declared ``state``; PAPER.md).

    In this port: inside an actor, a local whose defining RHS reads a
    MUTABLE ``self`` attribute (one its OWN class reassigns outside
    ``__init__`` — the epoch/backend/boundary state recovery and
    degradation swap out underneath a suspended actor; same-named
    attrs of other classes in the file don't cross-taint) or a
    module-level mutable container, where a def-use chain crosses an
    await/yield barrier (dataflow.py's crossed bit) with no re-binding
    in between.  Sanctioned escapes, mirroring Flow:

      * re-bind after the await (reaching-defs kills the stale fact);
      * ``# flowlint: state`` on the assignment line — the Python port
        of the ``state`` keyword: "this snapshot is MEANT to survive
        suspension" (e.g. folding one consistent view of a batch);
      * an immutable/copy snapshot — RHS is a call to a value-copying
        builtin (``list(self.x)``, ``int(self.v)``, ``sorted(...)``),
        a ``.join()``, or an eager comprehension (generator
        expressions stay flagged: they read the shared state lazily,
        after the await): taking an explicit copy IS the fix for torn
        reads;
      * an await result (``x = await f(self.y)``): the local holds
        post-suspension data, not a pre-await snapshot;
      * attributes a class only ever assigns in ``__init__`` are
        treated as immutable bindings and never flagged."""

    id = "FTL010"
    title = "stale shared-state snapshot read across await"

    SNAPSHOT_CALLS = frozenset({
        "bool", "bytes", "dict", "float", "frozenset", "int", "len",
        "list", "max", "min", "repr", "set", "sorted", "str", "sum",
        "tuple",
    })

    _FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)

    def begin_file(self, ctx) -> None:
        # Attributes assigned/deleted on `self` OUTSIDE __init__,
        # keyed by ENCLOSING CLASS: the "actually mutable" filter that
        # keeps init-frozen handles (self.id, self.interface) quiet —
        # and two classes sharing an attr NAME must not cross-taint
        # each other (the FTL009 scope lesson from PR 6).
        self._mutable_attrs: Dict[int, Set[str]] = {}
        self._mutable_globals: Set[str] = set()
        for node in ctx.nodes_of(ast.Assign, ast.AugAssign,
                                 ast.AnnAssign, ast.Delete):
            targets = list(node.targets) if isinstance(
                node, (ast.Assign, ast.Delete)) else [node.target]
            attrs = []
            while targets:          # incl. tuple-unpack/starred/chained
                t = targets.pop()
                if isinstance(t, (ast.Tuple, ast.List)):
                    targets.extend(t.elts)
                elif isinstance(t, ast.Starred):
                    targets.append(t.value)
                elif isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    attrs.append(t.attr)
            if attrs:
                fn = ctx.enclosing(node, self._FUNCS)
                if fn is not None and \
                        fn.name not in ("__init__", "__new__"):
                    cls = ctx.enclosing(node, (ast.ClassDef,))
                    self._mutable_attrs.setdefault(
                        id(cls) if cls else 0, set()).update(attrs)
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) and \
                    isinstance(node.value, (ast.Dict, ast.List, ast.Set)):
                self._mutable_globals.add(node.targets[0].id)

    def _shared_source(self, value: ast.expr,
                       mutable: Set[str]) -> Optional[str]:
        """Name of the shared mutable state `value` reads, or None."""
        for n in ast.walk(value):
            if isinstance(n, ast.Attribute) and \
                    isinstance(n.value, ast.Name) and \
                    n.value.id == "self" and \
                    isinstance(n.ctx, ast.Load) and \
                    n.attr in mutable:
                return f"self.{n.attr}"
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and n.id in self._mutable_globals:
                return n.id
        return None

    def begin_function(self, cfg, ctx) -> None:
        if not is_actor(cfg.func):
            return
        cls = ctx.class_stack[-1] if ctx.class_stack else None
        mutable = self._mutable_attrs.get(id(cls) if cls else 0, set())
        reported: Set = set()
        for name_node, node in cfg.loads:
            for dinfo, crossed in cfg.reaching(node, name_node.id):
                if not crossed or dinfo.is_param or dinfo.value is None:
                    continue
                key = (name_node.id, dinfo.idx)
                if key in reported:
                    continue
                if dinfo.lineno in ctx.state_lines:
                    continue        # declared state (Flow's keyword)
                v = dinfo.value
                if isinstance(v, ast.Await):
                    # `x = await f(self.y)`: x holds the await RESULT —
                    # post-suspension data, not a pre-await snapshot.
                    continue
                if isinstance(v, (ast.ListComp, ast.SetComp,
                                  ast.DictComp)):
                    # A comprehension EAGERLY copies what it iterates —
                    # same policy as set()/list() calls.  GeneratorExp
                    # stays flagged: it reads the shared state lazily,
                    # AFTER the await.
                    continue
                if isinstance(v, ast.Call) and (
                        (isinstance(v.func, ast.Name) and
                         v.func.id in self.SNAPSHOT_CALLS) or
                        (isinstance(v.func, ast.Attribute) and
                         v.func.attr == "join")):
                    continue        # explicit immutable/copy snapshot
                shared = self._shared_source(v, mutable)
                if shared is None:
                    continue
                reported.add(key)
                ctx.report(self, name_node,
                           f"local '{name_node.id}' snapshots shared "
                           f"mutable state ({shared}, assigned line "
                           f"{dinfo.lineno}) and is read after an await "
                           "without re-binding: the awaited suspension "
                           "may have changed it (recovery, degrade, "
                           "boundary move) — re-read it after the "
                           "await, take an explicit copy, or mark the "
                           "assignment `# flowlint: state` (Flow's "
                           "state keyword) if the snapshot is "
                           "intentional")


class AwaitHoldingLockRule(Rule):
    """FTL011: an actor awaiting — or blocking without a timeout —
    while holding a threading lock.

    A ``with self._lock:`` region containing an ``await`` parks the
    coroutine mid-critical-section: every OTHER thread that wants the
    lock (the supervisor's dispatch/fetch lanes, TCP handler threads)
    blocks for the whole suspension, and if the awaited completion is
    produced by one of those threads the process deadlocks.  Likewise,
    a timeout-less ``.result()``/``.wait()``/``.acquire()``/``.join()``
    /``.get()`` under a held lock stalls the one reactor thread
    unboundedly (the wait-without-timeout ROADMAP carry-over).  The
    lockset comes from the dataflow layer (meet = intersection, so a
    lock is "held" only when held on every path); ``async with`` locks
    are reactor-safe and never enter the lockset."""

    id = "FTL011"
    title = "await / unbounded wait while holding a lock"

    WAIT_METHODS = frozenset({"acquire", "get", "join", "result", "wait"})

    @staticmethod
    def _fmt(locks) -> str:
        return ", ".join(sorted(locks))

    def begin_function(self, cfg, ctx) -> None:
        if not is_actor(cfg.func):
            return
        for aw, node in cfg.awaits:
            held = cfg.lockset(node)
            if held:
                ctx.report(self, aw,
                           f"await while holding {self._fmt(held)}: the "
                           "lock stays held across the suspension — "
                           "worker threads contending for it stall for "
                           "the whole await (deadlock if they produce "
                           "the awaited result); copy what you need and "
                           "release before awaiting")
        for call, node in cfg.calls:
            f = call.func
            if not isinstance(f, ast.Attribute) or \
                    f.attr not in self.WAIT_METHODS:
                continue
            if call.args or any(kw.arg == "timeout"
                                for kw in call.keywords):
                continue
            held = cfg.lockset(node)
            if held:
                ctx.report(self, call,
                           f".{f.attr}() with no timeout while holding "
                           f"{self._fmt(held)}: an unbounded block in a "
                           "critical section wedges every contender "
                           "(and the reactor, in an actor) — pass "
                           "timeout= and handle expiry")


class LocksetDisciplineRule(Rule):
    """FTL012: lockset discipline — the static variant of Eraser
    (Savage et al.), scoped to classes that own or acquire a
    ``threading.Lock``.

    The PR-6 supervisor race class: ``_needs``/``_delta_bound`` were
    corrected under ``self._lock`` on the fetch lane but snapshotted
    lock-free on the dispatch path — caught by review, invisible to
    syntactic rules.  Here: within such a class, a ``self`` attribute
    WRITTEN at least once with a non-empty lockset (direct assignment,
    ``self.x[k] =``, or a container-mutator call like ``.append()``)
    must not be read or written at another site with an EMPTY lockset.
    ``__init__``/``__new__`` are exempt (object construction
    happens-before publication).  Since ISSUE 11 every access lockset
    is SEEDED interprocedurally before the discipline check: the meet
    of caller-held locksets for private methods whose callers are all
    known (the ``Tracer._roll`` "caller holds the lock" contract,
    previously a justified suppression, now proven), and lock
    PARAMETERS canonicalized to the one lock every caller passes.
    What this cannot prove (README): locks are keyed by source text,
    not object identity; cross-object guards are invisible; a
    lock-free access that is safe by a happens-before argument needs a
    justified suppression."""

    id = "FTL012"
    title = "lock-guarded attribute accessed with empty lockset"

    LOCK_FACTORIES = ("threading.Lock", "threading.RLock")

    class _ClassState:
        __slots__ = ("name", "path", "owns_lock", "acquired", "accesses")

        def __init__(self, name: str, path: str) -> None:
            self.name = name
            self.path = path
            self.owns_lock = False
            self.acquired: Set[str] = set()
            # attr -> [(kind, lockset, line, function name)]
            self.accesses: Dict[str, List[tuple]] = {}

    def __init__(self) -> None:
        # Keyed (path, class node id): reporting happens at
        # finish_program time, after the caller-held locksets exist.
        self._classes: Dict[tuple, LocksetDisciplineRule._ClassState] = {}

    def begin_file(self, ctx) -> None:
        for a in ctx.nodes_of(ast.Assign):
            if isinstance(a.value, ast.Call) and \
                    ctx.resolve_call(a.value.func) in self.LOCK_FACTORIES:
                cls = ctx.enclosing(a, (ast.ClassDef,))
                if cls is not None:
                    self._state_for(ctx, cls).owns_lock = True

    def _state_for(self, ctx, cls: ast.ClassDef) -> "_ClassState":
        key = (ctx.path, id(cls))
        state = self._classes.get(key)
        if state is None:
            state = self._classes[key] = self._ClassState(cls.name,
                                                          ctx.path)
        return state

    def begin_function(self, cfg, ctx) -> None:
        if not ctx.class_stack:
            return
        state = self._state_for(ctx, ctx.class_stack[-1])
        state.acquired |= {k for k in cfg.acquired_locks
                           if k.startswith("self.")}
        fname = cfg.func.name
        if fname in ("__init__", "__new__"):
            return
        for attr, node_ast, kind, cnode in cfg.self_accesses:
            if kind == "call" or lock_key(node_ast) is not None:
                continue            # methods / the lock objects themselves
            state.accesses.setdefault(attr, []).append(
                (kind, cfg.lockset(cnode),
                 getattr(node_ast, "lineno", 0), fname))

    def finish_program(self, program, report) -> None:
        for state in self._classes.values():
            if not (state.owns_lock or state.acquired):
                continue
            seeded: Dict[str, frozenset] = {}
            canons: Dict[str, Dict[str, str]] = {}
            for attr, accs in sorted(state.accesses.items()):
                eff = []
                for kind, locks, line, fname in accs:
                    qname = f"{state.name}.{fname}"
                    if qname not in seeded:
                        seeded[qname] = program.entry_locks(state.path,
                                                            qname)
                        canons[qname] = program.param_canon(state.path,
                                                            qname)
                    canon = canons[qname]
                    held = frozenset(canon.get(k, k) for k in locks) | \
                        seeded[qname]
                    eff.append((kind, held, line, fname))
                guarded = [a for a in eff if a[0] == "write" and a[1]]
                if not guarded:
                    continue
                locks = frozenset.intersection(*(a[1] for a in guarded))
                lock_txt = ", ".join(sorted(locks or guarded[0][1]))
                _gw_kind, _gl, gw_line, gw_fn = guarded[0]
                for kind, held, line, fname in eff:
                    if held:
                        continue
                    report(Finding(
                        self.id, state.path, line,
                        f"{state.name}.{attr} is written under "
                        f"{lock_txt} ({gw_fn}, line {gw_line}) but "
                        f"{'written' if kind == 'write' else 'read'}"
                        f" lock-free in {fname}: racy against "
                        "the guarded sites — take the lock, or "
                        "suppress with the happens-before "
                        "argument"))


class TransitiveBlockingRule(Rule):
    """FTL013: a call under a held threading lock whose callee — judged
    through the bottom-up summaries — reaches an unbounded block.

    FTL011 sees the ``.result()`` under the ``with self._lock:``; it
    cannot see ``with self._lock: self._drain()`` where ``_drain``
    (or something IT calls, any depth) does the timeout-less wait.
    The summaries make that one query: ``may_block(callee)``, LFP over
    the call graph, propagated through plain calls to sync callees
    only (an awaited callee's blocking is FTL011's await-under-lock
    finding at the caller; an un-awaited async call never runs).  The
    finding renders the full chain to the blocking site.  Findings
    fire only where the lock is LOCALLY held — deeper frames of the
    same chain would re-report the same hazard through their
    caller-held entry locksets, so those stay quiet.  A wrapper that
    FORWARDS a timeout (``fut.result(timeout=t)``) never enters the
    summary: timeouts are checked through wrappers for free.  Unknown
    callees contribute nothing (conservative: no invented findings)."""

    id = "FTL013"
    title = "transitive unbounded block while holding a lock"

    def finish_program(self, program, report) -> None:
        for rel, qname, fn, fid in program.iter_scanned_functions():
            canon = program.param_canon(rel, qname)
            for call, target in program.calls_with_targets(fid):
                line, _spec, locks, awaited, _largs = call
                if awaited or target is None or not locks:
                    continue
                tfn = program.graph.function(target)
                if tfn is None or tfn["async"]:
                    continue
                if not program.may_block(target):
                    continue
                held = ", ".join(sorted(canon.get(k, k) for k in locks))
                chain = " -> ".join(
                    [f"{rel}::{qname} line {line}"]
                    + program.block_chain(target))
                report(Finding(
                    self.id, rel, line,
                    f"call while holding {held} reaches an unbounded "
                    f"block: {chain} — the lock stays held across the "
                    "wait (deadlock if the completion needs the lock, "
                    "convoy otherwise); release before calling, or "
                    "bound the wait with timeout="))


class LockAliasRule(Rule):
    """FTL014: lock aliasing discipline.

    A single-valued alias (``lk = self._lock; with lk:``) now
    PARTICIPATES in lockset join/meet — the dataflow layer resolves it
    to the underlying attribute key, so FTL011/012/013 see through it
    (previously the alias silently dropped out of the lockset, the
    ``cs = self._x`` blind spot).  What this rule FLAGS is the residue
    static analysis cannot see through:

      * an alias whose reaching defs bind DIFFERENT locks (or a lock
        on one path and a non-lock on another) — its critical sections
        guard "some lock", which proves nothing;
      * a lock PARAMETER whose callers pass different locks — the
        callee's ``with lk:`` guards a different lock per callsite,
        so no cross-site discipline can be established.

    Both fixes are the same: name ONE lock (acquire the attribute
    directly, or split the function per lock)."""

    id = "FTL014"
    title = "ambiguous lock alias defeats lockset analysis"

    def begin_function(self, cfg, ctx) -> None:
        seen = set()
        for line, name, keys in cfg.alias_ambiguities:
            key = (name, tuple(keys))
            if key in seen:
                continue
            seen.add(key)
            ctx.report(self, line,
                       f"lock alias '{name}' may hold different locks "
                       f"here ({', '.join(keys)}): its critical "
                       "sections guard no ONE provable lock — bind the "
                       "alias to a single lock (or use the attribute "
                       "directly)")

    def finish_program(self, program, report) -> None:
        for rel, qname, pline, p, keymap in program.param_conflicts:
            if rel not in program.scanned:
                continue
            detail = "; ".join(f"{k} from {', '.join(v)}"
                               for k, v in sorted(keymap.items()))
            report(Finding(
                self.id, rel, pline,
                f"lock parameter '{p}' of {qname} receives a DIFFERENT "
                f"lock per caller ({detail}): no cross-site lockset "
                "discipline can be established through it — pass one "
                "lock, or split the function per lock"))


class LockOrderCycleRule(Rule):
    """FTL015: lock-ordering cycles — lockdep's discipline, static.

    Two threads taking the same two locks in opposite orders deadlock
    the moment their critical sections overlap; the hazard composes
    through calls (``with a: obj.m()`` where ``m`` — any depth down —
    takes ``b``, against a ``with b: ... a`` chain elsewhere), so no
    single-function rule can see it.  The engine builds a lock-order
    graph from the per-function acquisition summaries composed over the
    call graph and reports each elementary cycle with EVERY edge's
    acquisition chain as witness.

    Deliberately left out of FTL013 until lock identity became
    OBJECT-SENSITIVE (ISSUE 13): with locks keyed by source text, two
    instances sharing the attribute name ``self._lock`` alias, and
    every ``a.method()``/``b.method()`` cross-call between same-class
    instances reads as a self-cycle — object identities keyed by
    (class, attr, instance role) are what hold the noise floor at
    zero.  Reentrant same-identity nesting (RLock) is excluded: it is
    not an ordering between two locks."""

    id = "FTL015"
    title = "lock-ordering cycle (opposite acquisition orders deadlock)"

    def finish_program(self, program, report) -> None:
        for c in program.lock_cycles():
            report(Finding(self.id, c["path"], c["line"], c["message"]))


class PromiseProtocolRule(Rule):
    """FTL016: a locally created ``Promise``/``PromiseStream`` must be
    sent, broken, or escape on EVERY path.

    The ISSUE-10 bug class: a promise a deposed cluster controller left
    neither sent nor broken wedged its waiter until GC happened to run
    ``__del__`` — recovery hung on reference-counting luck.  The CFG
    path analysis (summaries.py ``_leaked_defs``) flags a creation a
    normal exit can be reached from with the promise neither resolved
    (``send``/``send_error``/``break_promise``/``close``) nor escaped
    (returned, stored, passed on — ownership moved); reads
    (``get_future``/``is_set``/``pop``/``empty``) transfer nothing.
    Raise paths are exempt (unwinding drops the local deterministically
    in CPython); the hazard is the branch that KEEPS RUNNING with the
    promise forgotten.  Interprocedural: a promise obtained from an
    in-package FACTORY (``p = make_reply()``) is tracked through the
    returns-instance summary exactly like a direct construction."""

    id = "FTL016"
    title = "promise neither resolved nor escaped on every path"

    PROMISE_CLASSES = frozenset({"Promise", "PromiseStream"})

    def finish_program(self, program, report) -> None:
        for rel, qname, fn, fid in program.iter_scanned_functions():
            for line, name, texpr in fn.get("leaks", ()):
                t = program.resolve_type(rel, fn.get("cls"), texpr)
                if t is None or t[1] not in self.PROMISE_CLASSES:
                    continue
                report(Finding(
                    self.id, rel, line,
                    f"{t[1]} '{name}' ({qname}) reaches a function exit "
                    "neither sent, broken, nor handed off on some path: "
                    "its waiter then hangs until GC luck breaks it (the "
                    "deposed-CC long-poll bug class) — send/send_error/"
                    "break_promise it on every path, or hand it off "
                    "explicitly"))


class ContainerOwnershipRule(Rule):
    """FTL017: a promise parked in a container field nobody drains.

    FTL016 treats ANY escape as "ownership moved"; this rule closes the
    container half of that trust (ISSUE 20).  Pushing a Promise into
    ``self.<field>`` (append/add/heappush/put/subscript-store) is only
    a sanctioned hand-off if some in-package function DRAINS that field
    — extracts elements (pop/popleft/heappop/subscript/iterate) and
    resolves them (send/send_error/break_promise), possibly through a
    helper the element is forwarded to (the producer/consumer
    summaries composed bottom-up in summaries.py's ownership
    fixpoint).  A registry nobody drains is the deposed-CC bug class
    at scale: every parked waiter hangs until GC luck.  Field identity
    is the allocation-site owner through the MRO (like lock
    identities), so a drain in Base sanctions parks in Sub.
    ``# flowlint: owned -- <why>`` on the CREATION line is the
    justified escape hatch (a registry drained outside the package's
    sight).  Conservative directions: an unresolvable park type or
    field contributes nothing; ANY in-package drain of the field
    sanctions it (may-analysis on the consumer side)."""

    id = "FTL017"
    title = "promise parked in a container field nobody drains"

    PROMISE_CLASSES = PromiseProtocolRule.PROMISE_CLASSES

    def finish_program(self, program, report) -> None:
        seen: Set[tuple] = set()
        for rel, qname, fn, fid in program.iter_scanned_functions():
            cls = fn.get("cls")
            if cls is None:
                continue        # parks are self-container stores only
            for line, attr, texpr in fn.get("parks", ()):
                t = program.resolve_type(rel, cls, texpr)
                if t is None or t[1] not in self.PROMISE_CLASSES:
                    continue
                if program.field_drained(rel, cls, attr) or \
                        program.owned_line(rel, line):
                    continue
                ident = program.field_identity(rel, cls, attr)
                key = (rel, line, ident)
                if key in seen:
                    continue
                seen.add(key)
                report(Finding(
                    self.id, rel, line,
                    f"{t[1]} created here ({qname}) is parked in "
                    f"'self.{attr}' but no in-package function drains "
                    f"{ident[1]}.{attr} (pop/iterate + send/send_error/"
                    "break_promise on the elements) — every parked "
                    "waiter hangs until GC luck (the deposed-CC bug "
                    "class); drain the registry, or annotate the "
                    "creation with '# flowlint: owned -- <why>'"))


class WireEvolutionRule(Rule):
    """FTL018: wire-evolution hazards on golden-frozen structs.

    PRs 14-16 froze the hot-RPC wire image behind sha256 goldens, with
    ``_ELIDE_DEFAULT_FIELDS`` (a field elided from the frame while at
    its default) and ``_CODEC_VERSIONS`` (an explicit format bump) as
    the two sanctioned evolution paths.  One field grafted outside
    them silently breaks the mixed-version rollout: the old decoder
    rejects the new frame mid-upgrade.  This rule cross-references the
    ``_GOLDEN_FROZEN_FIELDS`` registry against every scanned
    ``@dataclass`` field list:

      * a field beyond the frozen list that is neither elided nor
        version-gated -> finding at the field's line;
      * a sanctioned added field with NO default -> finding (the
        decode path is not format-transparent: a frame without the
        field cannot fill it);
      * a frozen field missing from the dataclass, or an elide entry
        naming a nonexistent field -> drift finding at the class line.

    ``reply`` fields never travel (serde's ``_iter_fields`` skips
    them) and are skipped here too.  A struct name defined in more
    than one scanned file is ambiguous and contributes nothing (the
    silent direction)."""

    id = "FTL018"
    title = "field grafted onto a golden-frozen wire struct"

    REGISTRIES = ("_GOLDEN_FROZEN_FIELDS", "_ELIDE_DEFAULT_FIELDS",
                  "_CODEC_VERSIONS")
    SKIP_FIELDS = frozenset({"reply"})

    def __init__(self) -> None:
        self._registries: Dict[str, dict] = {}
        # struct -> [(path, class line, fields, class-line suppressed)]
        # with fields = [(name, has_default, line, suppressed)].
        self._structs: Dict[str, List[tuple]] = {}

    def _collect_registry(self, name: str, value: ast.expr) -> None:
        if not isinstance(value, ast.Dict):
            return
        table = self._registries.setdefault(name, {})
        for k, v in zip(value.keys, value.values):
            if not (isinstance(k, ast.Constant) and
                    isinstance(k.value, str)):
                continue
            if isinstance(v, (ast.Tuple, ast.List)):
                elts = [e.value for e in v.elts
                        if isinstance(e, ast.Constant) and
                        isinstance(e.value, str)]
                table.setdefault(k.value, elts)
            elif isinstance(v, ast.Constant) and \
                    isinstance(v.value, int):
                table.setdefault(k.value, v.value)

    @staticmethod
    def _is_dataclass(node: ast.ClassDef) -> bool:
        for d in node.decorator_list:
            f = d.func if isinstance(d, ast.Call) else d
            name = f.id if isinstance(f, ast.Name) else \
                f.attr if isinstance(f, ast.Attribute) else None
            if name == "dataclass":
                return True
        return False

    def visit(self, node: ast.AST, ctx) -> None:
        if isinstance(node, ast.Assign) and ctx.at_module_level and \
                len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id in self.REGISTRIES:
            self._collect_registry(node.targets[0].id, node.value)
        elif isinstance(node, ast.ClassDef) and self._is_dataclass(node):
            fields = []
            for stmt in node.body:
                if not (isinstance(stmt, ast.AnnAssign) and
                        isinstance(stmt.target, ast.Name)):
                    continue
                ann = stmt.annotation
                head = ann.value if isinstance(ann, ast.Subscript) \
                    else ann
                hname = head.id if isinstance(head, ast.Name) else \
                    head.attr if isinstance(head, ast.Attribute) else None
                if hname == "ClassVar":
                    continue        # not a wire field
                fields.append((stmt.target.id, stmt.value is not None,
                               stmt.lineno,
                               ctx.is_suppressed(self.id, stmt.lineno)))
            self._structs.setdefault(node.name, []).append(
                (ctx.path, node.lineno, fields,
                 ctx.is_suppressed(self.id, node.lineno)))

    def finish(self, report) -> None:
        golden = self._registries.get("_GOLDEN_FROZEN_FIELDS")
        if not golden:
            return                  # no frozen registry in the scan
        elide = self._registries.get("_ELIDE_DEFAULT_FIELDS", {})
        versions = self._registries.get("_CODEC_VERSIONS", {})
        for struct in sorted(golden):
            frozen = golden[struct]
            defs = self._structs.get(struct, [])
            if not isinstance(frozen, list) or len(defs) != 1:
                continue
            path, cls_line, fields, cls_sup = defs[0]
            frozen_set = set(frozen)
            elided = set(elide.get(struct) or ())
            gated = isinstance(versions.get(struct), int) and \
                versions[struct] >= 2
            names: Set[str] = set()
            for fname, has_default, line, sup in fields:
                if fname in self.SKIP_FIELDS:
                    continue
                names.add(fname)
                if fname in frozen_set or sup:
                    continue
                if fname not in elided and not gated:
                    report(Finding(
                        self.id, path, line,
                        f"field '{fname}' grafted onto golden-frozen "
                        f"wire struct {struct} with no "
                        "_ELIDE_DEFAULT_FIELDS registration and no "
                        "_CODEC_VERSIONS bump — the previous release's "
                        "decoder rejects the new frame mid-rollout; "
                        "elide it at its default, or version-gate the "
                        "codec"))
                elif not has_default:
                    report(Finding(
                        self.id, path, line,
                        f"added field '{fname}' on golden-frozen "
                        f"{struct} has no default — the decode path is "
                        "not format-transparent (a frame without the "
                        "field cannot fill it); give it a wire-absent "
                        "default"))
            if cls_sup:
                continue
            for missing in sorted(frozen_set - names):
                report(Finding(
                    self.id, path, cls_line,
                    f"golden-frozen field '{missing}' of {struct} no "
                    "longer exists on the dataclass — frames encoded "
                    "by the frozen format no longer decode; restore "
                    "the field or re-freeze the goldens deliberately"))
            for ghost in sorted(elided - names):
                report(Finding(
                    self.id, path, cls_line,
                    f"_ELIDE_DEFAULT_FIELDS names '{ghost}' on "
                    f"{struct}, which has no such field — registry "
                    "drift; drop the stale entry"))


def make_rules() -> List[Rule]:
    """Fresh rule instances — ALWAYS construct per run: rules carry
    cross-file state (TraceEventRule._by_type), so sharing instances
    across Analyzer runs would accumulate callsites and emit phantom
    schema-drift findings."""
    return [WallClockRule(), UnawaitedCoroutineRule(),
            BroadExceptInActorRule(), StrKeyRule(), SetIterationRule(),
            BlockingInActorRule(), TraceEventRule(),
            HardcodedTunableRule(), KnobNameRule(),
            StaleStateAcrossAwaitRule(), AwaitHoldingLockRule(),
            LocksetDisciplineRule(), TransitiveBlockingRule(),
            LockAliasRule(), LockOrderCycleRule(), PromiseProtocolRule(),
            ContainerOwnershipRule(), WireEvolutionRule()]
