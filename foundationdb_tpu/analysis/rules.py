"""flowlint rules FTL001..FTL008.

Every rule is grounded in a bug class this repo has actually hit (see
ISSUE/PR history): wall-clock reads that break unseed reproduction,
str keys that crashed ``_pack_end``, broad excepts that can swallow
``ActorCancelled``, tunables hardcoded outside core/knobs.py.

Adding a rule: subclass ``engine.Rule``, set ``id``/``title``, implement
``visit`` (called once per AST node — never walk the tree yourself;
per-file prep goes in ``begin_file``, cross-file checks in ``finish``),
append it in ``make_rules()``, document it in README's rule table, and
add a known-bad fixture under tests/fixtures/flowlint/ with
``# expect: FTLnNN:<line>`` markers.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from .engine import Finding, Rule

# Modules that are real-mode-only BY CONSTRUCTION: never imported on a
# simulation code path, so wall-clock/entropy/set-order hazards in them
# cannot perturb a seeded run.  Mirrors (and extends, for the
# process-supervisor tool) testing/tester.py NondeterminismAudit
# ALLOWED_FILES — the runtime audit and the static pass must agree on
# what counts as sanctioned.
REAL_ONLY_MODULES = (
    "core/rng.py",          # seeds the nondeterministic id gen by design
    "core/scheduler.py",    # real-mode epoch reads the monotonic clock
    "core/threadpool.py",   # real threads only
    "core/profiler.py",     # wall-time slow-task detection
    "rpc/real_network.py",  # real sockets
    "server/real_fs.py",    # real disk
    "server/fdbserver.py",  # real-mode process entry (EventLoop(sim=False));
                            # per-incarnation entropy seeding is its job
    "tools/fdbmonitor.py",  # process supervisor: spawns real fdbservers
)


def _sim_reachable(path: str) -> bool:
    return not path.endswith(REAL_ONLY_MODULES)


class WallClockRule(Rule):
    """FTL001: wall-clock / OS-entropy calls in sim-reachable modules.

    The static complement of testing/tester.py's NondeterminismAudit:
    the audit only sees code paths a given seed executes; this rule sees
    every line.  ``random.Random(seed)`` is allowed (a seeded instance
    is deterministic); module-level ``random.*`` draws shared
    interpreter state and is not."""

    id = "FTL001"
    title = "wall-clock/entropy call in sim-reachable module"

    CLOCKS = {"time.time", "time.time_ns", "time.monotonic",
              "time.monotonic_ns", "time.perf_counter",
              "time.perf_counter_ns"}

    def visit(self, node: ast.AST, ctx) -> None:
        if not isinstance(node, ast.Call) or not _sim_reachable(ctx.path):
            return
        name = ctx.resolve_call(node.func)
        if name is None:
            return
        if name in self.CLOCKS:
            ctx.report(self, node,
                       f"{name}() in sim-reachable code: route through "
                       "core.scheduler.now() (virtual time) so seeded "
                       "runs replay identically")
        elif name == "os.urandom" or name == "random.SystemRandom":
            ctx.report(self, node,
                       f"{name} is OS entropy: draw from "
                       "core.rng.deterministic_random() instead")
        elif name.startswith("random.") and name != "random.Random":
            ctx.report(self, node,
                       f"module-level {name}() draws shared interpreter "
                       "RNG state: use core.rng.deterministic_random() "
                       "or a seeded random.Random instance")


class UnawaitedCoroutineRule(Rule):
    """FTL002: a coroutine created and immediately discarded.

    ``foo()`` as a bare statement where ``foo`` is an ``async def`` in
    the same file builds a coroutine object that never runs (Python only
    warns at GC time, and only if the warning isn't swallowed).  The
    call must be awaited or handed to ``spawn()``.  A name defined BOTH
    async and sync in the file (e.g. fdb_api.py's FDBDatabase.set
    convenience vs FDBTransaction.set) is ambiguous at a callsite and
    not flagged."""

    id = "FTL002"
    title = "un-awaited coroutine call"

    def begin_file(self, ctx) -> None:
        async_defs: Set[str] = set()
        sync_defs: Set[str] = set()
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.AsyncFunctionDef):
                async_defs.add(n.name)
            elif isinstance(n, ast.FunctionDef):
                sync_defs.add(n.name)
        self._async_defs = async_defs - sync_defs

    def visit(self, node: ast.AST, ctx) -> None:
        if not isinstance(node, ast.Expr) or \
                not isinstance(node.value, ast.Call):
            return
        func = node.value.func
        name = None
        if isinstance(func, ast.Name):
            name = func.id
        elif isinstance(func, ast.Attribute):
            name = func.attr
        if name in self._async_defs:
            ctx.report(self, node,
                       f"coroutine {name}() is created but never awaited "
                       "(await it, or hand it to spawn())")


class BroadExceptInActorRule(Rule):
    """FTL003: a handler inside an actor that can swallow cancellation.

    ``ActorCancelled`` derives from ``BaseException`` (core/error.py) —
    exactly so that ``except Exception`` is cancellation-safe, which is
    why this rule does NOT flag it.  What it flags, inside ``async
    def``: bare ``except:`` and ``except BaseException`` handlers that
    neither re-raise nor delegate to an ``on_error()`` retry helper
    (whose contract is to re-raise non-retryables, incl. cancellation)."""

    id = "FTL003"
    title = "broad except in actor can swallow ActorCancelled"

    @staticmethod
    def _catches_base(h: ast.ExceptHandler) -> bool:
        if h.type is None:
            return True
        names = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
        return any(isinstance(e, ast.Name) and e.id == "BaseException"
                   for e in names)

    @staticmethod
    def _handles_cancellation(h: ast.ExceptHandler) -> bool:
        for n in ast.walk(h):
            if isinstance(n, ast.Raise):
                return True
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "on_error":
                return True
        return False

    def visit(self, node: ast.AST, ctx) -> None:
        if not isinstance(node, ast.ExceptHandler) or not ctx.in_async:
            return
        if self._catches_base(node) and not self._handles_cancellation(node):
            what = "bare except:" if node.type is None \
                else "except BaseException"
            ctx.report(self, node,
                       f"{what} in an actor swallows ActorCancelled: "
                       "re-raise (bare `raise`), narrow to Exception, or "
                       "delegate to on_error()")


class StrKeyRule(Rule):
    """FTL004: a str literal flowing into a bytes-key API.

    The ``_pack_end`` bug class (PR 2/4): FDB keys and values are bytes;
    a str slips through dynamic paths until pack time, sometimes only on
    rarely-taken branches.  Flags str literals (incl. f-strings and
    ``"a" + x`` concatenations) at key/value positions of transaction
    methods and pack helpers.  Plain ``.get()`` is deliberately NOT
    checked: dict.get with str keys is pervasive and the noise would
    drown the signal; ``.set()`` is only checked at arity >= 2 or with
    a kv-style keyword (key=/value=/...) — signal objects like
    ``shutdown_signal.set("kill")`` are unary and keyword-free."""

    id = "FTL004"
    title = "str literal flows into bytes-key API"

    # method -> positional arg indices that must be bytes
    KEY_POSITIONS = {"set": (0, 1), "clear": (0, 1), "clear_range": (0, 1),
                     "get_range": (0, 1), "get_key": (0,), "watch": (0,),
                     "add_read_conflict_range": (0, 1),
                     "add_write_conflict_range": (0, 1),
                     "atomic_op": (1,)}
    KEY_KEYWORDS = ("key", "begin", "end", "value")
    PACK_HELPERS = ("_pack", "_pack_end")

    @classmethod
    def _strish(cls, node: ast.expr) -> bool:
        if isinstance(node, ast.Constant):
            return isinstance(node.value, str)
        if isinstance(node, ast.JoinedStr):
            return True
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            return cls._strish(node.left) or cls._strish(node.right)
        return False

    def visit(self, node: ast.AST, ctx) -> None:
        if not isinstance(node, ast.Call):
            return
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None)
        if name in self.PACK_HELPERS:
            positions = (0,)
        elif name in self.KEY_POSITIONS:
            positions = self.KEY_POSITIONS[name]
            if name == "set" and len(node.args) < 2 and not any(
                    kw.arg in self.KEY_KEYWORDS for kw in node.keywords):
                return      # unary .set() is a signal/flag, not a kv
                #             write; a kv-ish keyword (key=/value=)
                #             re-qualifies it as one
        else:
            return
        for i in positions:
            if i < len(node.args) and self._strish(node.args[i]):
                ctx.report(self, node,
                           f"str literal passed to {name}() arg {i}: keys "
                           "and values are bytes (b'...', or .encode())")
                return
        for kw in node.keywords:
            if kw.arg in self.KEY_KEYWORDS and self._strish(kw.value):
                ctx.report(self, node,
                           f"str literal passed to {name}({kw.arg}=...): "
                           "keys and values are bytes (b'...', or "
                           ".encode())")
                return


class SetIterationRule(Rule):
    """FTL005: iterating a set in sim-reachable code.

    str hashing is salted by PYTHONHASHSEED, so set iteration order is
    process-dependent — the exact hazard that breaks cross-process
    unseed reproduction (ROADMAP chaos follow-up).  Flags ``for``
    loops / comprehensions whose iterable is syntactically a set (set
    literal, set comprehension, ``set(...)``/``frozenset(...)`` call);
    wrap in ``sorted()`` to fix.  Dict iteration is NOT flagged:
    Python dicts are insertion-ordered, hence deterministic."""

    id = "FTL005"
    title = "set iteration order is PYTHONHASHSEED-dependent"

    @staticmethod
    def _is_set_expr(node: ast.expr) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        return isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Name) and \
            node.func.id in ("set", "frozenset")

    def _check_iter(self, it: ast.expr, ctx) -> None:
        if self._is_set_expr(it):
            ctx.report(self, it,
                       "iteration over a set: order depends on "
                       "PYTHONHASHSEED for str elements — wrap in "
                       "sorted() (deterministic) before iterating")

    def visit(self, node: ast.AST, ctx) -> None:
        if not _sim_reachable(ctx.path):
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            self._check_iter(node.iter, ctx)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                self._check_iter(gen.iter, ctx)


class BlockingInActorRule(Rule):
    """FTL006: a blocking call inside an actor.

    Actors interleave only at awaits on ONE reactor thread: a
    ``time.sleep`` stalls every other actor (and under sim stalls
    *virtual* time for wall time), and direct ``open()``/``os.open``
    bypasses sim_fs — the simulated power-loss/fault machinery never
    sees that file.  Use ``core.scheduler.delay()`` and the worker's
    filesystem handle (``sim_fs``/``real_fs``)."""

    id = "FTL006"
    title = "blocking call inside actor"

    BLOCKING = {"time.sleep": "core.scheduler.delay() (non-blocking, "
                              "virtual under sim)",
                "open": "the role's filesystem handle (sim_fs/real_fs)",
                "os.open": "the role's filesystem handle (sim_fs/real_fs)"}

    def visit(self, node: ast.AST, ctx) -> None:
        if not isinstance(node, ast.Call) or not ctx.in_async or \
                not _sim_reachable(ctx.path):
            return
        name = ctx.resolve_call(node.func)
        if name in self.BLOCKING:
            ctx.report(self, node,
                       f"blocking {name}() inside an actor: use "
                       f"{self.BLOCKING[name]}")


class TraceEventRule(Rule):
    """FTL007: TraceEvent naming + cross-module schema drift (absorbed
    from scripts/check_trace_events.py, which remains as a thin shim).

    1. every ``TraceEvent("Name")`` literal must be UpperCamelCase;
    2. no two modules may emit the same Type with different *chained*
       detail schemas — a Type is a contract for trace consumers.
       Details added through a variable are invisible statically and
       make that callsite "open" (exempt from the comparison)."""

    id = "FTL007"
    title = "TraceEvent naming / schema drift"

    CAMEL = re.compile(r"^[A-Z][A-Za-z0-9]*$")
    # Established cross-role correlation events whose Location field IS
    # the schema discriminator (emitted via trace_batch_event).
    SCHEMA_ALLOWLIST = {"CommitDebug", "TransactionDebug"}

    def __init__(self) -> None:
        # type -> {module: [keyset or None per callsite]}
        self._by_type: Dict[str, Dict[str, List[Optional[frozenset]]]] = {}

    @staticmethod
    def _chain(call: ast.Call):
        """For the OUTERMOST call of a TraceEvent(...).detail(...)...
        chain, return (type_name, chained detail keys or None when a key
        is not a literal); None for calls that are not such a chain."""
        keys: Set[str] = set()
        opaque = False
        node = call
        while True:
            f = node.func
            if isinstance(f, ast.Attribute):
                if f.attr == "detail":
                    if node.args and isinstance(node.args[0], ast.Constant) \
                            and isinstance(node.args[0].value, str):
                        keys.add(node.args[0].value)
                    else:
                        opaque = True
                elif f.attr not in ("error", "log"):
                    return None
                if not isinstance(f.value, ast.Call):
                    return None
                node = f.value
                continue
            if isinstance(f, ast.Name) and f.id == "TraceEvent":
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    return node.args[0].value, \
                        (None if opaque else frozenset(keys))
                return None
            return None

    def visit(self, node: ast.AST, ctx) -> None:
        if not isinstance(node, ast.Call):
            return
        # Only the outermost call of each chain: skip a Call that is the
        # receiver of another attribute call.
        parent = ctx.parent(node)
        if isinstance(parent, ast.Attribute):
            grand = ctx.parent(parent)
            if isinstance(grand, ast.Call) and grand.func is parent:
                return
        got = self._chain(node)
        if got is None:
            return
        type_name, keys = got
        if not self.CAMEL.match(type_name):
            ctx.report(self, node,
                       f"TraceEvent type {type_name!r} is not "
                       "UpperCamelCase")
        # A suppressed callsite (per-line or disable-file) must not
        # join the cross-file schema comparison either — finish()-time
        # findings have no line of their own, so this is the only place
        # the suppression can take effect for drift.
        if ctx.is_suppressed(self.id, getattr(node, "lineno", 0)):
            return
        self._by_type.setdefault(type_name, {}).setdefault(
            ctx.path, []).append(keys)

    def finish(self, report) -> None:
        for type_name, modules in sorted(self._by_type.items()):
            if len(modules) < 2 or type_name in self.SCHEMA_ALLOWLIST:
                continue
            schemas = {}
            for mod, keysets in modules.items():
                if any(k is None for k in keysets):
                    continue        # opaque callsite: module is "open"
                schemas[mod] = frozenset().union(*keysets)
            if len(set(schemas.values())) > 1:
                detail = "; ".join(
                    f"{m}: {sorted(s) or ['<none>']}"
                    for m, s in sorted(schemas.items()))
                report(Finding(
                    self.id, sorted(modules)[0], 0,
                    f"TraceEvent type {type_name!r} emitted from "
                    f"{len(modules)} modules with different detail "
                    f"schemas: {detail}"))


class HardcodedTunableRule(Rule):
    """FTL008: a hardcoded float tunable in a server/conflict hot path.

    Timeouts, cadences, and latency magnitudes belong in core/knobs.py:
    knobs are overridable at startup, BUGGIFY-randomizable per seed, and
    dynamically updatable through the config DB — a module-level float
    constant is none of those.  Int constants are NOT flagged: in this
    codebase they are format/protocol constants (magics, page sizes,
    opcode ids, lane counts), not tunables."""

    id = "FTL008"
    title = "hardcoded tunable should route through core/knobs.py"

    NAME = re.compile(r"^_?[A-Z][A-Z0-9_]*$")
    HOT_PATHS = ("server/", "conflict/")

    @staticmethod
    def _float_value(node: ast.expr):
        if isinstance(node, ast.UnaryOp) and \
                isinstance(node.op, ast.USub):
            node = node.operand
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, float):
            return node.value
        return None

    def visit(self, node: ast.AST, ctx) -> None:
        if not isinstance(node, ast.Assign) or not ctx.at_module_level:
            return
        if not any(h in ctx.path for h in self.HOT_PATHS):
            return
        if len(node.targets) != 1 or \
                not isinstance(node.targets[0], ast.Name):
            return
        name = node.targets[0].id
        value = self._float_value(node.value)
        if self.NAME.match(name) and value is not None:
            ctx.report(self, node,
                       f"hardcoded tunable {name} = {value!r}: move it "
                       "to core/knobs.py (overridable, "
                       "BUGGIFY-randomizable, dynamic-knob updatable)")


class KnobNameRule(Rule):
    """FTL009: a knob attribute name that does not exist on its knob
    class — the typo class dynamic knob plumbing makes silent.

    ``knobs.CONFLICT_DEVICE_TIMEOUT_SEC`` raises AttributeError only on
    the (possibly rare) path that reads it, and ``getattr(knobs, "NAME",
    default)`` never raises at all — a misspelled knob quietly pins the
    default forever.  The rule audits every ALL-CAPS attribute access
    (and getattr with a literal name) on values produced by the knob
    factories (``server_knobs()`` / ``client_knobs()``) against the
    field set statically extracted from core/knobs.py's ``self.NAME =``
    assignments, so the check needs no import of the linted code."""

    id = "FTL009"
    title = "unknown knob name (typo against the knob class field set)"

    FACTORIES = {"server_knobs": "ServerKnobs",
                 "client_knobs": "ClientKnobs"}
    NAME = re.compile(r"^[A-Z][A-Z0-9_]*$")

    def __init__(self, knobs_source: Optional[str] = None) -> None:
        self._fields = self._load_fields(knobs_source)
        self._vars: Dict[str, str] = {}

    @staticmethod
    def _load_fields(src_path: Optional[str] = None) -> Dict[str, Set[str]]:
        """{knob class -> field names} from core/knobs.py's AST (every
        ``self.NAME = ...`` in each class body)."""
        import os
        if src_path is None:
            src_path = os.path.join(os.path.dirname(__file__), os.pardir,
                                    "core", "knobs.py")
        fields: Dict[str, Set[str]] = {}
        try:
            with open(src_path, "r", encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError, ValueError):
            return fields          # no knob source: rule reports nothing
        for node in tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            names: Set[str] = set()
            for n in ast.walk(node):
                if isinstance(n, ast.Assign):
                    for t in n.targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self":
                            names.add(t.attr)
            fields[node.name] = names
        return fields

    def _factory_class(self, node: ast.expr, ctx) -> Optional[str]:
        """Knob class name when `node` is a knob-factory call."""
        if not isinstance(node, ast.Call):
            return None
        name = ctx.resolve_call(node.func)
        if name is None and isinstance(node.func, ast.Attribute):
            name = node.func.attr
        if name is None:
            return None
        return self.FACTORIES.get(name.rsplit(".", 1)[-1])

    _SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
               ast.Module)

    @classmethod
    def _scope(cls, node: ast.AST, ctx) -> Optional[ast.AST]:
        """Nearest enclosing function (or Module) of `node`."""
        n = ctx.parent(node)
        while n is not None and not isinstance(n, cls._SCOPES):
            n = ctx.parent(n)
        return n

    def begin_file(self, ctx) -> None:
        # Variables assigned from a factory call (`knobs =
        # server_knobs()`), keyed by ENCLOSING SCOPE: two functions may
        # bind the same name to different knob classes, so a file-wide
        # name map would resolve one of them wrongly (false FTL009 on a
        # valid knob read, or a masked real typo).
        self._vars = {}
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name):
                cls = self._factory_class(n.value, ctx)
                if cls is not None:
                    scope = self._scope(n, ctx)
                    self._vars[(id(scope), n.targets[0].id)] = cls

    def _receiver_class(self, node: ast.expr, ctx) -> Optional[str]:
        if isinstance(node, ast.Name):
            # Same-scope binding first, then a module-level one (the
            # common shared `knobs = server_knobs()` constant).  No
            # other-function fallback: that is exactly the wrong-class
            # hazard the scoping exists to avoid.
            scope = self._scope(node, ctx)
            cls = self._vars.get((id(scope), node.id))
            if cls is None and not isinstance(scope, ast.Module):
                cls = self._vars.get((id(ctx.tree), node.id))
            return cls
        return self._factory_class(node, ctx)

    def _check(self, cls: str, attr: str, node: ast.AST, ctx) -> None:
        known = self._fields.get(cls)
        if not known or not self.NAME.match(attr) or attr in known:
            return
        ctx.report(self, node,
                   f"unknown knob {cls}.{attr}: no such field in "
                   "core/knobs.py (typo? getattr defaults would mask it "
                   "silently)")

    def visit(self, node: ast.AST, ctx) -> None:
        if isinstance(node, ast.Attribute):
            cls = self._receiver_class(node.value, ctx)
            if cls is not None:
                self._check(cls, node.attr, node, ctx)
            return
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id == "getattr" and len(node.args) >= 2:
            cls = self._receiver_class(node.args[0], ctx)
            arg = node.args[1]
            if cls is not None and isinstance(arg, ast.Constant) and \
                    isinstance(arg.value, str):
                self._check(cls, arg.value, node, ctx)


def make_rules() -> List[Rule]:
    """Fresh rule instances — ALWAYS construct per run: rules carry
    cross-file state (TraceEventRule._by_type), so sharing instances
    across Analyzer runs would accumulate callsites and emit phantom
    schema-drift findings."""
    return [WallClockRule(), UnawaitedCoroutineRule(),
            BroadExceptInActorRule(), StrKeyRule(), SetIterationRule(),
            BlockingInActorRule(), TraceEventRule(),
            HardcodedTunableRule(), KnobNameRule()]
