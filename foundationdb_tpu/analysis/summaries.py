"""Bottom-up function summaries for flowlint (ISSUE 11).

The dataflow layer answers questions about ONE function; this module
answers the cross-function ones the remaining hazard shapes need:

  * **may-block-unbounded** — does calling this (sync) function ever
    reach a timeout-less ``.result()/.wait()/.join()/.get()/.acquire()``
    or ``time.sleep`` through any chain of plain calls?  (FTL013: a
    callsite under a held lock reaching such a function is a
    deadlock/convoy hazard; the finding renders the chain.)
  * **set-valued return** — does this function always return a set,
    judging returned calls through callee summaries (FTL005 through
    arbitrarily deep in-package chains; recursion converges via a
    greatest-fixpoint over the call-graph SCCs)?
  * **may-read-wall-clock** — does this REAL_ONLY-module function reach
    an unguarded wall-clock/entropy read (FTL001 at sim-reachable
    callsites: the static verification of the "never imported on a sim
    path" construction)?
  * **caller-held locksets** — for a private function every caller of
    which is known, the MEET (intersection) of the locksets held at
    all its callsites: FTL012 seeds each function's entry lockset with
    it, so ``Tracer._roll``'s "caller holds the lock" contract is
    PROVEN instead of suppressed.
  * **lock-parameter unification** — a parameter used in lock position
    is unified with the one concrete lock every caller passes (it then
    participates in FTL012's join/meet); callers that disagree are an
    FTL014 finding.

Facts are extracted per FILE (one dict per file, JSON-safe) and cached
on disk keyed by content hash, so ``--changed`` runs reuse the whole
unchanged program's facts without re-parsing; the cross-file passes
(call-graph resolution + fixpoints) are cheap and recomputed per run.
Summary composition is the RacerD/Infer shape: intraprocedural facts
feed compact per-function summaries, summaries compose bottom-up over
SCCs in reverse topological order (here: monotone worklist fixpoints,
which converge identically and need no explicit SCC enumeration), and
rules consume summaries instead of re-analyzing callees.

Conservative unknown-callee handling: an unresolvable call contributes
NO summary effects (never invents a finding), and its terminal name
disqualifies same-named functions from the caller-held seeding (an
invisible caller might hold no lock — the direction that would
SILENCE a real race is the one that needs all callers known).
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .callgraph import (CallGraph, base_spec, build_import_tables,
                        call_spec, module_name_for, resolve_external)
from .dataflow import FunctionDataflow, is_set_expr, lock_key
from .engine import _suppressions, iter_py_files, topmost_package
from .rules import AwaitHoldingLockRule, WallClockRule, _sim_reachable

CACHE_VERSION = 1

# THE wait-method and clock predicates live on the rules (FTL011 /
# FTL001); the summaries import them so the transitive reach can never
# drift from the direct checks.
WAIT_METHODS = AwaitHoldingLockRule.WAIT_METHODS

_SET_OPS = (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)
_SET_METHODS = ("union", "intersection", "difference",
                "symmetric_difference", "copy")

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _hash_source(source: str) -> str:
    return hashlib.sha1(
        f"v{CACHE_VERSION}:".encode() + source.encode()).hexdigest()


_is_clock_name = WallClockRule.is_nondeterministic


def _is_private(name: str) -> bool:
    return name.startswith("_") and not (
        name.startswith("__") and name.endswith("__"))


def _classify_return(v: Optional[ast.expr], cfg: FunctionDataflow,
                     node, depth: int = 0):
    """JSON-safe set-valuedness classification of one return value:
    'set' | 'other' | ['call', *spec] | ['any', [...]] (set operator:
    set if EITHER side is) | ['all', [...]] (multi-def name: set only
    if every reaching def is).  Evaluated against callee summaries at
    link time."""
    if v is None or depth > 3:
        return "other"
    if is_set_expr(v):
        return "set"
    if isinstance(v, ast.BinOp) and isinstance(v.op, _SET_OPS):
        return ["any", [_classify_return(v.left, cfg, node, depth + 1),
                        _classify_return(v.right, cfg, node, depth + 1)]]
    if isinstance(v, ast.Call):
        if isinstance(v.func, ast.Attribute) and \
                v.func.attr in _SET_METHODS:
            return _classify_return(v.func.value, cfg, node, depth + 1)
        spec = call_spec(v)
        if spec[0] != "opaque":
            return ["call"] + spec
        return "other"
    if isinstance(v, ast.Name):
        infos = {d.idx: d for d, _ in cfg.reaching(node, v.id)}.values()
        subs = []
        for d in infos:
            if d.is_param or d.unpacked or d.value is None:
                return "other"
            subs.append(_classify_return(d.value, cfg, node, depth + 1))
        if not subs:
            return "other"
        return subs[0] if len(subs) == 1 else ["all", subs]
    return "other"


def _line_suppressed(rule_id: str, line: int, suppress_line,
                     suppress_file) -> bool:
    ids = suppress_line.get(line, set()) | suppress_file
    return rule_id in ids or "all" in ids


def _arg_lock_keys(call: ast.Call, cfg: FunctionDataflow,
                   node) -> List[List[object]]:
    """[[position-or-keyword, lock key], ...] for every lock-shaped
    argument — how a concrete lock flows into a lock PARAMETER.  A Name
    argument resolves through the caller's reaching defs (``lk =
    self._lock; self._bump(lk)`` must unify like the attribute itself,
    not read as a DIFFERENT lock named 'lk' — a review catch)."""
    def key_of(a: ast.expr) -> Optional[str]:
        if isinstance(a, ast.Name):
            # Reaching defs FIRST: a lock-NAMED alias (`the_lock =
            # self._lock`) must canonicalize to the attribute, not to
            # its own caller-frame spelling.
            return cfg.alias_lock_key(node, a) or lock_key(a)
        return lock_key(a)

    out: List[List[object]] = []
    for i, a in enumerate(call.args):
        k = key_of(a)
        if k is not None:
            out.append([i, k])
    for kw in call.keywords:
        if kw.arg is not None:
            k = key_of(kw.value)
            if k is not None:
                out.append([kw.arg, k])
    return out


def extract_file_facts(rel: str, abspath: str, tree: ast.Module,
                       source: str, records, suppress_line,
                       suppress_file, parents=None) -> dict:
    """The per-file fact dict (JSON-safe, cacheable).  ``records`` is
    [(function node, FunctionDataflow, enclosing class name or None,
    nested?)] — the engine feeds the dataflows it already built during
    the shared walk; the standalone path (cache miss in ``--changed``)
    builds its own."""
    module, is_pkg = module_name_for(abspath)
    tables = build_import_tables(tree, module, is_pkg)

    classes: Dict[str, dict] = {}
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            classes[node.name] = {
                "bases": [s for s in map(base_spec, node.bases)
                          if s is not None],
                "methods": {n.name: n.lineno for n in node.body
                            if isinstance(n, _FUNCS)},
            }

    if parents is None:
        parents = {}
        for p in ast.walk(tree):
            for child in ast.iter_child_nodes(p):
                parents[id(child)] = p

    functions: Dict[str, dict] = {}
    for func, cfg, cls_name, nested in records:
        if nested:
            continue                # closures run under their own control
        qname = f"{cls_name}.{func.name}" if cls_name else func.name
        awaited_ids = {id(aw.value) for aw, _ in cfg.awaits
                       if isinstance(aw.value, ast.Call)}
        calls, blocks, clock = [], [], []
        for call, node in cfg.calls:
            line = getattr(call, "lineno", 0)
            spec = call_spec(call)
            calls.append([line, spec, sorted(cfg.lockset(node)),
                          id(call) in awaited_ids,
                          _arg_lock_keys(call, cfg, node)])
            f = call.func
            if isinstance(f, ast.Attribute) and f.attr in WAIT_METHODS \
                    and not call.args and \
                    not any(kw.arg == "timeout" for kw in call.keywords):
                # A bare .acquire() the LOCKSET layer already owns is an
                # acquisition, not a block; one on a non-lock receiver
                # (semaphore, condition) blocks like any other wait.
                if f.attr == "acquire" and (
                        lock_key(f.value) is not None or node.acquires):
                    pass
                elif not _line_suppressed("FTL013", line, suppress_line,
                                          suppress_file):
                    blocks.append([line, f".{f.attr}() with no timeout"])
            name = resolve_external(tables, f)
            if name == "time.sleep" and not _line_suppressed(
                    "FTL013", line, suppress_line, suppress_file):
                blocks.append([line, "time.sleep()"])
            if _is_clock_name(name) and not _line_suppressed(
                    "FTL001", line, suppress_line, suppress_file):
                clock.append([line, name])
        returns = []
        for node in cfg.nodes:
            if isinstance(node.stmt, ast.Return):
                returns.append(_classify_return(node.stmt.value, cfg,
                                                node))
        sim_ref = any(
            (isinstance(n, ast.Name) and n.id == "sim") or
            (isinstance(n, ast.Attribute) and n.attr == "sim")
            for n in ast.walk(func))
        functions[qname] = {
            "line": func.lineno, "async": cfg.is_async,
            "cls": cls_name, "name": func.name,
            "private": _is_private(func.name),
            "decorated": bool(func.decorator_list),
            "params": [a.arg for a in
                       (list(func.args.posonlyargs) + list(func.args.args)
                        + list(func.args.kwonlyargs))],
            "calls": calls, "blocks": blocks, "clock": clock,
            "returns": returns,
            "lock_params": dict(cfg.lock_params),
            "sim_ref": sim_ref,
        }

    # Address-taken detection: a function referenced OUTSIDE call
    # position (handed to spawn(), stored, decorated, getattr'd) has
    # callers the graph cannot see — it must never claim "all my
    # callers hold the lock".
    escapes: Set[str] = set()
    top_fns = {q for q, fn in functions.items() if fn["cls"] is None}
    method_owners: Dict[str, List[str]] = {}
    for cname, c in classes.items():
        for m in c["methods"]:
            method_owners.setdefault(m, []).append(cname)
    for q, fn in functions.items():
        if fn["decorated"]:
            escapes.add(q)
    def _enclosing_class(node: ast.AST) -> Optional[str]:
        n = parents.get(id(node))
        while n is not None and not isinstance(n, ast.ClassDef):
            n = parents.get(id(n))
        return n.name if n is not None else None

    for node in ast.walk(tree):
        parent = parents.get(id(node))
        in_call_pos = isinstance(parent, ast.Call) and parent.func is node
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if node.id in top_fns and not in_call_pos:
                escapes.add(node.id)
        elif isinstance(node, ast.Attribute) and \
                isinstance(node.ctx, ast.Load) and not in_call_pos:
            base = node.value
            if isinstance(base, ast.Name) and base.id in ("self", "cls"):
                # Scoped to the ENCLOSING class: `self.X` can only name
                # a method of the class the access sits in (same-named
                # methods of other classes must not lose their seeding
                # — the FTL009/FTL010 scope lesson again).
                owner = _enclosing_class(node)
                if owner is not None and node.attr in \
                        classes.get(owner, {}).get("methods", {}):
                    escapes.add(f"{owner}.{node.attr}")
                else:
                    # Inherited (or dynamic) method: can't pin the
                    # owner — escape every same-named method in the
                    # file (the conservative direction).
                    for cname in method_owners.get(node.attr, ()):
                        escapes.add(f"{cname}.{node.attr}")
            elif isinstance(base, ast.Name) and base.id in classes:
                if node.attr in classes[base.id]["methods"]:
                    escapes.add(f"{base.id}.{node.attr}")
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id == "getattr" and len(node.args) >= 2 and \
                isinstance(node.args[1], ast.Constant) and \
                isinstance(node.args[1].value, str):
            name = node.args[1].value
            if name in top_fns:
                escapes.add(name)
            for cname in method_owners.get(name, ()):
                escapes.add(f"{cname}.{name}")

    return {"module": module, "is_pkg": is_pkg, "classes": classes,
            "imports": tables, "escapes": sorted(escapes),
            "functions": functions}


def extract_standalone(rel: str, abspath: str,
                       source: str) -> Optional[dict]:
    """Cache-miss path: parse + build dataflow for every top-level
    function and method, then extract — used for program files that are
    outside the scanned set (``--changed``) and not in the cache."""
    try:
        tree = ast.parse(source, filename=abspath)
    except (SyntaxError, ValueError):
        return None
    records = []
    for node in tree.body:
        if isinstance(node, _FUNCS):
            records.append((node, FunctionDataflow(node), None, False))
        elif isinstance(node, ast.ClassDef):
            for m in node.body:
                if isinstance(m, _FUNCS):
                    records.append((m, FunctionDataflow(m), node.name,
                                    False))
    sup_line, sup_file = _suppressions(source)
    return extract_file_facts(rel, abspath, tree, source, records,
                              sup_line, sup_file)


class ProgramIndex:
    """The whole-lint-run interprocedural context: per-file facts (live
    for scanned files, cache/standalone for the rest of the program),
    the call graph over them, and the composed summaries."""

    def __init__(self, program_files: List[Tuple[str, str]],
                 cache_path: Optional[str] = None) -> None:
        self.program_files = program_files
        self.cache_path = cache_path
        self.scanned: Set[str] = set()
        self.facts: Dict[str, dict] = {}
        self.graph: Optional[CallGraph] = None
        self._hashes: Dict[str, str] = {}
        self._suppress: Dict[str, tuple] = {}
        self._entry: Dict[str, Optional[FrozenSet[str]]] = {}
        self._blocked: Dict[str, tuple] = {}
        self._clocked: Dict[str, tuple] = {}
        self._set_valued: Set[str] = set()
        self._param_canon: Dict[str, Dict[str, str]] = {}
        # [(rel, qname, line, param, {key: [caller sites]})]
        self.param_conflicts: List[tuple] = []
        # rel paths excluded from the program because two roots own the
        # same rel (for_roots sets this; add_scanned must respect it).
        self._collisions: Set[str] = set()
        self._rel_to_path = {rel: path for path, rel in program_files}
        self.cache_hits = 0
        self.cache_misses = 0

    @classmethod
    def for_roots(cls, scan_roots,
                  cache_path: Optional[str] = None) -> "ProgramIndex":
        """Program = the topmost enclosing package of every scan root
        (a directory root is its own program root), so a ``--changed``
        run over three files still links against the whole package."""
        roots: List[str] = []
        for p in scan_roots:
            a = os.path.abspath(p)
            r = a if os.path.isdir(a) else (topmost_package(a) or a)
            roots.append(os.path.realpath(r))
        uniq = sorted(set(roots))
        keep = [r for r in uniq
                if not any(o != r and r.startswith(o + os.sep)
                           for o in uniq)]
        files: List[Tuple[str, str]] = []
        seen: Dict[str, str] = {}
        collisions: Set[str] = set()
        for r in keep:
            for path, rel in iter_py_files(r):
                if rel not in seen:
                    seen[rel] = path
                    files.append((path, rel))
                elif seen[rel] != path:
                    # Two sibling roots both contain e.g. utils.py: the
                    # rel path IS the identity everywhere (findings,
                    # baseline, facts), so keeping both would cross-wire
                    # their facts.  Both drop out of the program — the
                    # rules degrade to intraprocedural for them, never
                    # to wrong-file resolution.
                    collisions.add(rel)
        pi = cls([f for f in files if f[1] not in collisions],
                 cache_path=cache_path)
        pi._collisions = collisions
        return pi

    # -- feeding -------------------------------------------------------------
    def add_scanned(self, ctx, abspath: str) -> None:
        """Called by the Analyzer for every file it walks: live facts
        from the dataflows the walk already built.  A file whose rel
        collides across roots (or maps to a DIFFERENT abspath than the
        program enumerated) contributes nothing — overwriting would
        resolve one package's calls against another's facts."""
        if ctx.path in self._collisions:
            return
        known = self._rel_to_path.get(ctx.path)
        if known is not None and \
                os.path.realpath(known) != os.path.realpath(abspath):
            return
        self.facts[ctx.path] = extract_file_facts(
            ctx.path, abspath, ctx.tree, ctx.source, ctx.cfg_records,
            ctx.suppress_line, ctx.suppress_file, parents=ctx._parents)
        self._hashes[ctx.path] = _hash_source(ctx.source)
        self._suppress[ctx.path] = (ctx.suppress_line, ctx.suppress_file)
        self.scanned.add(ctx.path)

    # -- cache ---------------------------------------------------------------
    def _load_cache(self) -> Dict[str, dict]:
        if not self.cache_path or not os.path.exists(self.cache_path):
            return {}
        try:
            with open(self.cache_path, "r", encoding="utf-8") as f:
                data = json.load(f)
            if data.get("version") != CACHE_VERSION:
                return {}
            return data.get("files", {})
        except (OSError, ValueError):
            return {}               # corrupt cache: fall back to parsing

    def save_cache(self) -> None:
        """Persist every program file's facts keyed by content hash —
        fail-soft (an unwritable cache degrades to re-parsing)."""
        if not self.cache_path:
            return
        entries = {rel: {"hash": self._hashes[rel],
                         "facts": self.facts[rel]}
                   for rel in self.facts if rel in self._hashes}
        try:
            tmp = self.cache_path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"version": CACHE_VERSION, "files": entries}, f)
            os.replace(tmp, self.cache_path)
        except OSError:
            pass

    # -- linking -------------------------------------------------------------
    def link(self) -> None:
        cache = self._load_cache()
        for abspath, rel in self.program_files:
            if rel in self.facts:
                continue
            try:
                with open(abspath, "r", encoding="utf-8") as f:
                    source = f.read()
            except OSError:
                continue
            h = _hash_source(source)
            entry = cache.get(rel)
            if entry and entry.get("hash") == h:
                self.facts[rel] = entry["facts"]
                self.cache_hits += 1
            else:
                facts = extract_standalone(rel, abspath, source)
                if facts is None:
                    continue        # unparseable: no facts, no summaries
                self.facts[rel] = facts
                self.cache_misses += 1
            self._hashes[rel] = h
        self.graph = CallGraph(self.facts)
        self.graph.resolve_all()
        self._compute_param_canon()
        self._compute_blocked()
        self._compute_clocked()
        self._compute_set_valued()
        self._compute_entry_locks()

    # -- summary fixpoints ---------------------------------------------------
    def _functions(self):
        for rel, f in self.facts.items():
            for qname, fn in f["functions"].items():
                yield rel, qname, fn, CallGraph.fid(rel, qname)

    def _escaped(self, rel: str, qname: str, fn: dict) -> bool:
        """All-callers-known is the premise of entry-lockset seeding AND
        lock-param unification; any way a hidden caller could exist
        breaks it: address-taken, a same-named call nobody resolved, or
        virtual dispatch (the method overrides / is overridden / sits
        under an unresolved base — `self.m()` in the base class runs
        the OVERRIDE at runtime, which static resolution cannot see)."""
        if qname in self.facts[rel]["escapes"]:
            return True
        if fn["name"] in self.graph.unresolved_names:
            return True
        cls = fn.get("cls")
        return cls is not None and \
            self.graph.virtually_dispatched(rel, cls, fn["name"])

    def _compute_blocked(self) -> None:
        """may-block-unbounded, LFP with a witness for chain rendering:
        witness = ('direct', line, desc) | ('call', line, callee fid).
        Propagates over PLAIN calls to SYNC callees only — an awaited
        callee's blocking is the await site's problem (FTL011), and an
        un-awaited async call never runs its body."""
        work: List[str] = []
        for rel, qname, fn, fid in self._functions():
            if fn["blocks"]:
                line, desc = fn["blocks"][0]
                self._blocked[fid] = ("direct", line, desc)
                work.append(fid)
        while work:
            target = work.pop()
            tfn = self.graph.function(target)
            if tfn is None or tfn["async"]:
                continue
            for caller, call in self.graph.callers.get(target, ()):
                if call[3]:         # awaited edge
                    continue
                if caller not in self._blocked:
                    self._blocked[caller] = ("call", call[0], target)
                    work.append(caller)

    def _compute_clocked(self) -> None:
        """may-read-wall-clock for REAL_ONLY-module functions: direct
        unsuppressed reads in functions that are NOT mode-guarded (no
        ``sim`` reference — ``EventLoop.now()``'s virtual/real branch is
        the sanctioned pattern), propagated through real-only-module
        callees.  Sim-reachable functions never propagate: their own
        direct reads are FTL001 findings already."""
        work: List[str] = []
        for rel, qname, fn, fid in self._functions():
            if _sim_reachable(rel) or fn["sim_ref"]:
                continue
            if fn["clock"]:
                line, name = fn["clock"][0]
                self._clocked[fid] = ("direct", line, name)
                work.append(fid)
        while work:
            target = work.pop()
            for caller, call in self.graph.callers.get(target, ()):
                rel = caller.partition("::")[0]
                if _sim_reachable(rel):
                    continue        # the FTL001 rule reports this edge
                cfn = self.graph.function(caller)
                if cfn is None or cfn["sim_ref"]:
                    continue
                tfn = self.graph.function(target)
                if tfn and tfn["async"] and not call[3]:
                    continue        # coroutine never awaited: no read
                if caller not in self._clocked:
                    self._clocked[caller] = ("call", call[0], target)
                    work.append(caller)

    def _compute_set_valued(self) -> None:
        """Set-valued returns, GREATEST fixpoint: start optimistic for
        every function whose returns are all set-shaped-or-call, then
        demote until stable — recursion (``def a(): return b()`` /
        ``def b(): return a()`` guarded by a base case returning a set)
        converges to True instead of diverging or defaulting False."""
        candidates: Dict[str, tuple] = {}
        for rel, qname, fn, fid in self._functions():
            if fn["returns"] and all(e != "other" for e in fn["returns"]):
                candidates[fid] = (rel, fn.get("cls"), fn["returns"])
        sv = set(candidates)
        changed = True
        while changed:
            changed = False
            for fid, (rel, cls, returns) in candidates.items():
                if fid not in sv:
                    continue
                if not all(self._eval_set(e, rel, cls, sv)
                           for e in returns):
                    sv.discard(fid)
                    changed = True
        # Groundedness (LFP): the optimism above keeps a PURE call
        # cycle with no base case "set-valued" forever — demand every
        # survivor reach at least one literal set return through the
        # chain (``return rec(x)`` / ``return rec2(x)`` alone proves
        # nothing; it never returns at all).
        grounded: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for fid, (rel, cls, returns) in candidates.items():
                if fid not in sv or fid in grounded:
                    continue
                if any(self._eval_grounded(e, rel, cls, grounded)
                       for e in returns):
                    grounded.add(fid)
                    changed = True
        self._set_valued = sv & grounded

    def _eval_set(self, entry, rel, cls, sv) -> bool:
        if entry == "set":
            return True
        if not isinstance(entry, list):
            return False
        kind = entry[0]
        if kind == "call":
            target = self.graph.resolve(rel, cls, entry[1:])
            return target in sv
        if kind == "any":
            return any(self._eval_set(e, rel, cls, sv) for e in entry[1])
        if kind == "all":
            return all(self._eval_set(e, rel, cls, sv) for e in entry[1])
        return False

    def _eval_grounded(self, entry, rel, cls, grounded) -> bool:
        if entry == "set":
            return True
        if not isinstance(entry, list):
            return False
        if entry[0] == "call":
            return self.graph.resolve(rel, cls, entry[1:]) in grounded
        return any(self._eval_grounded(e, rel, cls, grounded)
                   for e in entry[1])

    def _translate_locks(self, locks: Set[str], spec: List[str],
                         same_rel: bool) -> FrozenSet[str]:
        """Caller-frame lock keys that keep meaning in the callee's
        frame: ``self.*``/``cls.*`` survive self/cls/super dispatch
        (same object), bare module-level names survive same-module
        calls; everything else (locals, params, other objects) drops."""
        out = set()
        self_call = spec and spec[0] in ("self", "cls", "super")
        for k in sorted(locks):
            if k.startswith(("self.", "cls.")):
                if self_call:
                    out.add(k)
            elif "." not in k and same_rel:
                out.add(k)
        return frozenset(out)

    def _compute_entry_locks(self) -> None:
        """Caller-held locksets, top-down meet: entry(f) = ⋂ over every
        callsite of translate(canon(local lockset) ∪ entry(caller)).
        Only PRIVATE, non-escaped functions with at least one resolved
        caller are eligible — everything else enters with ∅ (a public
        function must stand on its own locks).  TOP (= every lock) is
        the optimistic start so recursion/SCCs converge downward."""
        eligible: Dict[str, tuple] = {}
        for rel, qname, fn, fid in self._functions():
            if fn["private"] and not self._escaped(rel, qname, fn) and \
                    self.graph.callers.get(fid):
                eligible[fid] = (rel, fn)
        entry: Dict[str, Optional[FrozenSet[str]]] = \
            {fid: None for fid in eligible}     # None = TOP
        for _ in range(50):
            changed = False
            for fid, (rel, fn) in eligible.items():
                val: Optional[FrozenSet[str]] = None
                for caller, call in self.graph.callers[fid]:
                    crel = caller.partition("::")[0]
                    canon = self._param_canon.get(caller, {})
                    locks = {canon.get(k, k) for k in call[2]}
                    ce = entry.get(caller, frozenset())
                    if ce is None:
                        continue    # caller still TOP: identity for meet
                    eff = self._translate_locks(
                        locks | set(ce), call[1], crel == rel)
                    val = eff if val is None else (val & eff)
                if val != entry[fid]:
                    entry[fid] = val
                    changed = True
            if not changed:
                break
        self._entry = entry

    def _compute_param_canon(self) -> None:
        """Unify each lock PARAMETER with the concrete lock its callers
        pass: all callers agree -> the param canonicalizes to that
        dotted key (participates in FTL012's meet); callers DISAGREE ->
        an FTL014 finding (the alias defeats lockset analysis)."""
        for rel, qname, fn, fid in self._functions():
            if not fn["lock_params"]:
                continue
            callers = self.graph.callers.get(fid, [])
            if not callers or self._escaped(rel, qname, fn):
                continue
            for p, pline in fn["lock_params"].items():
                try:
                    idx = fn["params"].index(p)
                except ValueError:
                    continue
                keys: Dict[str, List[str]] = {}
                complete = True
                for caller, call in callers:
                    shift = 1 if call[1] and \
                        call[1][0] in ("self", "cls", "super") else 0
                    k = None
                    for pos_or_name, lk in call[4]:
                        if pos_or_name == p or (
                                isinstance(pos_or_name, int) and
                                pos_or_name + shift == idx):
                            k = lk
                            break
                    if k is None:
                        complete = False
                    else:
                        keys.setdefault(k, []).append(
                            f"{caller}:{call[0]}")
                if len(keys) == 1 and complete:
                    self._param_canon.setdefault(fid, {})[p] = \
                        next(iter(keys))
                elif len(keys) >= 2:
                    self.param_conflicts.append(
                        (rel, qname, pline, p,
                         {k: sorted(v) for k, v in keys.items()}))

    # -- rule-facing queries -------------------------------------------------
    def entry_locks(self, rel: str, qname: str) -> FrozenSet[str]:
        v = self._entry.get(CallGraph.fid(rel, qname))
        return v if v else frozenset()

    def param_canon(self, rel: str, qname: str) -> Dict[str, str]:
        return self._param_canon.get(CallGraph.fid(rel, qname), {})

    def may_block(self, fid: Optional[str]) -> bool:
        return fid is not None and fid in self._blocked

    def may_clock(self, fid: Optional[str]) -> bool:
        return fid is not None and fid in self._clocked

    def set_valued(self, fid: Optional[str]) -> bool:
        return fid is not None and fid in self._set_valued

    def resolve(self, rel: str, cls_name: Optional[str],
                spec) -> Optional[str]:
        return self.graph.resolve(rel, cls_name, list(spec))

    def _chain(self, witness_map: Dict[str, tuple],
               fid: str) -> List[str]:
        out, cur = [], fid
        for _ in range(20):
            w = witness_map.get(cur)
            if w is None:
                break
            if w[0] == "direct":
                out.append(f"{cur} line {w[1]}: {w[2]}")
                break
            out.append(f"{cur} line {w[1]}")
            cur = w[2]
        return out

    def block_chain(self, fid: str) -> List[str]:
        return self._chain(self._blocked, fid)

    def clock_chain(self, fid: str) -> List[str]:
        return self._chain(self._clocked, fid)

    def iter_scanned_functions(self):
        """(rel, qname, fn facts, fid) for every function of every
        SCANNED file — where interprocedural findings may be reported."""
        for rel in sorted(self.scanned):
            f = self.facts.get(rel)
            if not f:
                continue
            for qname, fn in sorted(f["functions"].items()):
                yield rel, qname, fn, CallGraph.fid(rel, qname)

    def calls_with_targets(self, fid: str):
        """[(call record, resolved callee fid or None)] for one
        function (call record: [line, spec, locks, awaited,
        lock_args])."""
        return self.graph.calls_of.get(fid, [])

    def is_suppressed(self, rule_id: str, rel: str, line: int) -> bool:
        sup = self._suppress.get(rel)
        if sup is None:
            return False            # findings only land in scanned files
        return _line_suppressed(rule_id, line, sup[0], sup[1])

    def dump_callgraph(self) -> List[Dict[str, object]]:
        return self.graph.dump() if self.graph else []
