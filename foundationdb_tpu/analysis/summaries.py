"""Bottom-up function summaries for flowlint (ISSUE 11).

The dataflow layer answers questions about ONE function; this module
answers the cross-function ones the remaining hazard shapes need:

  * **may-block-unbounded** — does calling this (sync) function ever
    reach a timeout-less ``.result()/.wait()/.join()/.get()/.acquire()``
    or ``time.sleep`` through any chain of plain calls?  (FTL013: a
    callsite under a held lock reaching such a function is a
    deadlock/convoy hazard; the finding renders the chain.)
  * **set-valued return** — does this function always return a set,
    judging returned calls through callee summaries (FTL005 through
    arbitrarily deep in-package chains; recursion converges via a
    greatest-fixpoint over the call-graph SCCs)?
  * **may-read-wall-clock** — does this REAL_ONLY-module function reach
    an unguarded wall-clock/entropy read (FTL001 at sim-reachable
    callsites: the static verification of the "never imported on a sim
    path" construction)?
  * **caller-held locksets** — for a private function every caller of
    which is known, the MEET (intersection) of the locksets held at
    all its callsites: FTL012 seeds each function's entry lockset with
    it, so ``Tracer._roll``'s "caller holds the lock" contract is
    PROVEN instead of suppressed.
  * **lock-parameter unification** — a parameter used in lock position
    is unified with the one concrete lock every caller passes (it then
    participates in FTL012's join/meet); callers that disagree are an
    FTL014 finding.
  * **container ownership** (ISSUE 20) — a promise parked in a
    ``self.<field>`` container is only a sanctioned escape if some
    in-package function DRAINS that field (extract + resolve, composed
    bottom-up through pass-the-promise helpers); an undrained registry
    is FTL017 at the creation line.

Facts are extracted per FILE (one dict per file, JSON-safe) and cached
on disk keyed by content hash, so ``--changed`` runs reuse the whole
unchanged program's facts without re-parsing; the cross-file passes
(call-graph resolution + fixpoints) are cheap and recomputed per run.
Summary composition is the RacerD/Infer shape: intraprocedural facts
feed compact per-function summaries, summaries compose bottom-up over
SCCs in reverse topological order (here: monotone worklist fixpoints,
which converge identically and need no explicit SCC enumeration), and
rules consume summaries instead of re-analyzing callees.

Conservative unknown-callee handling: an unresolvable call contributes
NO summary effects (never invents a finding), and its terminal name
disqualifies same-named functions from the caller-held seeding (an
invisible caller might hold no lock — the direction that would
SILENCE a real race is the one that needs all callers known).
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .callgraph import (CallGraph, base_spec, build_import_tables,
                        call_spec, module_name_for, resolve_external)
from .dataflow import DefInfo, FunctionDataflow, is_set_expr, lock_key
from .engine import (_suppressions, iter_py_files, owned_lines,
                     topmost_package)
from .rules import AwaitHoldingLockRule, WallClockRule, _sim_reachable

# The cache FILE format version (shape of the JSON envelope).
CACHE_VERSION = 1
# The analysis-version stamp (ISSUE 13): bumped whenever the fact
# EXTRACTOR or a summary consumer changes shape, so a rule/extractor
# upgrade invalidates every cached per-file fact dict instead of
# silently serving pre-upgrade facts (which would lack the new keys —
# missed findings at best, KeyErrors at worst).  Every cache entry is
# keyed by (content hash, stamp); either mismatch is a miss.
#   2: ISSUE 13 — typed call specs, lock registry (attrs/attr_types/
#      module_locks), acquisitions, rets_type, promise leaks.
#   3: ISSUE 20 — container ownership protocol (parks/drains/
#      drain_forwards/resolver_params/param_forwards, per-file owned
#      lines), per-class container element types (elem_types),
#      annotation-driven receiver specs (Optional[C] / C | None /
#      string forward references).
ANALYSIS_VERSION = 3

# THE wait-method and clock predicates live on the rules (FTL011 /
# FTL001); the summaries import them so the transitive reach can never
# drift from the direct checks.
WAIT_METHODS = AwaitHoldingLockRule.WAIT_METHODS

_SET_OPS = (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)
_SET_METHODS = ("union", "intersection", "difference",
                "symmetric_difference", "copy")

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)

LOCK_FACTORY_NAMES = ("threading.Lock", "threading.RLock")


def _hash_source(source: str) -> str:
    return hashlib.sha1(source.encode()).hexdigest()


_is_clock_name = WallClockRule.is_nondeterministic


def _is_private(name: str) -> bool:
    return name.startswith("_") and not (
        name.startswith("__") and name.endswith("__"))


def _classify_return(v: Optional[ast.expr], cfg: FunctionDataflow,
                     node, depth: int = 0):
    """JSON-safe set-valuedness classification of one return value:
    'set' | 'other' | ['call', *spec] | ['any', [...]] (set operator:
    set if EITHER side is) | ['all', [...]] (multi-def name: set only
    if every reaching def is).  Evaluated against callee summaries at
    link time."""
    if v is None or depth > 3:
        return "other"
    if is_set_expr(v):
        return "set"
    if isinstance(v, ast.BinOp) and isinstance(v.op, _SET_OPS):
        return ["any", [_classify_return(v.left, cfg, node, depth + 1),
                        _classify_return(v.right, cfg, node, depth + 1)]]
    if isinstance(v, ast.Call):
        if isinstance(v.func, ast.Attribute) and \
                v.func.attr in _SET_METHODS:
            return _classify_return(v.func.value, cfg, node, depth + 1)
        spec = call_spec(v)
        if spec[0] != "opaque":
            return ["call"] + spec
        return "other"
    if isinstance(v, ast.Name):
        infos = {d.idx: d for d, _ in cfg.reaching(node, v.id)}.values()
        subs = []
        for d in infos:
            if d.is_param or d.unpacked or d.value is None:
                return "other"
            # At the def's own node (see _classify_ret_type): names in
            # the RHS must be judged by what reached the ASSIGNMENT.
            subs.append(_classify_return(d.value, cfg,
                                         cfg.node_for(d.value) or node,
                                         depth + 1))
        if not subs:
            return "other"
        return subs[0] if len(subs) == 1 else ["all", subs]
    return "other"


def _line_suppressed(rule_id: str, line: int, suppress_line,
                     suppress_file) -> bool:
    ids = suppress_line.get(line, set()) | suppress_file
    return rule_id in ids or "all" in ids


def _arg_lock_keys(call: ast.Call, cfg: FunctionDataflow,
                   node) -> List[List[object]]:
    """[[position-or-keyword, lock key], ...] for every lock-shaped
    argument — how a concrete lock flows into a lock PARAMETER.  A Name
    argument resolves through the caller's reaching defs (``lk =
    self._lock; self._bump(lk)`` must unify like the attribute itself,
    not read as a DIFFERENT lock named 'lk' — a review catch)."""
    def key_of(a: ast.expr) -> Optional[str]:
        if isinstance(a, ast.Name):
            # Reaching defs FIRST: a lock-NAMED alias (`the_lock =
            # self._lock`) must canonicalize to the attribute, not to
            # its own caller-frame spelling.
            return cfg.alias_lock_key(node, a) or lock_key(a)
        return lock_key(a)

    out: List[List[object]] = []
    for i, a in enumerate(call.args):
        k = key_of(a)
        if k is not None:
            out.append([i, k])
    for kw in call.keywords:
        if kw.arg is not None:
            k = key_of(kw.value)
            if k is not None:
                out.append([kw.arg, k])
    return out


# -- local type inference (ISSUE 13) -----------------------------------------

def _texpr_of_value(v: Optional[ast.expr]):
    """JSON-safe type expression for a def's RHS, or None: a (possibly
    awaited) call with a non-opaque target spec — a constructor or a
    factory, told apart at link time against the class tables and the
    returns-instance summaries."""
    if isinstance(v, ast.Await):
        v = v.value
    if isinstance(v, ast.Call):
        spec = call_spec(v)
        if spec[0] != "opaque":
            return ["call"] + spec
    return None


_OPTIONAL_HEADS = frozenset({"Optional"})
_UNION_HEADS = frozenset({"Union"})
_ELEM_CONTAINER_HEADS = frozenset({
    "List", "list", "Set", "set", "FrozenSet", "frozenset", "Deque",
    "deque", "Sequence", "MutableSequence", "Iterable", "Iterator",
    "Tuple", "tuple"})
_ELEM_MAPPING_HEADS = frozenset({
    "Dict", "dict", "DefaultDict", "defaultdict", "OrderedDict",
    "Mapping", "MutableMapping"})
_SCALAR_ANN_NAMES = frozenset({
    "None", "Any", "int", "float", "bool", "str", "bytes", "bytearray",
    "object", "complex"})


def _ann_head(a: ast.expr) -> Optional[str]:
    if isinstance(a, ast.Name):
        return a.id
    if isinstance(a, ast.Attribute):
        return a.attr
    return None


def _parse_str_ann(a: ast.expr) -> ast.expr:
    """A string annotation re-parsed to its expression (PEP 484 forward
    references — the codebase's dominant spelling for self-referential
    classes)."""
    if isinstance(a, ast.Constant) and isinstance(a.value, str):
        try:
            return ast.parse(a.value.strip(), mode="eval").body
        except (SyntaxError, ValueError):
            return a
    return a


def ann_spec(a: Optional[ast.expr]):
    """Base spec of the ONE class an annotation names, through the
    idioms the codebase actually writes: plain ``C``/``mod.C``,
    ``Optional[C]``, ``Union[C, None]``, ``C | None``, and ``"C"``
    forward references.  A union of two real classes is ambiguity —
    None (the conservative direction: a wrongly-typed receiver can
    silence caller-held seeding for a real race)."""
    if a is None:
        return None
    a = _parse_str_ann(a)
    if isinstance(a, ast.Subscript):
        head = _ann_head(a.value)
        if head in _OPTIONAL_HEADS:
            return ann_spec(a.slice)
        if head in _UNION_HEADS:
            elts = a.slice.elts if isinstance(a.slice, ast.Tuple) \
                else [a.slice]
            return _one_class_spec(elts)
        return None
    if isinstance(a, ast.BinOp) and isinstance(a.op, ast.BitOr):
        elts, work = [], [a]
        while work:
            e = work.pop()
            if isinstance(e, ast.BinOp) and isinstance(e.op, ast.BitOr):
                work.extend([e.left, e.right])
            else:
                elts.append(e)
        return _one_class_spec(elts)
    if _ann_head(a) in _SCALAR_ANN_NAMES:
        return None
    return base_spec(a)


def _one_class_spec(elts):
    specs = []
    for e in elts:
        s = ann_spec(e)
        if s is not None and s not in specs:
            specs.append(s)
    return specs[0] if len(specs) == 1 else None


def _join_type(table: dict, key: str, te) -> None:
    """Single-type join for the class attr/elem type tables:
    conflicting sites poison the entry (False, stripped after the
    walk) — ambiguity never types a receiver."""
    prior = table.get(key)
    if prior is None:
        table[key] = te
    elif prior != te:
        table[key] = False


def elem_ann_spec(a: Optional[ast.expr]):
    """Base spec of the ONE class a CONTAINER annotation stores:
    ``List[C]`` / ``Deque[C]`` / ``Set[C]`` elements, ``Dict[K, C]``
    values, with one level of ``Tuple[...]`` flattening
    (``List[Tuple[int, int, Promise]]`` — the notified-waiter heap
    shape) and scalar members ignored.  None unless exactly one class
    survives — a heterogeneous container types nothing."""
    if a is None:
        return None
    a = _parse_str_ann(a)
    if not isinstance(a, ast.Subscript):
        return None
    head = _ann_head(a.value)
    if head in _OPTIONAL_HEADS:
        return elem_ann_spec(a.slice)
    sl = a.slice
    if head in _ELEM_MAPPING_HEADS:
        if not (isinstance(sl, ast.Tuple) and len(sl.elts) == 2):
            return None
        cands = [sl.elts[1]]
    elif head in _ELEM_CONTAINER_HEADS:
        cands = list(sl.elts) if isinstance(sl, ast.Tuple) else [sl]
    else:
        return None
    flat = []
    for c in cands:
        c = _parse_str_ann(c)
        if isinstance(c, ast.Subscript) and \
                _ann_head(c.value) in ("Tuple", "tuple"):
            inner = c.slice
            flat.extend(inner.elts if isinstance(inner, ast.Tuple)
                        else [inner])
        else:
            flat.append(c)
    return _one_class_spec(flat)


def _infer_receiver(cfg: FunctionDataflow, node, name: str):
    """The local type-inference lattice, joined over reaching defs:
    every def must yield the SAME type expression (constructor/factory
    value, or a class-naming parameter annotation) or the receiver is
    unknown — ambiguity never resolves a call (the conservative
    direction: a wrongly-resolved callee could silence caller-held
    seeding for a real race)."""
    infos = {d.idx: d for d, _ in cfg.reaching(node, name)}.values()
    if not infos:
        return None
    out = None
    for d in infos:
        if d.is_param:
            spec = ann_spec(d.annotation)
            te = (["ann"] + spec) if spec is not None else None
        elif d.unpacked or d.value is None:
            te = None
        else:
            te = _texpr_of_value(d.value)
        if te is None:
            return None
        if out is None:
            out = te
        elif out != te:
            return None             # lattice join of two types: unknown
    return out


def _classify_ret_type(v: Optional[ast.expr], cfg: FunctionDataflow,
                       node, depth: int = 0):
    """Type expression of one return value (for the returns-instance
    summary), traced through single-valued local names; 'other' when it
    cannot be pinned."""
    if v is None or depth > 3:
        return "other"
    te = _texpr_of_value(v)
    if te is not None:
        return te
    if isinstance(v, ast.Name):
        infos = {d.idx: d for d, _ in cfg.reaching(node, v.id)}.values()
        out = None
        for d in infos:
            if d.is_param or d.unpacked or d.value is None:
                return "other"
            # Recurse at the DEF's own node, not the return's: a name
            # the def's RHS mentions may have been REBOUND between the
            # assignment and the return (`y = x; x = Other(); return
            # y`), and querying reaching defs at the return would read
            # the rebound value — the wrong-class direction that can
            # silently re-type a receiver.
            sub = _classify_ret_type(d.value, cfg,
                                     cfg.node_for(d.value) or node,
                                     depth + 1)
            if sub == "other" or (out is not None and sub != out):
                return "other"
            out = sub
        return out if out is not None else "other"
    return "other"


# -- promise-protocol path analysis (FTL016) ---------------------------------

# Methods that RESOLVE a promise/stream (the protocol's terminal ops)
# vs. reads that transfer nothing.  Any OTHER use of the name — an
# argument, a return, a store, an unknown method — is an ESCAPE:
# ownership moved, the protocol is someone else's problem.
PROMISE_RESOLVERS = frozenset({"send", "send_error", "break_promise",
                               "close", "break_buffered_replies"})
_PROMISE_READS = frozenset({"get_future", "is_set", "is_ready", "empty",
                            "pop"})


def _leaked_defs(cfg: FunctionDataflow, parents) -> List[list]:
    """[[line, name, texpr], ...] for every call-valued local def that
    reaches a NORMAL function exit neither resolved nor escaped on some
    path (forward may-analysis over the CFG, one bitmask fixpoint).
    Whether the def actually creates a Promise/PromiseStream is decided
    at link time from its type expression — this pass only computes the
    path property.  Raise exits and exception EDGES are exempt:
    unwinding drops the local and CPython's refcount breaks the promise
    deterministically; the hazard class is the branch that KEEPS
    RUNNING with the promise forgotten (the deposed-CC long-poll shape,
    ISSUE 10).  A name captured by a nested def/lambda escapes the
    frame outright (``call_at(..., lambda: p.send(None))`` hands
    ownership to the scheduler) — closures are outside the CFG, so the
    whole candidate drops."""
    captured: Set[str] = set()
    for sub in ast.walk(cfg.func):
        if sub is cfg.func or not isinstance(
                sub, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for nm in ast.walk(sub):
            if isinstance(nm, ast.Name) and isinstance(nm.ctx, ast.Load):
                captured.add(nm.id)
    cands: List[Tuple[DefInfo, list]] = []
    for d in cfg.defs:
        if d.is_param or d.unpacked or d.value is None or \
                d.name in captured:
            continue
        # Plain assignment statements only: a walrus inside a larger
        # expression hands its value to the enclosing expression (an
        # escape the name-load scan cannot see).
        if not isinstance(parents.get(id(d.value)),
                          (ast.Assign, ast.AnnAssign)):
            continue
        te = _texpr_of_value(d.value)
        if te is not None:
            cands.append((d, te))
    if not cands:
        return []
    idx = {id(d): i for i, (d, _) in enumerate(cands)}
    by_name: Dict[str, List[int]] = {}
    for i, (d, _) in enumerate(cands):
        by_name.setdefault(d.name, []).append(i)

    n = len(cfg.nodes)
    gens = [0] * n
    kills = [0] * n
    for node in cfg.nodes:
        for d in node.defs:
            for i in by_name.get(d.name, ()):
                kills[node.idx] |= 1 << i       # rebind kills (refcount
                #                                 breaks the old value)
            i = idx.get(id(d))
            if i is not None:
                gens[node.idx] |= 1 << i
    for name_node, node in cfg.loads:
        ids = by_name.get(name_node.id)
        if not ids:
            continue
        parent = parents.get(id(name_node))
        resolves_or_escapes = True
        if isinstance(parent, ast.Attribute) and parent.value is name_node:
            grand = parents.get(id(parent))
            if isinstance(grand, ast.Call) and grand.func is parent:
                if parent.attr in PROMISE_RESOLVERS:
                    pass                        # protocol satisfied
                elif parent.attr in _PROMISE_READS:
                    resolves_or_escapes = False  # transfers nothing
                # any other method: conservatively an escape
        if resolves_or_escapes:
            for i in ids:
                kills[node.idx] |= 1 << i

    # Propagate along NORMAL edges only — a fact reaching an exit
    # through an exception edge describes an unwinding path, which the
    # Raise exemption already covers.  EXCEPTION: a Return/Break/
    # Continue under a try-with-finalbody completes NORMALLY through
    # the finally junction (the CFG wires that edge via the exception
    # stack) — re-admit those junction edges, or the finalbody never
    # sees return-path facts and the exit exemption below would
    # silence every leak exiting through a try/finally.
    normal = []
    for node in cfg.nodes:
        succs = node.succs - node.exc_succs
        if isinstance(node.stmt, (ast.Return, ast.Break, ast.Continue)):
            for s in node.exc_succs:
                st = cfg.nodes[s].stmt
                if isinstance(st, ast.Try) and st.finalbody:
                    succs = succs | {s}     # the finally junction
        normal.append(sorted(succs))
    preds: List[List[int]] = [[] for _ in cfg.nodes]
    for node in cfg.nodes:
        for s in normal[node.idx]:
            preds[s].append(node.idx)
    outs: List[Optional[int]] = [None] * n      # None = not yet visited
    pending = [False] * n
    # Entry points: the function entry AND every except-handler entry —
    # handlers are reachable only through the (excluded) exception
    # edges, but a handler that catches KEEPS RUNNING with its own
    # creations live, so they seed with empty facts (facts from before
    # the try stay exempt on the unwind path, as designed).
    work = [0] + [node.idx for node in cfg.nodes
                  if isinstance(node.stmt, ast.ExceptHandler)]
    for i in work:
        pending[i] = True
    while work:
        i = work.pop()
        pending[i] = False
        merged = 0
        for p in preds[i]:
            if outs[p] is not None:
                merged |= outs[p]
        out = (merged & ~kills[i]) | gens[i]
        if out != outs[i]:
            outs[i] = out
            for s in normal[i]:
                if not pending[s]:
                    pending[s] = True
                    work.append(s)

    leaked = 0
    fallthrough_exits = set(cfg.exit_preds)
    for node in cfg.nodes:
        st = node.stmt
        if isinstance(st, ast.Raise) or outs[node.idx] is None:
            continue
        # Exits: nodes whose fall-through leaves the function (the
        # implicit return off the end — a last-statement branch test
        # STILL HAS in-body successors, so successor-lessness alone
        # misses it) plus successor-less nodes (returns, finalbody
        # ends).
        if node.idx not in fallthrough_exits and normal[node.idx]:
            continue
        if isinstance(st, ast.Return):
            # A return under a try-with-finalbody exits THROUGH the
            # finalbody, which may still resolve the promise — that
            # path is the finally junction's (exception-edge) business,
            # so this node is not an exit of its own.
            ch: ast.AST = st
            p = parents.get(id(st))
            through_finally = False
            while p is not None and p is not cfg.func and \
                    not isinstance(p, (ast.FunctionDef,
                                       ast.AsyncFunctionDef, ast.Lambda)):
                # Stop at the ENCLOSING function: an outer try/finally
                # around the whole def must not exempt its returns.  A
                # try exempts only returns in its body/handlers/orelse
                # — a return INSIDE the finalbody exits the function
                # directly, with no further finally of THIS try to
                # resolve anything.
                if isinstance(p, ast.Try) and p.finalbody and \
                        not any(ch is s for s in p.finalbody):
                    through_finally = True
                    break
                ch = p
                p = parents.get(id(p))
            if through_finally:
                continue
        leaked |= outs[node.idx]
    return [[d.lineno, d.name, te] for i, (d, te) in enumerate(cands)
        if leaked & (1 << i)]


# -- container ownership protocol (FTL017, ISSUE 20) -------------------------

_PARK_METHODS = frozenset({"append", "appendleft", "add", "push",
                           "put", "put_nowait"})
_PARK_FREE = frozenset({"heappush"})
_POP_METHODS = frozenset({"pop", "popleft", "popitem", "get",
                          "get_nowait"})
_POP_FREE = frozenset({"heappop"})
_ITER_WRAPPERS = frozenset({"list", "tuple", "sorted", "iter",
                            "reversed"})
_ITER_VIEWS = frozenset({"values", "items"})


def _walk_own_scope(func):
    """Walk the function's OWN statements — nested defs/lambdas run
    (and drain) under their own control, mirroring the records loop."""
    work = list(ast.iter_child_nodes(func))
    while work:
        n = work.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        yield n
        work.extend(ast.iter_child_nodes(n))


def _self_attr_name(e) -> Optional[str]:
    if isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name) \
            and e.value.id == "self":
        return e.attr
    return None


def _terminal_of(e) -> Optional[str]:
    if isinstance(e, ast.Name):
        return e.id
    if isinstance(e, ast.Attribute):
        return e.attr
    return None


def _container_protocol(func, cfg: FunctionDataflow) -> dict:
    """Producer/consumer facts for the cross-function ownership
    protocol: an escape into a ``self.<field>`` container is only
    sanctioned when some in-package function DRAINS that field —
    extracts elements (pop/popleft/heappop/subscript/iterate) and
    resolves them (PROMISE_RESOLVERS), possibly through a helper the
    element is handed to.  Five JSON-safe fact lists:

      parks:           [[creation line, field, texpr]] — a value pushed
                       into a self-container (append/add/heappush/
                       put/setdefault/subscript-store), attributed to
                       the CREATION line of the pushed name's
                       call-valued def(s) (the push line for an inline
                       call), tuple/list wrappers unwrapped;
      drains:          [field] — fields whose extracted elements this
                       function resolves directly;
      drain_forwards:  [[field, callee spec, arg index]] — an extracted
                       element handed to a callee; a drain iff the
                       callee's matching param resolves (composed
                       bottom-up at link time);
      resolver_params: [param] — params this function resolves;
      param_forwards:  [[param, callee spec, arg index]].

    Unknown callees and unidentifiable fields contribute nothing (the
    silent direction — FTL017 never invents a finding from ambiguity;
    the drain side is deliberately may-analysis: ANY in-package drain
    sanctions the registry)."""
    own = [n for n in _walk_own_scope(func)]
    params = {a.arg for a in (list(func.args.posonlyargs)
                              + list(func.args.args)
                              + list(func.args.kwonlyargs))}

    def _unwrap_or(e):
        # `self._batch or []` — the swap-with-default idiom; the field
        # is the interesting operand.
        if isinstance(e, ast.BoolOp) and isinstance(e.op, ast.Or):
            for v in e.values:
                if _self_attr_name(v) is not None:
                    return v
        return e

    # One level of local aliasing: `ws = self._waiters` AND the atomic
    # tuple swap `ws, self._waiters = self._waiters, []` (the
    # swap-and-drain idiom in core/futures.py / cluster_controller's
    # _publish), with an optional `or []` default on the swapped-out
    # value.
    alias: Dict[str, str] = {}
    for n in own:
        if not isinstance(n, ast.Assign) or len(n.targets) != 1:
            continue
        t0 = n.targets[0]
        if isinstance(t0, ast.Name):
            fld = _self_attr_name(_unwrap_or(n.value))
            if fld is not None:
                alias[t0.id] = fld
        elif isinstance(t0, ast.Tuple) and \
                isinstance(n.value, ast.Tuple) and \
                len(t0.elts) == len(n.value.elts):
            for tt, vv in zip(t0.elts, n.value.elts):
                if isinstance(tt, ast.Name):
                    fld = _self_attr_name(_unwrap_or(vv))
                    if fld is not None:
                        alias[tt.id] = fld

    def field_of(e) -> Optional[str]:
        fld = _self_attr_name(e)
        if fld is not None:
            return fld
        if isinstance(e, ast.Name):
            return alias.get(e.id)
        return None

    def pop_field(call) -> Optional[str]:
        """self.<field> (or an alias of it) an extraction call pulls
        from, else None."""
        if not isinstance(call, ast.Call):
            return None
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr in _POP_METHODS:
            return field_of(f.value)
        if _terminal_of(f) in _POP_FREE and call.args:
            return field_of(call.args[0])
        return None

    def iter_field(e) -> Optional[str]:
        """self.<field> a for-loop iterable ranges over — directly,
        via .values()/.items(), or under one list()/sorted()-style
        wrapper."""
        fld = field_of(e)
        if fld is not None:
            return fld
        if isinstance(e, ast.Call):
            f = e.func
            if isinstance(f, ast.Attribute) and f.attr in _ITER_VIEWS:
                return field_of(f.value)
            if isinstance(f, ast.Name) and f.id in _ITER_WRAPPERS \
                    and e.args:
                return iter_field(e.args[0])
        return None

    parks: List[list] = []

    def record_park(field: str, value, line: int) -> None:
        vs = value.elts if isinstance(value, (ast.Tuple, ast.List)) \
            else [value]
        for v in vs:
            te = _texpr_of_value(v)
            if te is not None:
                if [line, field, te] not in parks:
                    parks.append([line, field, te])
                continue
            if not isinstance(v, ast.Name):
                continue
            node = cfg.node_for(v)
            infos = [d for d, _ in cfg.reaching(node, v.id)] \
                if node is not None else \
                [d for d in cfg.defs if d.name == v.id]
            for d in infos:
                if d.is_param or d.unpacked or d.value is None:
                    continue
                dte = _texpr_of_value(d.value)
                if dte is not None and \
                        [d.lineno, field, dte] not in parks:
                    parks.append([d.lineno, field, dte])

    for n in own:
        if isinstance(n, ast.Call):
            f = n.func
            if isinstance(f, ast.Attribute) and \
                    f.attr in _PARK_METHODS and n.args:
                fld = field_of(f.value)
                if fld is not None:
                    record_park(fld, n.args[0], n.lineno)
            elif isinstance(f, ast.Attribute) and \
                    f.attr == "setdefault" and len(n.args) >= 2:
                fld = field_of(f.value)
                if fld is not None:
                    record_park(fld, n.args[1], n.lineno)
            elif _terminal_of(f) in _PARK_FREE and len(n.args) >= 2:
                fld = field_of(n.args[0])
                if fld is not None:
                    record_park(fld, n.args[1], n.lineno)
        elif isinstance(n, ast.Assign):
            for t in n.targets:
                if isinstance(t, ast.Subscript):
                    fld = field_of(t.value)
                    if fld is not None:
                        record_park(fld, n.value, n.lineno)

    drains: Set[str] = set()
    bound: Dict[str, Set[str]] = {}     # extracted name -> source fields

    def bind(target, field: str) -> None:
        tgts = target.elts if isinstance(target, ast.Tuple) \
            else [target]
        for t in tgts:
            if isinstance(t, ast.Name):
                bound.setdefault(t.id, set()).add(field)
            elif isinstance(t, ast.Tuple):
                bind(t, field)

    def popped_fields(v) -> Set[str]:
        """Fields whose extracted element(s) `v` evaluates to: a pop
        call, a Subscript PROJECTION of one (``self._pending.pop(rid)
        [0]`` — the element rides inside a tuple entry), or a Name
        already bound to popped values (two-step unpack: ``entry =
        d.pop(k); p, _c, t0 = entry``)."""
        fld = pop_field(v)
        if fld is not None:
            return {fld}
        if isinstance(v, ast.Subscript):
            return popped_fields(v.value)
        if isinstance(v, ast.Name):
            return set(bound.get(v.id, ()))
        return set()

    # To fixpoint: _walk_own_scope yields in stack order, not source
    # order, so a name-through-name binding may be seen before its
    # source name is bound.  Bounded by the alias-chain depth.
    changed = True
    while changed:
        changed = False
        before = {k: set(v) for k, v in bound.items()}
        for n in own:
            if isinstance(n, (ast.Assign, ast.AnnAssign)) and \
                    getattr(n, "value", None) is not None:
                targets = n.targets if isinstance(n, ast.Assign) \
                    else [n.target]
                for fld in popped_fields(n.value):
                    for t in targets:
                        bind(t, fld)
            elif isinstance(n, (ast.For, ast.AsyncFor)):
                fld = iter_field(n.iter)
                if fld is not None:
                    bind(n.target, fld)
        if {k: set(v) for k, v in bound.items()} != before:
            changed = True

    resolved_names: Set[str] = set()
    for n in own:
        if not (isinstance(n, ast.Call) and
                isinstance(n.func, ast.Attribute) and
                n.func.attr in PROMISE_RESOLVERS):
            continue
        recv = n.func.value
        fld = pop_field(recv)
        if fld is not None:             # self.F.pop(0).send(...)
            drains.add(fld)
        elif isinstance(recv, ast.Subscript):
            fld = field_of(recv.value)
            if fld is not None:         # self.F[k].send(...)
                drains.add(fld)
        elif isinstance(recv, ast.Name):
            resolved_names.add(recv.id)

    for name, fields in bound.items():
        if name in resolved_names:
            drains.update(fields)

    drain_forwards: List[list] = []
    param_forwards: List[list] = []
    for n in own:
        if not isinstance(n, ast.Call):
            continue
        spec = call_spec(n)
        if spec[0] == "opaque":
            continue
        for i, a in enumerate(n.args):
            if not isinstance(a, ast.Name):
                continue
            for fld in sorted(bound.get(a.id, ())):
                rec = [fld, spec, i]
                if rec not in drain_forwards:
                    drain_forwards.append(rec)
            if a.id in params:
                rec = [a.id, spec, i]
                if rec not in param_forwards:
                    param_forwards.append(rec)

    return {"parks": parks, "drains": sorted(drains),
            "drain_forwards": drain_forwards,
            "resolver_params": sorted(params & resolved_names),
            "param_forwards": param_forwards}


def extract_file_facts(rel: str, abspath: str, tree: ast.Module,
                       source: str, records, suppress_line,
                       suppress_file, parents=None) -> dict:
    """The per-file fact dict (JSON-safe, cacheable).  ``records`` is
    [(function node, FunctionDataflow, enclosing class name or None,
    nested?)] — the engine feeds the dataflows it already built during
    the shared walk; the standalone path (cache miss in ``--changed``)
    builds its own."""
    module, is_pkg = module_name_for(abspath)
    tables = build_import_tables(tree, module, is_pkg)

    classes: Dict[str, dict] = {}
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            c = classes[node.name] = {
                "bases": [s for s in map(base_spec, node.bases)
                          if s is not None],
                "methods": {n.name: n.lineno for n in node.body
                            if isinstance(n, _FUNCS)},
                # The object-sensitivity registry (ISSUE 13): every
                # self-assigned OR class-body-assigned attr name
                # (allocation-site ownership for lock identities) and
                # the attrs with ONE inferable class (constructor
                # assignment / annotation — conflicting sites drop
                # out).  A class-body `_lock = threading.Lock()` is an
                # allocation site like any `self._lock = ...`: Sub and
                # Base methods locking it must agree on ONE identity.
                "attrs": [],
                "attr_types": {},
                # attr -> ONE inferable element type for container
                # attrs (``Dict[K, C]`` values / ``List[C]`` elements,
                # ISSUE 20) — feeds ``self.X[k].m()`` receiver typing.
                "elem_types": {},
            }
            for stmt in node.body:
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target] if isinstance(stmt, ast.AnnAssign) \
                    else ()
                for t in targets:
                    if isinstance(t, ast.Name) and \
                            t.id not in c["attrs"]:
                        c["attrs"].append(t.id)
                if isinstance(stmt, ast.AnnAssign) and \
                        isinstance(stmt.target, ast.Name):
                    es = elem_ann_spec(stmt.annotation)
                    if es is not None:
                        _join_type(c["elem_types"], stmt.target.id,
                                   ["ann"] + es)

    if parents is None:
        parents = {}
        for p in ast.walk(tree):
            for child in ast.iter_child_nodes(p):
                parents[id(child)] = p

    def _enclosing_class_name(node: ast.AST) -> Optional[str]:
        n = parents.get(id(node))
        while n is not None and not isinstance(n, ast.ClassDef):
            n = parents.get(id(n))
        return n.name if n is not None else None

    module_locks: List[str] = []
    for node in ast.walk(tree):
        targets, value, annot = (), None, None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign):
            targets, value, annot = [node.target], node.value, \
                node.annotation
        else:
            continue
        is_lock = isinstance(value, ast.Call) and \
            resolve_external(tables, value.func) in LOCK_FACTORY_NAMES
        for t in targets:
            if isinstance(t, ast.Name) and is_lock and \
                    parents.get(id(node)) is tree:
                module_locks.append(t.id)
            if not (isinstance(t, ast.Attribute) and
                    isinstance(t.value, ast.Name) and
                    t.value.id == "self"):
                continue
            owner = _enclosing_class_name(node)
            c = classes.get(owner) if owner else None
            if c is None:
                continue
            if t.attr not in c["attrs"]:
                c["attrs"].append(t.attr)
            te = _texpr_of_value(value)
            if te is None and annot is not None:
                spec = ann_spec(annot)
                te = (["ann"] + spec) if spec is not None else None
            if te is not None:
                _join_type(c["attr_types"], t.attr, te)
            if annot is not None:
                es = elem_ann_spec(annot)
                if es is not None:
                    _join_type(c["elem_types"], t.attr, ["ann"] + es)
    for c in classes.values():
        c["attr_types"] = {k: v for k, v in c["attr_types"].items()
                           if v is not False}
        c["elem_types"] = {k: v for k, v in c["elem_types"].items()
                           if v is not False}

    functions: Dict[str, dict] = {}
    for func, cfg, cls_name, nested in records:
        if nested:
            continue                # closures run under their own control
        qname = f"{cls_name}.{func.name}" if cls_name else func.name
        awaited_ids = {id(aw.value) for aw, _ in cfg.awaits
                       if isinstance(aw.value, ast.Call)}
        calls, blocks, clock = [], [], []
        for call, node in cfg.calls:
            line = getattr(call, "lineno", 0)
            spec = call_spec(call)
            f = call.func
            if spec[0] == "attr" and spec[1] not in tables["aliases"] \
                    and spec[1] not in tables["from"] and \
                    spec[1] not in classes:
                # obj.m() on a plain local: the receiver-typed case.  A
                # single inferable type upgrades the spec; ambiguity
                # leaves it an unknown callee (conservatism intact).
                te = _infer_receiver(cfg, node, spec[1])
                if te is not None:
                    spec = ["typed", te, spec[2]]
            elif spec[0] == "opaque" and isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Attribute) and \
                    isinstance(f.value.value, ast.Name) and \
                    f.value.value.id == "self":
                # self.X.m(): typed through the class's attribute-type
                # table; the receiver PATH (self.X) also names the
                # instance role for object-sensitive lock identity.
                spec = ["typed", ["selfattr", f.value.attr], f.attr]
            elif spec[0] == "opaque" and isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Subscript) and \
                    isinstance(f.value.value, ast.Attribute) and \
                    isinstance(f.value.value.value, ast.Name) and \
                    f.value.value.value.id == "self":
                # self.X[k].m(): typed through the class's container
                # ELEMENT-type table (``Dict[K, C]`` / ``List[C]``
                # annotations) — every element of one container
                # collapses to a single may-alias identity.
                spec = ["typed", ["selfelem", f.value.value.attr],
                        f.attr]
            calls.append([line, spec, sorted(cfg.lockset(node)),
                          id(call) in awaited_ids,
                          _arg_lock_keys(call, cfg, node)])
            if isinstance(f, ast.Attribute) and f.attr in WAIT_METHODS \
                    and not call.args and \
                    not any(kw.arg == "timeout" for kw in call.keywords):
                # A bare .acquire() the LOCKSET layer already owns is an
                # acquisition, not a block; one on a non-lock receiver
                # (semaphore, condition) blocks like any other wait.
                if f.attr == "acquire" and (
                        lock_key(f.value) is not None or node.acquires):
                    pass
                elif not _line_suppressed("FTL013", line, suppress_line,
                                          suppress_file):
                    blocks.append([line, f".{f.attr}() with no timeout"])
            name = resolve_external(tables, f)
            if name == "time.sleep" and not _line_suppressed(
                    "FTL013", line, suppress_line, suppress_file):
                blocks.append([line, "time.sleep()"])
            if _is_clock_name(name) and not _line_suppressed(
                    "FTL001", line, suppress_line, suppress_file):
                clock.append([line, name])
        returns, rets_type = [], []
        for node in cfg.nodes:
            if isinstance(node.stmt, ast.Return):
                returns.append(_classify_return(node.stmt.value, cfg,
                                                node))
                rets_type.append(_classify_ret_type(node.stmt.value,
                                                    cfg, node))
        # Lock acquisitions with the locks already held at that point —
        # the per-function half of the FTL015 lock-ordering summary.
        # An FTL015-suppressed line contributes no nesting facts, so a
        # justified ordering never re-enters a cycle through deeper
        # composition.
        acquisitions = []
        for node in cfg.nodes:
            if not node.acquires:
                continue
            aline = getattr(node.stmt, "lineno", 0)
            if _line_suppressed("FTL015", aline, suppress_line,
                                suppress_file):
                continue
            held = sorted(cfg.lockset(node))
            for key in sorted(node.acquires):
                acquisitions.append([aline, key,
                                     [h for h in held if h != key]])
        leaks = [lk for lk in _leaked_defs(cfg, parents)
                 if not _line_suppressed("FTL016", lk[0], suppress_line,
                                         suppress_file)]
        sim_ref = any(
            (isinstance(n, ast.Name) and n.id == "sim") or
            (isinstance(n, ast.Attribute) and n.attr == "sim")
            for n in ast.walk(func))
        proto = _container_protocol(func, cfg)
        functions[qname] = {
            "line": func.lineno, "async": cfg.is_async,
            "cls": cls_name, "name": func.name,
            "private": _is_private(func.name),
            "decorated": bool(func.decorator_list),
            "params": [a.arg for a in
                       (list(func.args.posonlyargs) + list(func.args.args)
                        + list(func.args.kwonlyargs))],
            "calls": calls, "blocks": blocks, "clock": clock,
            "returns": returns, "rets_type": rets_type,
            "acquisitions": acquisitions, "leaks": leaks,
            "lock_params": dict(cfg.lock_params),
            "sim_ref": sim_ref,
            "parks": proto["parks"], "drains": proto["drains"],
            "drain_forwards": proto["drain_forwards"],
            "resolver_params": proto["resolver_params"],
            "param_forwards": proto["param_forwards"],
        }

    # Address-taken detection: a function referenced OUTSIDE call
    # position (handed to spawn(), stored, decorated, getattr'd) has
    # callers the graph cannot see — it must never claim "all my
    # callers hold the lock".
    escapes: Set[str] = set()
    top_fns = {q for q, fn in functions.items() if fn["cls"] is None}
    method_owners: Dict[str, List[str]] = {}
    for cname, c in classes.items():
        for m in c["methods"]:
            method_owners.setdefault(m, []).append(cname)
    for q, fn in functions.items():
        if fn["decorated"]:
            escapes.add(q)
    for node in ast.walk(tree):
        parent = parents.get(id(node))
        in_call_pos = isinstance(parent, ast.Call) and parent.func is node
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if node.id in top_fns and not in_call_pos:
                escapes.add(node.id)
        elif isinstance(node, ast.Attribute) and \
                isinstance(node.ctx, ast.Load) and not in_call_pos:
            base = node.value
            if isinstance(base, ast.Name) and base.id in ("self", "cls"):
                # Scoped to the ENCLOSING class: `self.X` can only name
                # a method of the class the access sits in (same-named
                # methods of other classes must not lose their seeding
                # — the FTL009/FTL010 scope lesson again).
                owner = _enclosing_class_name(node)
                if owner is not None and node.attr in \
                        classes.get(owner, {}).get("methods", {}):
                    escapes.add(f"{owner}.{node.attr}")
                else:
                    # Inherited (or dynamic) method: can't pin the
                    # owner — escape every same-named method in the
                    # file (the conservative direction).
                    for cname in method_owners.get(node.attr, ()):
                        escapes.add(f"{cname}.{node.attr}")
            elif isinstance(base, ast.Name) and base.id in classes:
                if node.attr in classes[base.id]["methods"]:
                    escapes.add(f"{base.id}.{node.attr}")
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id == "getattr" and len(node.args) >= 2 and \
                isinstance(node.args[1], ast.Constant) and \
                isinstance(node.args[1].value, str):
            name = node.args[1].value
            if name in top_fns:
                escapes.add(name)
            for cname in method_owners.get(name, ()):
                escapes.add(f"{cname}.{name}")

    return {"module": module, "is_pkg": is_pkg, "classes": classes,
            "imports": tables, "escapes": sorted(escapes),
            "module_locks": sorted(set(module_locks)),
            # ``# flowlint: owned -- why`` lines: the FTL017 escape
            # hatch, kept in the FACTS (not the engine's per-scan
            # suppression maps) so cached files keep their sanction.
            "owned": owned_lines(source),
            "functions": functions}


def extract_standalone(rel: str, abspath: str,
                       source: str) -> Optional[dict]:
    """Cache-miss path: parse + build dataflow for every top-level
    function and method, then extract — used for program files that are
    outside the scanned set (``--changed``) and not in the cache."""
    try:
        tree = ast.parse(source, filename=abspath)
    except (SyntaxError, ValueError):
        return None
    records = []
    for node in tree.body:
        if isinstance(node, _FUNCS):
            records.append((node, FunctionDataflow(node), None, False))
        elif isinstance(node, ast.ClassDef):
            for m in node.body:
                if isinstance(m, _FUNCS):
                    records.append((m, FunctionDataflow(m), node.name,
                                    False))
    sup_line, sup_file = _suppressions(source)
    return extract_file_facts(rel, abspath, tree, source, records,
                              sup_line, sup_file)


class ProgramIndex:
    """The whole-lint-run interprocedural context: per-file facts (live
    for scanned files, cache/standalone for the rest of the program),
    the call graph over them, and the composed summaries."""

    def __init__(self, program_files: List[Tuple[str, str]],
                 cache_path: Optional[str] = None) -> None:
        self.program_files = program_files
        self.cache_path = cache_path
        self.scanned: Set[str] = set()
        self.facts: Dict[str, dict] = {}
        self.graph: Optional[CallGraph] = None
        self._hashes: Dict[str, str] = {}
        self._suppress: Dict[str, tuple] = {}
        self._entry: Dict[str, Optional[FrozenSet[str]]] = {}
        self._blocked: Dict[str, tuple] = {}
        self._clocked: Dict[str, tuple] = {}
        self._set_valued: Set[str] = set()
        self._param_canon: Dict[str, Dict[str, str]] = {}
        # may-acquire (FTL015): fid -> {entry: witness}, entry =
        # ("S", symbolic self-rooted key) | ("C", concrete identity).
        self._acq: Dict[str, Dict[tuple, tuple]] = {}
        # FTL017 ownership protocol: drained field identities
        # (rel, class, attr) and the composed resolver-param sets.
        self._drained: Set[tuple] = set()
        self._resolver_params: Dict[str, Set[str]] = {}
        # [(rel, qname, line, param, {key: [caller sites]})]
        self.param_conflicts: List[tuple] = []
        # rel paths excluded from the program because two roots own the
        # same rel (for_roots sets this; add_scanned must respect it).
        self._collisions: Set[str] = set()
        self._rel_to_path = {rel: path for path, rel in program_files}
        self.cache_hits = 0
        self.cache_misses = 0

    @classmethod
    def for_roots(cls, scan_roots,
                  cache_path: Optional[str] = None) -> "ProgramIndex":
        """Program = the topmost enclosing package of every scan root
        (a directory root is its own program root), so a ``--changed``
        run over three files still links against the whole package."""
        roots: List[str] = []
        for p in scan_roots:
            a = os.path.abspath(p)
            r = a if os.path.isdir(a) else (topmost_package(a) or a)
            roots.append(os.path.realpath(r))
        uniq = sorted(set(roots))
        keep = [r for r in uniq
                if not any(o != r and r.startswith(o + os.sep)
                           for o in uniq)]
        files: List[Tuple[str, str]] = []
        seen: Dict[str, str] = {}
        collisions: Set[str] = set()
        for r in keep:
            for path, rel in iter_py_files(r):
                if rel not in seen:
                    seen[rel] = path
                    files.append((path, rel))
                elif seen[rel] != path:
                    # Two sibling roots both contain e.g. utils.py: the
                    # rel path IS the identity everywhere (findings,
                    # baseline, facts), so keeping both would cross-wire
                    # their facts.  Both drop out of the program — the
                    # rules degrade to intraprocedural for them, never
                    # to wrong-file resolution.
                    collisions.add(rel)
        pi = cls([f for f in files if f[1] not in collisions],
                 cache_path=cache_path)
        pi._collisions = collisions
        return pi

    # -- feeding -------------------------------------------------------------
    def add_scanned(self, ctx, abspath: str) -> None:
        """Called by the Analyzer for every file it walks: live facts
        from the dataflows the walk already built.  A file whose rel
        collides across roots (or maps to a DIFFERENT abspath than the
        program enumerated) contributes nothing — overwriting would
        resolve one package's calls against another's facts."""
        if ctx.path in self._collisions:
            return
        known = self._rel_to_path.get(ctx.path)
        if known is not None and \
                os.path.realpath(known) != os.path.realpath(abspath):
            return
        self.facts[ctx.path] = extract_file_facts(
            ctx.path, abspath, ctx.tree, ctx.source, ctx.cfg_records,
            ctx.suppress_line, ctx.suppress_file, parents=ctx._parents)
        self._hashes[ctx.path] = _hash_source(ctx.source)
        self._suppress[ctx.path] = (ctx.suppress_line, ctx.suppress_file)
        self.scanned.add(ctx.path)

    # -- cache ---------------------------------------------------------------
    def _load_cache(self) -> Dict[str, dict]:
        if not self.cache_path or not os.path.exists(self.cache_path):
            return {}
        try:
            with open(self.cache_path, "r", encoding="utf-8") as f:
                data = json.load(f)
            if data.get("version") != CACHE_VERSION:
                return {}
            return data.get("files", {})
        except (OSError, ValueError):
            return {}               # corrupt cache: fall back to parsing

    def save_cache(self) -> None:
        """Persist every program file's facts keyed by (content hash,
        analysis-version stamp) — fail-soft (an unwritable cache
        degrades to re-parsing)."""
        if not self.cache_path:
            return
        entries = {rel: {"hash": self._hashes[rel],
                         "stamp": ANALYSIS_VERSION,
                         "facts": self.facts[rel]}
                   for rel in self.facts if rel in self._hashes}
        try:
            tmp = self.cache_path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"version": CACHE_VERSION, "files": entries}, f)
            os.replace(tmp, self.cache_path)
        except OSError:
            pass

    # -- linking -------------------------------------------------------------
    def link(self) -> None:
        cache = self._load_cache()
        for abspath, rel in self.program_files:
            if rel in self.facts:
                continue
            try:
                with open(abspath, "r", encoding="utf-8") as f:
                    source = f.read()
            except OSError:
                continue
            h = _hash_source(source)
            entry = cache.get(rel)
            if entry and entry.get("hash") == h and \
                    entry.get("stamp") == ANALYSIS_VERSION:
                # BOTH keys must match: a content hit from a cache
                # written by an older analysis version is STALE — its
                # facts predate the current extractor/rule shapes.
                self.facts[rel] = entry["facts"]
                self.cache_hits += 1
            else:
                facts = extract_standalone(rel, abspath, source)
                if facts is None:
                    continue        # unparseable: no facts, no summaries
                self.facts[rel] = facts
                self.cache_misses += 1
            self._hashes[rel] = h
        self.graph = CallGraph(self.facts)
        self.graph.resolve_all()
        # Second resolution pass (ISSUE 13): the returns-instance
        # fixpoint needs resolved factory calls, and factory-typed
        # receivers need returns-instance — resolve, compute, re-resolve
        # (the graph is cheap; the facts are not touched).
        self._compute_returns_instance()
        self.graph.clear_resolution()
        self.graph.resolve_all()
        self._compute_param_canon()
        self._compute_blocked()
        self._compute_clocked()
        self._compute_set_valued()
        self._compute_entry_locks()
        self._compute_acquires()
        self._compute_ownership()

    # -- summary fixpoints ---------------------------------------------------
    def _functions(self):
        for rel, f in self.facts.items():
            for qname, fn in f["functions"].items():
                yield rel, qname, fn, CallGraph.fid(rel, qname)

    def _escaped(self, rel: str, qname: str, fn: dict) -> bool:
        """All-callers-known is the premise of entry-lockset seeding AND
        lock-param unification; any way a hidden caller could exist
        breaks it: address-taken, a same-named call nobody resolved, or
        virtual dispatch (the method overrides / is overridden / sits
        under an unresolved base — `self.m()` in the base class runs
        the OVERRIDE at runtime, which static resolution cannot see)."""
        if qname in self.facts[rel]["escapes"]:
            return True
        if fn["name"] in self.graph.unresolved_names:
            return True
        cls = fn.get("cls")
        return cls is not None and \
            self.graph.virtually_dispatched(rel, cls, fn["name"])

    def _compute_blocked(self) -> None:
        """may-block-unbounded, LFP with a witness for chain rendering:
        witness = ('direct', line, desc) | ('call', line, callee fid).
        Propagates over PLAIN calls to SYNC callees only — an awaited
        callee's blocking is the await site's problem (FTL011), and an
        un-awaited async call never runs its body."""
        work: List[str] = []
        for rel, qname, fn, fid in self._functions():
            if fn["blocks"]:
                line, desc = fn["blocks"][0]
                self._blocked[fid] = ("direct", line, desc)
                work.append(fid)
        while work:
            target = work.pop()
            tfn = self.graph.function(target)
            if tfn is None or tfn["async"]:
                continue
            for caller, call in self.graph.callers.get(target, ()):
                if call[3]:         # awaited edge
                    continue
                if caller not in self._blocked:
                    self._blocked[caller] = ("call", call[0], target)
                    work.append(caller)

    def _compute_clocked(self) -> None:
        """may-read-wall-clock for REAL_ONLY-module functions: direct
        unsuppressed reads in functions that are NOT mode-guarded (no
        ``sim`` reference — ``EventLoop.now()``'s virtual/real branch is
        the sanctioned pattern), propagated through real-only-module
        callees.  Sim-reachable functions never propagate: their own
        direct reads are FTL001 findings already."""
        work: List[str] = []
        for rel, qname, fn, fid in self._functions():
            if _sim_reachable(rel) or fn["sim_ref"]:
                continue
            if fn["clock"]:
                line, name = fn["clock"][0]
                self._clocked[fid] = ("direct", line, name)
                work.append(fid)
        while work:
            target = work.pop()
            for caller, call in self.graph.callers.get(target, ()):
                rel = caller.partition("::")[0]
                if _sim_reachable(rel):
                    continue        # the FTL001 rule reports this edge
                cfn = self.graph.function(caller)
                if cfn is None or cfn["sim_ref"]:
                    continue
                tfn = self.graph.function(target)
                if tfn and tfn["async"] and not call[3]:
                    continue        # coroutine never awaited: no read
                if caller not in self._clocked:
                    self._clocked[caller] = ("call", call[0], target)
                    work.append(caller)

    def _compute_set_valued(self) -> None:
        """Set-valued returns, GREATEST fixpoint: start optimistic for
        every function whose returns are all set-shaped-or-call, then
        demote until stable — recursion (``def a(): return b()`` /
        ``def b(): return a()`` guarded by a base case returning a set)
        converges to True instead of diverging or defaulting False."""
        candidates: Dict[str, tuple] = {}
        for rel, qname, fn, fid in self._functions():
            if fn["returns"] and all(e != "other" for e in fn["returns"]):
                candidates[fid] = (rel, fn.get("cls"), fn["returns"])
        sv = set(candidates)
        changed = True
        while changed:
            changed = False
            for fid, (rel, cls, returns) in candidates.items():
                if fid not in sv:
                    continue
                if not all(self._eval_set(e, rel, cls, sv)
                           for e in returns):
                    sv.discard(fid)
                    changed = True
        # Groundedness (LFP): the optimism above keeps a PURE call
        # cycle with no base case "set-valued" forever — demand every
        # survivor reach at least one literal set return through the
        # chain (``return rec(x)`` / ``return rec2(x)`` alone proves
        # nothing; it never returns at all).
        grounded: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for fid, (rel, cls, returns) in candidates.items():
                if fid not in sv or fid in grounded:
                    continue
                if any(self._eval_grounded(e, rel, cls, grounded)
                       for e in returns):
                    grounded.add(fid)
                    changed = True
        self._set_valued = sv & grounded

    def _eval_set(self, entry, rel, cls, sv) -> bool:
        if entry == "set":
            return True
        if not isinstance(entry, list):
            return False
        kind = entry[0]
        if kind == "call":
            target = self.graph.resolve(rel, cls, entry[1:])
            return target in sv
        if kind == "any":
            return any(self._eval_set(e, rel, cls, sv) for e in entry[1])
        if kind == "all":
            return all(self._eval_set(e, rel, cls, sv) for e in entry[1])
        return False

    def _eval_grounded(self, entry, rel, cls, grounded) -> bool:
        if entry == "set":
            return True
        if not isinstance(entry, list):
            return False
        if entry[0] == "call":
            return self.graph.resolve(rel, cls, entry[1:]) in grounded
        return any(self._eval_grounded(e, rel, cls, grounded)
                   for e in entry[1])

    def _translate_locks(self, locks: Set[str], spec: List[str],
                         same_rel: bool) -> FrozenSet[str]:
        """Caller-frame lock keys that keep meaning in the callee's
        frame: ``self.*``/``cls.*`` survive self/cls/super dispatch
        (same object), bare module-level names survive same-module
        calls; everything else (locals, params, other objects) drops."""
        out = set()
        self_call = spec and spec[0] in ("self", "cls", "super")
        for k in sorted(locks):
            if k.startswith(("self.", "cls.")):
                if self_call:
                    out.add(k)
            elif "." not in k and same_rel:
                out.add(k)
        return frozenset(out)

    def _compute_entry_locks(self) -> None:
        """Caller-held locksets, top-down meet: entry(f) = ⋂ over every
        callsite of translate(canon(local lockset) ∪ entry(caller)).
        Only PRIVATE, non-escaped functions with at least one resolved
        caller are eligible — everything else enters with ∅ (a public
        function must stand on its own locks).  TOP (= every lock) is
        the optimistic start so recursion/SCCs converge downward."""
        eligible: Dict[str, tuple] = {}
        for rel, qname, fn, fid in self._functions():
            if fn["private"] and not self._escaped(rel, qname, fn) and \
                    self.graph.callers.get(fid):
                eligible[fid] = (rel, fn)
        entry: Dict[str, Optional[FrozenSet[str]]] = \
            {fid: None for fid in eligible}     # None = TOP
        for _ in range(50):
            changed = False
            for fid, (rel, fn) in eligible.items():
                val: Optional[FrozenSet[str]] = None
                for caller, call in self.graph.callers[fid]:
                    crel = caller.partition("::")[0]
                    canon = self._param_canon.get(caller, {})
                    locks = {canon.get(k, k) for k in call[2]}
                    ce = entry.get(caller, frozenset())
                    if ce is None:
                        continue    # caller still TOP: identity for meet
                    eff = self._translate_locks(
                        locks | set(ce), call[1], crel == rel)
                    val = eff if val is None else (val & eff)
                if val != entry[fid]:
                    entry[fid] = val
                    changed = True
            if not changed:
                break
        self._entry = entry

    def _compute_param_canon(self) -> None:
        """Unify each lock PARAMETER with the concrete lock its callers
        pass — agreement judged on OBJECT-SENSITIVE identities (ISSUE
        13), not source text: two callers spelling ``self._lock`` from
        different classes pass two different lock objects and must
        CONFLICT (FTL014), not unify.  All callers agree -> the param
        canonicalizes (textual key when every caller is same-instance
        self-dispatch, the qualified identity otherwise); disagree ->
        an FTL014 finding (the alias defeats lockset analysis)."""
        for rel, qname, fn, fid in self._functions():
            if not fn["lock_params"]:
                continue
            callers = self.graph.callers.get(fid, [])
            if not callers or self._escaped(rel, qname, fn):
                continue
            for p, pline in fn["lock_params"].items():
                try:
                    idx = fn["params"].index(p)
                except ValueError:
                    continue
                keys: Dict[str, List[str]] = {}
                texts: Dict[str, str] = {}
                fabricated: Set[str] = set()
                complete = True
                self_only = True
                for caller, call in callers:
                    shift = 1 if call[1] and \
                        call[1][0] in ("self", "cls", "super",
                                       "typed") else 0
                    k = None
                    for pos_or_name, lk in call[4]:
                        if pos_or_name == p or (
                                isinstance(pos_or_name, int) and
                                pos_or_name + shift == idx):
                            k = lk
                            break
                    if k is not None and "." not in k and \
                            k in (self.graph.function(caller) or
                                  {}).get("params", ()):
                        # The caller's OWN param passed through: its
                        # concrete lock is whatever the caller's callers
                        # pass — use the caller's canon when computed,
                        # else UNKNOWN (a fabricated per-caller key here
                        # would falsely conflict same-lock passthrough
                        # wrappers).  One pass, no fixpoint: an
                        # unresolved chain just stays un-canonicalized.
                        k = self._param_canon.get(caller, {}).get(k)
                    if k is None:
                        complete = False
                    else:
                        crel = caller.partition("::")[0]
                        cfn = self.graph.function(caller)
                        ccls = cfn.get("cls") if cfn else None
                        if "#" in k:
                            qs = [k]    # already a qualified identity
                        else:
                            qs = self.lock_identities(crel, ccls, k)
                        if qs:
                            qk = qs[0]
                        else:
                            # A lock with NO shared identity (caller's
                            # function-local): the per-caller key below
                            # serves grouping/conflict detection only —
                            # it must never leak out as a canon value
                            # (a fresh-per-call lock is not a concrete
                            # identity two threads can contend on).
                            qk = f"{caller}#{k}"
                            fabricated.add(qk)
                        keys.setdefault(qk, []).append(
                            f"{caller}:{call[0]}")
                        texts.setdefault(qk, k)
                        if not (call[1] and
                                call[1][0] in ("self", "cls", "super")):
                            self_only = False
                if len(keys) == 1 and complete:
                    qk = next(iter(keys))
                    if qk not in fabricated:
                        self._param_canon.setdefault(fid, {})[p] = \
                            texts[qk] if self_only else qk
                elif len(keys) >= 2:
                    self.param_conflicts.append(
                        (rel, qname, pline, p,
                         {qk: sorted(v) for qk, v in keys.items()}))

    def _compute_returns_instance(self) -> None:
        """returns-instance summary (ISSUE 13), LFP: a function every
        return of which resolves to the SAME in-package class returns
        an instance of it — constructor returns ground the fixpoint,
        factory-through-factory chains converge by iteration.  Feeds
        receiver-typed call resolution (``x = make(); x.m()``) and
        FTL016's factory-created promises."""
        ri = self.graph.returns_instance
        cands = []
        for rel, qname, fn, fid in self._functions():
            rts = fn.get("rets_type") or []
            if rts and all(t != "other" for t in rts):
                cands.append((fid, rel, fn.get("cls"), rts))
        changed = True
        while changed:
            changed = False
            for fid, rel, cls, rts in cands:
                if fid in ri:
                    continue
                vals = {self.graph.resolve_type(rel, cls, list(t))
                        for t in rts}
                if len(vals) == 1:
                    v = vals.pop()
                    if v is not None:
                        ri[fid] = v
                        changed = True

    # -- container ownership protocol (ISSUE 20) -----------------------------
    def _compute_ownership(self) -> None:
        """The FTL017 producer/consumer protocol, composed bottom-up:
        an LFP over param forwarding lifts "resolves its param" through
        pass-the-promise helper chains, then every drain site — direct,
        or a forward whose callee's matching param resolves — marks its
        FIELD IDENTITY (allocation-site owner through the MRO, exactly
        like lock identities) as drained.  Unknown callees contribute
        nothing: a forward the graph cannot resolve never sanctions a
        registry."""
        rp: Dict[str, Set[str]] = {}
        for rel, qname, fn, fid in self._functions():
            if fn.get("resolver_params"):
                rp[fid] = set(fn["resolver_params"])

        def forwarded_resolves(rel, cls, spec, i) -> bool:
            target = self.graph.resolve(rel, cls, list(spec))
            if target is None:
                return False
            tfn = self.graph.function(target)
            if tfn is None:
                return False
            shift = 1 if spec and spec[0] in ("self", "cls", "super",
                                              "typed") else 0
            tparams = tfn.get("params", [])
            j = i + shift
            return j < len(tparams) and tparams[j] in rp.get(target, ())

        changed = True
        while changed:
            changed = False
            for rel, qname, fn, fid in self._functions():
                for param, spec, i in fn.get("param_forwards", ()):
                    if param in rp.get(fid, ()):
                        continue
                    if forwarded_resolves(rel, fn.get("cls"), spec, i):
                        rp.setdefault(fid, set()).add(param)
                        changed = True
        self._resolver_params = rp

        drained: Set[tuple] = set()
        for rel, qname, fn, fid in self._functions():
            cls = fn.get("cls")
            if cls is None:
                continue
            for attr in fn.get("drains", ()):
                drained.add(self.field_identity(rel, cls, attr))
            for attr, spec, i in fn.get("drain_forwards", ()):
                if forwarded_resolves(rel, cls, spec, i):
                    drained.add(self.field_identity(rel, cls, attr))
        self._drained = drained

    def field_identity(self, rel: str, cls: str, attr: str) -> tuple:
        """(rel, class, attr) keyed by the base-most assigner through
        the MRO — Sub parking into an inherited registry and Base
        draining it agree on ONE field."""
        owner = self.graph.attr_owner(rel, cls, attr)
        return (owner[0], owner[1], attr)

    def field_drained(self, rel: str, cls: str, attr: str) -> bool:
        return self.field_identity(rel, cls, attr) in self._drained

    def owned_line(self, rel: str, line: int) -> bool:
        """``# flowlint: owned -- why`` on the creation line — the
        FTL017 justified-escape hatch."""
        return line in self.facts.get(rel, {}).get("owned", ())

    # -- object-sensitive lock identity (ISSUE 13) ---------------------------
    def lock_identities(self, rel: str, cls: Optional[str],
                        key: str) -> List[str]:
        """Identities for a textual lock key seen in (rel, cls), keyed
        by (class, attr, instance role) instead of source text:

          * ``self._lock`` -> ``<rel>::<AllocOwner>#_lock`` — the
            allocation-site owner through the MRO, so Base and Sub
            methods locking the inherited lock agree, while two CLASSES
            each allocating a ``self._lock`` get distinct identities;
          * ``self.a._lock`` -> the ROLE identity ``<rel>::<C>#a._lock``
            (two instances held in different fields never alias) PLUS,
            when ``a``'s class is known, the class-generic identity of
            the rest rebased onto it (roles still participate in
            class-level ordering — the AB/BA cycle through a field);
          * a bare module-level lock -> ``<rel>#<name>``; a bare
            function-local lock has NO shared identity (fresh per call)
            and contributes nothing;
          * a container element key ``self._locks[*]`` (ISSUE 20)
            carries its may-alias marker through: the identity is the
            ALLOCATION SITE of the container, same as a scalar attr —
            ``<rel>::<AllocOwner>#_locks[*]``.
        """
        suffix = ""
        if key.endswith("[*]"):
            key, suffix = key[:-3], "[*]"
        parts = key.split(".")
        if parts[0] in ("self", "cls"):
            if cls is None or len(parts) < 2:
                return []
            owner = self.graph.attr_owner(rel, cls, parts[1])
            out = [f"{owner[0]}::{owner[1]}#"
                   f"{'.'.join(parts[1:])}{suffix}"]
            if len(parts) > 2:
                t = self.graph.attr_type(rel, cls, parts[1])
                if t is not None:
                    out.extend(self.lock_identities(
                        t[0], t[1],
                        "self." + ".".join(parts[2:]) + suffix))
            return out
        if len(parts) == 1 and not suffix and \
                key in self.facts.get(rel, {}).get("module_locks", ()):
            return [f"{rel}#{key}"]
        # Bare function-locals AND dotted non-self paths (a local
        # instance's lock, a module-attr lock): no shared identity —
        # keying them by source text would alias every same-spelled
        # local across functions (false cycles, the unsound direction).
        return []

    def _acq_entry(self, rel: str, fn: dict, fid: str,
                   key: str) -> Optional[tuple]:
        """may-acquire entry for one direct acquisition: self-rooted
        keys stay SYMBOLIC (rebound through the receiver role at each
        call edge); module locks are concrete; canonicalized lock
        params adopt their canon; locals contribute nothing."""
        parts = key.split(".")
        if parts[0] in ("self", "cls"):
            return ("S", key)
        if "." not in key:
            if key in self.facts.get(rel, {}).get("module_locks", ()):
                return ("C", f"{rel}#{key}")
            if key in fn.get("lock_params", {}):
                canon = self._param_canon.get(fid, {}).get(key)
                if canon is None:
                    return None
                if "#" in canon:
                    return ("C", canon)
                if canon.split(".")[0] in ("self", "cls"):
                    return ("S", canon)
            return None
        return None

    def _transfer_entry(self, e: tuple, spec,
                        target: str) -> List[tuple]:
        """Transform one may-acquire entry of `target` into the frame
        of a caller dispatching via `spec`: self-dispatch keeps the
        symbol (same instance); ``self.X.m()`` rebinds ``self.`` to the
        receiver role ``self.X.``; everything else concretizes to the
        callee's own class-generic identities."""
        if e[0] == "C":
            return [e]
        key = e[1]
        k0 = spec[0] if spec else None
        if k0 in ("self", "cls", "super"):
            return [e]
        if k0 == "typed" and spec[1][0] == "selfattr" and \
                key.split(".")[0] == "self":
            newkey = "self." + spec[1][1] + key[4:]
            if newkey.count(".") <= 4:
                return [("S", newkey)]
        trel = target.partition("::")[0]
        tfn = self.graph.function(target)
        tcls = tfn.get("cls") if tfn else None
        return [("C", i) for i in self.lock_identities(trel, tcls, key)]

    def _compute_acquires(self) -> None:
        """may-acquire, LFP with witnesses: every lock (identity or
        self-rooted symbol) a function may take, directly or through
        any chain of plain sync calls — awaited edges are FTL011's,
        async bodies never run un-awaited (the may-block precedent)."""
        T: Dict[str, Dict[tuple, tuple]] = {}
        for rel, qname, fn, fid in self._functions():
            d = {}
            for line, key, held in fn.get("acquisitions", ()):
                e = self._acq_entry(rel, fn, fid, key)
                if e is not None and e not in d:
                    d[e] = ("direct", line)
            if d:
                T[fid] = d
        work = sorted(T)
        in_work = set(work)
        while work:
            target = work.pop()
            in_work.discard(target)
            tfn = self.graph.function(target)
            if tfn is None or tfn["async"]:
                continue
            entries = list(T.get(target, ()))
            for caller, call in self.graph.callers.get(target, ()):
                if call[3]:         # awaited edge
                    continue
                td = T.setdefault(caller, {})
                added = False
                for e in entries:
                    for e2 in self._transfer_entry(e, call[1], target):
                        if e2 not in td:
                            td[e2] = ("call", call[0], target, e)
                            added = True
                if added and caller not in in_work:
                    in_work.add(caller)
                    work.append(caller)
        self._acq = T

    def lock_cycles(self) -> List[dict]:
        """FTL015: the lock-order graph (edge A -> B = B acquired while
        A held, directly or through the composed may-acquire summary,
        on object-sensitive identities) and its elementary cycles.
        Returns [{path, line, message}] — one per distinct cycle, each
        edge carrying its acquisition-chain witness; cycles with no
        witness site in a scanned file are dropped (nowhere to
        report)."""
        adj: Dict[str, Dict[str, tuple]] = {}

        def add(src: str, dst: str, wit: tuple) -> None:
            if src == dst:
                return              # reentrant same-identity nesting
                #                     (RLock, role self-aliasing): not
                #                     an ORDERING hazard between locks
            adj.setdefault(src, {}).setdefault(dst, wit)

        def add_pair(held_ids: List[str], acq_ids: List[str],
                     wit: tuple) -> None:
            # Pair identities BY LEVEL — role-to-role and generic-to-
            # generic (a lock's identity list runs role-most to
            # generic-most) — never role-to-generic cross products:
            # those duplicate every role-level cycle once more through
            # the class-generic node.
            if not held_ids or not acq_ids:
                return
            add(held_ids[0], acq_ids[0], wit)
            add(held_ids[-1], acq_ids[-1], wit)

        for rel, qname, fn, fid in sorted(self._functions(),
                                          key=lambda t: t[3]):
            cls = fn.get("cls")
            canon = self._param_canon.get(fid, {})

            def ids_of(key: str) -> List[str]:
                k = canon.get(key, key)
                if "#" in k:
                    return [k]
                return self.lock_identities(rel, cls, k)

            for line, key, held in fn.get("acquisitions", ()):
                for h in held:
                    add_pair(ids_of(h), ids_of(key),
                             (rel, fid, line, None, None))
            for call, target in self.calls_with_targets(fid):
                line, spec, locks, awaited = call[0], call[1], \
                    call[2], call[3]
                if not locks or target is None or awaited:
                    continue
                tfn = self.graph.function(target)
                if tfn is None or tfn["async"]:
                    continue
                for e in self._acq.get(target, ()):
                    for e2 in self._transfer_entry(e, spec, target):
                        dqs = [e2[1]] if e2[0] == "C" else \
                            self.lock_identities(rel, cls, e2[1])
                        for h in locks:
                            add_pair(ids_of(h), dqs,
                                     (rel, fid, line, target, e))

        cycles: List[List[str]] = []
        seen_sets: Set[FrozenSet[str]] = set()

        def dfs(start: str, cur: str, path: List[str]) -> None:
            if len(cycles) >= 20:
                return
            for nxt in sorted(adj.get(cur, ())):
                if nxt == start and len(path) >= 2:
                    key = frozenset(path)
                    if key not in seen_sets:
                        seen_sets.add(key)
                        cycles.append(list(path))
                elif nxt > start and nxt not in path and len(path) < 6:
                    dfs(start, nxt, path + [nxt])

        for start in sorted(adj):
            dfs(start, start, [start])

        out = []
        for cyc in cycles:
            edges = []
            for i, a in enumerate(cyc):
                b = cyc[(i + 1) % len(cyc)]
                edges.append((a, b, adj[a][b]))
            site = next(((w[0], w[2]) for _, _, w in edges
                         if w[0] in self.scanned), None)
            if site is None:
                continue
            parts = []
            for a, b, (wrel, wfid, wline, wtarget, wentry) in edges:
                hop = f"{a} then {b} at {wfid} line {wline}"
                if wtarget is not None:
                    chain = self._acq_chain(wtarget, wentry)
                    if chain:
                        hop += " (via " + " -> ".join(chain) + ")"
                parts.append(hop)
            ring = " -> ".join(cyc + [cyc[0]])
            out.append({
                "path": site[0], "line": site[1],
                "message": (
                    f"lock-ordering cycle {ring}: " + "; ".join(parts) +
                    " — threads interleaving these acquisition orders "
                    "deadlock; impose one global order, or suppress "
                    "with the reason the orders never run "
                    "concurrently")})
        return out

    def _acq_chain(self, target: str, entry: tuple) -> List[str]:
        """Render the acquisition chain behind one composed edge."""
        out: List[str] = []
        cur_f, cur_e = target, entry
        for _ in range(12):
            w = self._acq.get(cur_f, {}).get(cur_e)
            if w is None:
                break
            if w[0] == "direct":
                out.append(f"{cur_f} line {w[1]}: acquires")
                break
            out.append(f"{cur_f} line {w[1]}")
            cur_f, cur_e = w[2], w[3]
        return out

    # -- rule-facing queries -------------------------------------------------
    def resolve_type(self, rel: str, cls_name: Optional[str],
                     texpr) -> Optional[Tuple[str, str]]:
        """(rel, class name) a type expression denotes — the local
        type-inference result, resolved against the class tables and
        returns-instance summaries (FTL016's promise classification)."""
        return self.graph.resolve_type(rel, cls_name, list(texpr))

    def entry_locks(self, rel: str, qname: str) -> FrozenSet[str]:
        v = self._entry.get(CallGraph.fid(rel, qname))
        return v if v else frozenset()

    def param_canon(self, rel: str, qname: str) -> Dict[str, str]:
        return self._param_canon.get(CallGraph.fid(rel, qname), {})

    def may_block(self, fid: Optional[str]) -> bool:
        return fid is not None and fid in self._blocked

    def may_clock(self, fid: Optional[str]) -> bool:
        return fid is not None and fid in self._clocked

    def set_valued(self, fid: Optional[str]) -> bool:
        return fid is not None and fid in self._set_valued

    def resolve(self, rel: str, cls_name: Optional[str],
                spec) -> Optional[str]:
        return self.graph.resolve(rel, cls_name, list(spec))

    def _chain(self, witness_map: Dict[str, tuple],
               fid: str) -> List[str]:
        out, cur = [], fid
        for _ in range(20):
            w = witness_map.get(cur)
            if w is None:
                break
            if w[0] == "direct":
                out.append(f"{cur} line {w[1]}: {w[2]}")
                break
            out.append(f"{cur} line {w[1]}")
            cur = w[2]
        return out

    def block_chain(self, fid: str) -> List[str]:
        return self._chain(self._blocked, fid)

    def clock_chain(self, fid: str) -> List[str]:
        return self._chain(self._clocked, fid)

    def iter_scanned_functions(self):
        """(rel, qname, fn facts, fid) for every function of every
        SCANNED file — where interprocedural findings may be reported."""
        for rel in sorted(self.scanned):
            f = self.facts.get(rel)
            if not f:
                continue
            for qname, fn in sorted(f["functions"].items()):
                yield rel, qname, fn, CallGraph.fid(rel, qname)

    def calls_with_targets(self, fid: str):
        """[(call record, resolved callee fid or None)] for one
        function (call record: [line, spec, locks, awaited,
        lock_args])."""
        return self.graph.calls_of.get(fid, [])

    def is_suppressed(self, rule_id: str, rel: str, line: int) -> bool:
        sup = self._suppress.get(rel)
        if sup is None:
            return False            # findings only land in scanned files
        return _line_suppressed(rule_id, line, sup[0], sup[1])

    def dump_callgraph(self) -> List[Dict[str, object]]:
        return self.graph.dump() if self.graph else []
