"""fdbrestore: restore CLI (reference fdbbackup/backup.actor.cpp, the
fdbrestore program alias).  Thin entry point over tools/fdbbackup.py.

    python -m foundationdb_tpu.tools.fdbrestore start \
        -C 127.0.0.1:4770 -r file:///tmp/backups/b1
"""

import sys

from .fdbbackup import main

if __name__ == "__main__":
    sys.exit(main(restore_mode=True))
