"""fdbcli: the interactive/administrative command surface.

Reference: fdbcli/fdbcli.actor.cpp (+ one file per command, e.g.
ExcludeCommand.actor.cpp) — get/set/clear/getrange data commands, status,
configure, exclude/include, consistency check.  Connects like any client
(client/database.open_cluster) and speaks only public surfaces: ordinary
transactions, the management API's \xff/conf keys, and the status
document — no private channel into the cluster.

    python -m foundationdb_tpu.tools.fdbcli -C 127.0.0.1:4700 \
        [--exec "set k v; get k; status"]

Without --exec, reads commands from stdin (one per line; `help` lists
them).  Keys/values accept backslash-x hex escapes.
"""

from __future__ import annotations

import argparse
import json
import shlex
import sys
from typing import List, Optional


def _unescape(s: str) -> bytes:
    return s.encode("utf-8").decode("unicode_escape").encode("latin-1")


def _printable(b: bytes) -> str:
    return "".join(chr(c) if 32 <= c < 127 else "\\x%02x" % c for c in b)


HELP = """\
Commands (reference fdbcli command set):
  get KEY                    read one key
  set KEY VALUE              write one key
  clear KEY                  clear one key
  clearrange BEGIN END       clear a range
  getrange BEGIN END [N]     read up to N (default 25) pairs
  status [json]              cluster status summary (or the raw document)
  metrics [FILTER]           per-stage latency bands + role counters
                             (FILTER substring narrows both sections)
  top                        cluster heat: hot conflict ranges, read-hot
                             shards, busiest tags/tenants
  configure FIELD=VALUE ...  change configuration transactionally
  getconfiguration           committed \\xff/conf overrides
  lock                       reject non-LOCK_AWARE commits (prints uid)
  unlock UID                 release the database lock
  exclude TAG [TAG...]       drain + exclude storage servers by tag
  include [TAG...]           re-admit excluded servers (no args: all)
  excluded                   list excluded tags
  tenant create NAME         create a tenant (idempotent)
  tenant delete NAME         delete an (empty) tenant
  tenant list [BEGIN [END]]  list tenants by name range
  tenant get NAME            one tenant's id/prefix
  quota set NAME TPS         per-tenant transaction-rate quota
  quota clear NAME           remove a tenant's quota
  quota get [NAME]           committed quotas (all, or one tenant's)
  watch KEY                  block until KEY changes once
  help                       this text
  exit / quit
"""


class Cli:
    def __init__(self, cluster_spec: str) -> None:
        from ..client.database import open_cluster
        self.loop, self.db = open_cluster(cluster_spec)

    def run_async(self, coro, timeout: float = 30.0):
        return self.loop.run_until(self.loop.spawn(coro), timeout=timeout)

    async def _txn(self, fn):
        t = self.db.create_transaction()
        from ..core.error import FdbError
        while True:
            try:
                r = await fn(t)
                await t.commit()
                return r
            except FdbError as e:
                await t.on_error(e)

    # -- commands ------------------------------------------------------------
    def cmd_get(self, key: str) -> str:
        async def go(t):
            return await t.get(_unescape(key))
        v = self.run_async(self._txn(go))
        return (f"`{key}' is `{_printable(v)}'" if v is not None
                else f"`{key}': not found")

    def cmd_set(self, key: str, value: str) -> str:
        async def go(t):
            t.set(_unescape(key), _unescape(value))
        self.run_async(self._txn(go))
        return "Committed"

    def cmd_clear(self, key: str) -> str:
        async def go(t):
            t.clear(_unescape(key))
        self.run_async(self._txn(go))
        return "Committed"

    def cmd_clearrange(self, begin: str, end: str) -> str:
        async def go(t):
            t.clear(_unescape(begin), _unescape(end))
        self.run_async(self._txn(go))
        return "Committed"

    def cmd_getrange(self, begin: str, end: str, limit: str = "25") -> str:
        async def go(t):
            return await t.get_range(_unescape(begin), _unescape(end),
                                     limit=int(limit))
        rows = self.run_async(self._txn(go))
        out = [f"`{_printable(k)}' is `{_printable(v)}'" for k, v in rows]
        out.append(f"({len(rows)} results)")
        return "\n".join(out)

    def cmd_status(self, mode: str = "") -> str:
        async def go():
            return await self.db.cluster.get_status()
        doc = self.run_async(go())
        if mode == "json":
            return json.dumps(doc, indent=2, default=str)
        cl = doc.get("cluster", {})
        data = cl.get("data", {})
        lines = [
            "Configuration:",
            f"  Redundancy mode        - {cl.get('configuration', {})}",
            "Cluster:",
            f"  Recovery state         - {cl.get('recovery_state', '?')}",
            f"  Epoch                  - {cl.get('generation', '?')}",
            f"  Workers                - {cl.get('machines', '?')}",
            "Data:",
            f"  State                  - "
            f"{data.get('state', {}).get('name', '?')}",
            f"  KV size               - "
            f"{data.get('total_kv_size_bytes', '?')} bytes",
            "Database:",
            f"  Available              - "
            f"{doc.get('client', {}).get('database_status', {})}",
        ]
        regions = cl.get("regions") or {}
        if regions.get("configured"):
            lines += [
                "Regions:",
                f"  Replication            - "
                f"{regions.get('replication', '?')}"
                f" (remote dc {regions.get('remote_dc', '?')!r},"
                f" {regions.get('log_routers', 0)} routers /"
                f" {regions.get('remote_tlogs', 0)} remote logs /"
                f" {regions.get('remote_replicas', 0)} replicas)",
            ]
        fo = regions.get("failover")
        if fo:
            lines += [
                f"  Last failover          - epoch {fo.get('epoch')}"
                f" at version {fo.get('failover_version')}"
                f" ({'drained' if fo.get('drained') else 'UNDRAINED: '}"
                + ("" if fo.get("drained")
                   else f"{fo.get('lost_tail_versions')} versions of "
                        f"acked tail lost") + ")",
            ]
        return "\n".join(lines)

    def cmd_metrics(self, group: str = "") -> str:
        """Commit-pipeline observability (ISSUE 3): per-stage latency
        bands (cluster.latency_statistics) and per-group counter sums
        (cluster.metrics) from the status document.  An optional FILTER
        substring narrows BOTH sections (e.g. `metrics tlog`)."""
        async def go():
            return await self.db.cluster.get_status()
        cl = self.run_async(go()).get("cluster", {})
        needle = group.lower()
        bands = {n: b for n, b in
                 (cl.get("latency_statistics", {}) or {}).items()
                 if needle in n.lower()}
        counters = {g: c for g, c in (cl.get("metrics", {}) or {}).items()
                    if needle in g.lower()}
        lines = ["Latency bands (ms):",
                 f"  {'stage':<24}{'count':>8}{'mean':>9}{'p50':>9}"
                 f"{'p95':>9}{'p99':>9}{'max':>9}"]
        for name in sorted(bands):
            b = bands[name]
            lines.append(
                f"  {name:<24}{b['count']:>8}"
                f"{b['mean'] * 1e3:>9.3f}{b['p50'] * 1e3:>9.3f}"
                f"{b['p95'] * 1e3:>9.3f}{b['p99'] * 1e3:>9.3f}"
                f"{b['max'] * 1e3:>9.3f}")
        if len(lines) == 2:
            lines.append(f"  (no samples{' matching ' + group if group else ' yet'})")
        lines.append("Counters:")
        if not counters:
            lines.append(f"  (no counters{' matching ' + group if group else ''})")
        for g in sorted(counters):
            vals = ", ".join(f"{k}={v}" for k, v in
                             sorted(counters[g].items()))
            lines.append(f"  {g}: {vals}")
        # Partitioned resolution plane (ISSUE 7): per-resolver conflict
        # stats + backend supervision keyed by resolver id, and the
        # generation's key-range ownership.
        res = cl.get("resolution", {}) or {}
        if res.get("resolvers") and (not needle or
                                     needle in "resolution resolvers"):
            lines.append(f"Resolution plane ({res.get('count', 0)} "
                         "resolvers):")
            lines.append(f"  {'resolver':<22}{'resolved':>10}"
                         f"{'conflicts':>10}{'p95 ms':>9}  backend")
            for rid in sorted(res["resolvers"]):
                r = res["resolvers"][rid]
                if not r.get("txn_resolved") and "reachable" in r:
                    lines.append(f"  {rid:<22}{'(unreachable)':>10}")
                    continue
                band = r.get("resolve") or {}
                p95 = (f"{band['p95'] * 1e3:.3f}" if band else "-")
                cb = r.get("conflict_backend") or {}
                state = ("degraded" if cb.get("degraded")
                         else "ok" if cb else "-")
                lines.append(
                    f"  {rid:<22}{r.get('txn_resolved', 0):>10}"
                    f"{r.get('txn_conflicts', 0):>10}{p95:>9}  {state}")
            for rr in res.get("ranges", []):
                lines.append(f"    [{rr['begin']!r}, {rr['end']!r}) -> "
                             f"{rr['resolver']}")
        # Conflict-aware scheduling plane (ISSUE 12): predictor
        # deferrals, reorder swaps, repair counters per proxy — the same
        # cluster.scheduler document the special keys mirror.
        sched = cl.get("scheduler", {}) or {}
        if sched and (not needle or needle in "scheduler sched"):
            en = sched.get("enabled", {})
            tot = sched.get("totals", {})
            lines.append(
                "Scheduler (predictor="
                f"{'on' if en.get('predictor') else 'off'}, reorder="
                f"{'on' if en.get('reorder') else 'off'}, repair="
                f"{'on' if en.get('repair') else 'off'}):")
            lines.append(
                f"  totals: deferrals={tot.get('deferrals', 0)} "
                f"reorder_swaps={tot.get('reorder_swaps', 0)} "
                f"repairs={tot.get('repairs_attempted', 0)}"
                f"/{tot.get('repairs_succeeded', 0)} ok"
                f"/{tot.get('repairs_exhausted', 0)} exhausted")
            for pid in sorted(sched.get("grv_proxies", {})):
                p = sched["grv_proxies"][pid]
                doomed = ",".join(p.get("doomed_tags", [])) or "-"
                lines.append(
                    f"  grv {pid}: deferrals={p.get('deferrals', 0)} "
                    f"held={p.get('deferred_held', 0)} "
                    f"ranges={p.get('tracked_ranges', 0)} "
                    f"doomed_tags={doomed}")
            for pid in sorted(sched.get("commit_proxies", {})):
                p = sched["commit_proxies"][pid]
                lines.append(
                    f"  proxy {pid}: reorder="
                    f"{p.get('reorder_swaps', 0)} swaps"
                    f"/{p.get('reorder_batches', 0)} batches "
                    f"repairs={p.get('repairs_attempted', 0)}"
                    f"/{p.get('repairs_succeeded', 0)} ok"
                    f"/{p.get('repairs_exhausted', 0)} exhausted")
        # Gray-failure plane (ISSUE 18): the same cluster.peer_health
        # document status JSON carries and \xff\xff/metrics/peer_health/
        # mirrors — three surfaces, one source.
        ph = cl.get("peer_health", {}) or {}
        if (ph.get("links") or ph.get("degraded_processes")) and \
                (not needle or needle in "peer health peer_health"):
            lines.append(
                "Peer health (degraded links; process conviction needs "
                f">={ph.get('required_reporters', '?')} reporters):")
            lines.append(f"  {'reporter':<22}{'peer':<22}{'rtt ms':>9}"
                         f"{'to frac':>9}{'age s':>8}")
            for row in ph.get("links", []):
                rtt = row.get("rtt_ema")
                lines.append(
                    f"  {row.get('reporter', '?'):<22}"
                    f"{row.get('peer', '?'):<22}"
                    f"{(rtt * 1e3 if rtt is not None else 0):>9.2f}"
                    f"{row.get('timeout_fraction') or 0:>9.2f}"
                    f"{row.get('report_age') or 0:>8.1f}")
            for entry in ph.get("degraded_processes", []):
                lines.append(
                    f"  DEGRADED {entry.get('address', '?')} "
                    f"(worker {entry.get('worker') or '?'}; reporters: "
                    f"{', '.join(entry.get('reporters', []))})")
        return "\n".join(lines)

    def cmd_top(self) -> str:
        """Cluster heat telemetry (ISSUE 8): the three tables of
        status cluster.heat — per-resolver decayed hot CONFLICT ranges
        (exact abort attribution), per-storage read-hot shards, and the
        busiest tags/tenants by conflicts — the same document the
        \\xff\\xff/metrics/ special keys mirror."""
        async def go():
            return await self.db.cluster.get_status()
        heat = self.run_async(go()).get("cluster", {}).get("heat", {}) or {}
        lines = ["Hot conflict ranges (decayed, per resolver):",
                 f"  {'resolver':<12}{'begin':<22}{'end':<22}"
                 f"{'conflicts':>10}{'load':>8}"]
        n = len(lines)
        for rid in sorted(heat.get("conflict_ranges", {})):
            for row in heat["conflict_ranges"][rid].get(
                    "top_conflict_ranges", []):
                lines.append(
                    f"  {rid:<12}{row['begin']:<22.22}{row['end']:<22.22}"
                    f"{row['conflicts']:>10}{row['load']:>8}")
        if len(lines) == n:
            lines.append("  (no conflicts attributed yet)")
        lines.append("Read-hot shards:")
        lines.append(f"  {'storage':<12}{'begin':<22}{'end':<22}"
                     f"{'ops/s':>10}{'bytes/s':>12}")
        n = len(lines)
        for tag in sorted(heat.get("read_hot_ranges", {})):
            for row in heat["read_hot_ranges"][tag]:
                lines.append(
                    f"  {row['storage_server']:<12}{row['begin']:<22.22}"
                    f"{row['end']:<22.22}{row['read_ops_per_sec']:>10.1f}"
                    f"{row['read_bytes_per_sec']:>12.1f}")
        if len(lines) == n:
            lines.append("  (no read-hot shards)")
        lines.append("Busiest tags / tenants (by attributed conflicts):")
        rows = [f"  tag {r['tag']}: {r['conflicts']}"
                for r in heat.get("busiest_tags", [])]
        rows += [f"  tenant {r['tenant_id']}: {r['conflicts']}"
                 for r in heat.get("busiest_tenants", [])]
        lines.extend(rows or ["  (none)"])
        return "\n".join(lines)

    def cmd_configure(self, *assignments: str) -> str:
        from ..client.management import change_configuration
        fields = {}
        for a in assignments:
            if "=" not in a:
                return f"bad assignment `{a}' (want FIELD=VALUE)"
            k, v = a.split("=", 1)
            fields[k] = v
        self.run_async(change_configuration(self.db, **fields), timeout=60)
        return "Configuration changed"

    def cmd_lock(self) -> str:
        from ..client.management import lock_database
        uid = self.run_async(lock_database(self.db), timeout=60)
        return (f"Database locked (uid {uid.decode()}). Only LOCK_AWARE "
                "transactions commit until `unlock <uid>'.")

    def cmd_unlock(self, uid: str) -> str:
        from ..client.management import unlock_database
        self.run_async(unlock_database(self.db, uid.encode()), timeout=60)
        return "Database unlocked"

    def cmd_getconfiguration(self) -> str:
        from ..client.management import get_configuration
        conf = self.run_async(get_configuration(self.db))
        if not conf:
            return "(all defaults)"
        return "\n".join(f"{k} = {v.decode(errors='replace')}"
                         for k, v in sorted(conf.items()))

    def cmd_exclude(self, *tags: str) -> str:
        from ..client.management import exclude_servers
        self.run_async(exclude_servers(self.db, [int(t) for t in tags]))
        return f"Excluded tags {', '.join(tags)} (draining in background)"

    def cmd_include(self, *tags: str) -> str:
        from ..client.management import include_servers
        self.run_async(include_servers(
            self.db, [int(t) for t in tags] if tags else None))
        return "Included"

    def cmd_excluded(self) -> str:
        from ..client.management import excluded_servers
        tags = self.run_async(excluded_servers(self.db))
        return f"Excluded tags: {tags or 'none'}"

    def cmd_setknob(self, name: str, value: str = "",
                    scope: str = "server") -> str:
        """setknob NAME VALUE [scope] — live dynamic-knob change (empty
        VALUE clears the override)."""
        from ..client.management import set_knob
        self.run_async(set_knob(self.db, name, value or None, scope=scope))
        return (f"Knob {scope}/{name} "
                f"{'cleared' if not value else 'set to ' + value} "
                "(workers apply without restart)")

    def cmd_getknobs(self) -> str:
        from ..client.management import get_knob_overrides
        overrides = self.run_async(get_knob_overrides(self.db))
        if not overrides:
            return "No dynamic knob overrides"
        return "\n".join(f"{k} = {v}" for k, v in sorted(overrides.items()))

    def cmd_cache_range(self, action: str, begin: str,
                        end: str = "") -> str:
        """cache_range set BEGIN END | cache_range clear BEGIN"""
        from ..client.management import cache_range, uncache_range
        if action == "set":
            self.run_async(cache_range(self.db, _unescape(begin),
                                       _unescape(end)))
            return f"Caching [{begin}, {end})"
        if action == "clear":
            self.run_async(uncache_range(self.db, _unescape(begin)))
            return f"Uncached range at {begin}"
        return "usage: cache_range set BEGIN END | cache_range clear BEGIN"

    def cmd_coordinators(self, *spec: str) -> str:
        """coordinators                 — show the committed quorum spec
           coordinators ip:port,...    — changeQuorum to the new spec"""
        from ..client.management import (change_coordinators,
                                         get_coordinators)
        if not spec:
            cur = self.run_async(get_coordinators(self.db))
            return f"Coordinators: {cur or '(boot spec; never changed)'}"
        new_spec = ",".join(spec)
        self.run_async(change_coordinators(self.db, new_spec))
        return (f"Coordinators changing to {new_spec} (the master moves "
                "the quorum and recovers; clients follow the forward)")

    def cmd_tenant(self, action: str, *args: str) -> str:
        """tenant create/delete/list/get (reference fdbcli tenant
        command family, TenantManagement)."""
        from ..tenant import management as tm
        if action == "create" and len(args) == 1:
            entry = self.run_async(
                tm.create_tenant(self.db, _unescape(args[0])))
            return (f"The tenant `{args[0]}' has been created "
                    f"(id {entry.id}, prefix {_printable(entry.prefix)})")
        if action == "delete" and len(args) == 1:
            self.run_async(tm.delete_tenant(self.db, _unescape(args[0])))
            return f"The tenant `{args[0]}' has been deleted"
        if action == "list" and len(args) <= 2:
            begin = _unescape(args[0]) if args else b""
            end = _unescape(args[1]) if len(args) > 1 else b"\xff"
            entries = self.run_async(
                tm.list_tenants(self.db, begin, end))
            if not entries:
                return "The cluster has no tenants in that range"
            return "\n".join(
                f"{i + 1}. {_printable(e.name)}"
                for i, e in enumerate(entries))
        if action == "get" and len(args) == 1:
            entry = self.run_async(
                tm.get_tenant(self.db, _unescape(args[0])))
            if entry is None:
                return f"ERROR: tenant `{args[0]}' not found"
            return (f"id: {entry.id}\n"
                    f"prefix: {_printable(entry.prefix)}")
        return ("usage: tenant create NAME | tenant delete NAME | "
                "tenant list [BEGIN [END]] | tenant get NAME")

    def cmd_quota(self, action: str, *args: str) -> str:
        """quota set/clear/get — per-tenant tps quotas enforced by the
        ratekeeper through tag throttles."""
        from ..tenant import management as tm
        if action == "set" and len(args) == 2:
            self.run_async(tm.set_tenant_quota(
                self.db, _unescape(args[0]), float(args[1])))
            return f"Quota for `{args[0]}' set to {args[1]} tps"
        if action == "clear" and len(args) == 1:
            self.run_async(tm.set_tenant_quota(
                self.db, _unescape(args[0]), None))
            return f"Quota for `{args[0]}' cleared"
        if action == "get" and len(args) <= 1:
            quotas = self.run_async(tm.get_tenant_quotas(self.db))
            if args:
                tps = quotas.get(_unescape(args[0]))
                return (f"`{args[0]}': {tps:g} tps" if tps is not None
                        else f"`{args[0]}': no quota")
            if not quotas:
                return "No tenant quotas set"
            return "\n".join(f"{_printable(n)} = {tps:g} tps"
                             for n, tps in sorted(quotas.items()))
        return ("usage: quota set NAME TPS | quota clear NAME | "
                "quota get [NAME]")

    def cmd_watch(self, key: str) -> str:
        async def go():
            t = self.db.create_transaction()
            f = await t.watch(_unescape(key))
            await t.commit()
            await f
            return True
        self.run_async(go(), timeout=3600)
        return f"`{key}' changed"

    # -- dispatch ------------------------------------------------------------
    def dispatch(self, line: str) -> Optional[str]:
        parts = shlex.split(line)
        if not parts:
            return None
        cmd, args = parts[0].lower(), parts[1:]
        if cmd in ("exit", "quit"):
            raise SystemExit(0)
        if cmd == "help":
            return HELP
        fn = getattr(self, f"cmd_{cmd}", None)
        if fn is None:
            return f"ERROR: unknown command `{cmd}' (try help)"
        try:
            return fn(*args)
        except TypeError as e:
            return f"ERROR: {e}"
        except Exception as e:  # noqa: BLE001 — surface, keep the REPL up
            return f"ERROR: {e!r}"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="fdbcli")
    ap.add_argument("-C", "--cluster", required=True,
                    help="coordinator list host:port,host:port,...")
    ap.add_argument("--exec", dest="exec_cmds", default=None,
                    help="semicolon-separated commands, then exit")
    args = ap.parse_args(argv)
    cli = Cli(args.cluster)
    if args.exec_cmds is not None:
        rc = 0
        for line in args.exec_cmds.split(";"):
            out = cli.dispatch(line.strip())
            if out:
                print(out)
            if out and out.startswith("ERROR"):
                rc = 1
        return rc
    print("fdbcli — type `help' for commands")
    for line in sys.stdin:
        out = cli.dispatch(line.strip())
        if out:
            print(out)
        sys.stdout.flush()
    return 0


if __name__ == "__main__":
    sys.exit(main())
