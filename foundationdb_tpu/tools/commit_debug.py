"""commit_debug: reconstruct cross-role commit timelines from traces.

Reference: contrib/commit_debug.py in the reference repo — post-processes
g_traceBatch "TransactionDebug"/"CommitDebug" point events from the trace
files of every process into a per-transaction waterfall (client GRV ->
commit proxy batch -> resolver -> TLog -> reply), which is how "where does
a commit spend its time" questions get answered in production.

Event model (core/trace.trace_batch_event):

* TransactionDebug events are keyed by the CLIENT's debug id
  (transaction.debug_id): NativeAPI.getConsistentReadVersion.Before/.After,
  GrvProxy.reply, NativeAPI.commit.Before/.After.
* CommitDebug events are keyed by the commit proxy's per-batch SPAN:
  CommitProxy.batchStart/gotCommitVersion/afterResolution/afterTLogCommit/
  reply, Resolver.<id>.resolveBatch/afterResolve, TLog.<id>.commit/durable.
* The link between the two is the proxy's "CommitProxy.batch:<span>"
  CommitDebug event, emitted with DebugID = the client debug id.
* A debug-tagged txn that aborts on a conflict additionally gets a
  CommitConflictDetail event (DebugID, Ranges, Exact) from its proxy:
  the conflicting ranges and whether the resolver attributed the TRUE
  culprits (exact) or blamed the whole read set (conservative).

Usage:

    python -m foundationdb_tpu.tools.commit_debug trace.0.jsonl \
        [more.jsonl ...] [--debug-id ID]

prints one waterfall per debug-id-tagged transaction plus a stage summary
table aggregated over all reconstructed timelines.

Caveat for REAL multi-process traces: each process's trace Time field is
monotonic since THAT process's start, so cross-file ordering is skewed —
hop pairs within one process stay valid, and simulation traces (one
shared clock) reconstruct exactly.  Client-side NativeAPI.* points land
in the CLIENT's tracer (its datadir/ring), so include its trace file too
or expect the completeness check to name them.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, Iterable, List, Optional, Tuple

# Location substrings a COMPLETE GRV->reply timeline must contain (the
# test gate for the instrumentation staying wired end-to-end).
REQUIRED_STAGES = (
    "NativeAPI.getConsistentReadVersion.Before",
    "GrvProxy.reply",
    "NativeAPI.commit.Before",
    "CommitProxy.batchStart",
    "CommitProxy.gotCommitVersion",
    "CommitProxy.afterResolution",
    "Resolver.",          # any resolver instance
    "TLog.",              # any TLog instance
    "CommitProxy.afterTLogCommit",
    "CommitProxy.reply",
    "NativeAPI.commit.After",
)

_BATCH_LINK_PREFIX = "CommitProxy.batch:"

# Location prefixes that belong to the READ side of a transaction's
# timeline (--reads): client-side NativeAPI get points and the storage
# server's version-wait/lookup points keyed by the same debug id.
READ_STAGE_PREFIXES = (
    "NativeAPI.getConsistentReadVersion.",
    "GrvProxy.",
    "NativeAPI.getValue.",
    "NativeAPI.getRange.",
    "StorageServer.",
)

# Substrings a COMPLETE point-read waterfall must contain: GRV, the
# client Before/After bracket, and the storage server's own points (the
# test gate that client->storage debug-id plumbing stays wired).
REQUIRED_READ_STAGES = (
    "NativeAPI.getConsistentReadVersion.Before",
    "NativeAPI.getValue.Before",
    "StorageServer.getValue.DoRead",
    "StorageServer.getValue.AfterRead",
    "NativeAPI.getValue.After",
)


def is_read_point(loc: str) -> bool:
    return any(loc.startswith(p) for p in READ_STAGE_PREFIXES)


def read_timelines(timelines: Dict[str, List[Tuple[float, str]]]
                   ) -> Dict[str, List[Tuple[float, str]]]:
    """Project full timelines onto their read legs (--reads mode): only
    read-side points survive, ids with none drop out."""
    out: Dict[str, List[Tuple[float, str]]] = {}
    for did, timeline in timelines.items():
        reads = [(t, loc) for t, loc in timeline if is_read_point(loc)]
        if reads:
            out[did] = reads
    return out


def conflict_details(events: Iterable[Dict[str, Any]]
                     ) -> Dict[str, Dict[str, Any]]:
    """{debug_id: {"ranges": str, "exact": bool}} from the proxy's
    CommitConflictDetail events (emitted for every debug-tagged txn that
    aborted on a conflict, server/commit_proxy.py): the conflicting
    ranges and whether their attribution was exact (the resolver pinned
    the true culprits) or conservative (whole read set blamed)."""
    out: Dict[str, Dict[str, Any]] = {}
    for e in events:
        if e.get("Type") != "CommitConflictDetail":
            continue
        did = e.get("DebugID")
        if did:
            # Keep the LAST abort of a retried txn (closest to the
            # attempt the reconstructed timeline ends on).
            out[did] = {"ranges": e.get("Ranges", ""),
                        "exact": bool(e.get("Exact"))}
    return out


def load_events(paths: Iterable[str]) -> List[Dict[str, Any]]:
    """Parse trace JSONL files into event dicts (unparseable lines — e.g.
    the torn tail of a crashed process — are skipped)."""
    events: List[Dict[str, Any]] = []
    for path in paths:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except ValueError:
                    continue
    return events


def build_timelines(events: Iterable[Dict[str, Any]],
                    debug_id: Optional[str] = None
                    ) -> Dict[str, List[Tuple[float, str]]]:
    """{debug_id: [(time, location), ...] time-sorted} for every client
    debug id seen (or just `debug_id`).  A debug id's timeline is its own
    TransactionDebug/CommitDebug points plus every point of each commit
    batch span it was correlated to."""
    by_id: Dict[str, List[Tuple[float, str]]] = {}
    span_points: Dict[str, List[Tuple[float, str]]] = {}
    links: Dict[str, List[str]] = {}   # debug id -> [span, ...]
    for e in events:
        if e.get("Type") not in ("CommitDebug", "TransactionDebug"):
            continue
        did = e.get("DebugID")
        loc = e.get("Location", "")
        t = float(e.get("Time", 0.0))
        if loc.startswith(_BATCH_LINK_PREFIX):
            links.setdefault(did, []).append(
                loc[len(_BATCH_LINK_PREFIX):])
            by_id.setdefault(did, []).append((t, "CommitProxy.batch"))
            continue
        # A point is a span point iff some link names its DebugID as a
        # span; collected for both roles — resolution happens below.
        span_points.setdefault(did, []).append((t, loc))
        by_id.setdefault(did, []).append((t, loc))
    spans = {s for ss in links.values() for s in ss}
    out: Dict[str, List[Tuple[float, str]]] = {}
    for did, points in by_id.items():
        if did in spans or (debug_id is not None and did != debug_id):
            continue   # a bare span is not a client transaction
        timeline = list(points)
        for span in dict.fromkeys(links.get(did, ())):   # dedupe resends
            timeline.extend(span_points.get(span, ()))
        timeline.sort()
        out[did] = timeline
    return out


def is_complete(timeline: List[Tuple[float, str]]) -> bool:
    """True iff the timeline covers every REQUIRED_STAGES hop."""
    locs = [loc for _t, loc in timeline]
    return all(any(req in loc for loc in locs) for req in REQUIRED_STAGES)


def render_waterfall(debug_id: str,
                     timeline: List[Tuple[float, str]],
                     width: int = 40) -> str:
    """ASCII waterfall: per-hop offset from the first event plus a bar
    marking where in the total span the hop landed."""
    if not timeline:
        return f"{debug_id}: no events"
    t0 = timeline[0][0]
    total = max(timeline[-1][0] - t0, 1e-9)
    lines = [f"Commit timeline for {debug_id!r} "
             f"(total {total * 1e3:.3f} ms, {len(timeline)} hops)"]
    prev = t0
    for t, loc in timeline:
        off = t - t0
        start = int((prev - t0) / total * width)
        end = max(int(off / total * width), start + 1)
        bar = " " * start + "#" * (end - start)
        lines.append(f"  {off * 1e3:9.3f} ms  |{bar:<{width}}|  {loc}")
        prev = t
    return "\n".join(lines)


def stage_summary(timelines: Dict[str, List[Tuple[float, str]]]
                  ) -> List[Tuple[str, int, float, float]]:
    """Aggregate consecutive-hop durations across all timelines:
    [(\"from -> to\", count, mean_s, max_s), ...] sorted by total time
    spent (the top row is where commits spend their time)."""
    agg: Dict[str, List[float]] = {}
    for timeline in timelines.values():
        for (t_a, loc_a), (t_b, loc_b) in zip(timeline, timeline[1:]):
            agg.setdefault(f"{loc_a} -> {loc_b}", []).append(t_b - t_a)
    rows = [(stage, len(ds), sum(ds) / len(ds), max(ds))
            for stage, ds in agg.items()]
    rows.sort(key=lambda r: -(r[1] * r[2]))
    return rows


def render_summary(rows: List[Tuple[str, int, float, float]]) -> str:
    lines = ["Stage summary (by total time):",
             f"  {'count':>5}  {'mean ms':>9}  {'max ms':>9}  stage"]
    for stage, count, mean, mx in rows:
        lines.append(f"  {count:>5}  {mean * 1e3:>9.3f}  "
                     f"{mx * 1e3:>9.3f}  {stage}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="commit_debug",
        description="Reconstruct cross-role commit timelines from "
                    "trace JSONL files.")
    ap.add_argument("traces", nargs="+", help="trace JSONL file(s)")
    ap.add_argument("--debug-id", default=None,
                    help="only this transaction's timeline")
    ap.add_argument("--reads", action="store_true",
                    help="read waterfall: only GRV/getValue/getRange/"
                         "StorageServer points (where reads spend time)")
    args = ap.parse_args(argv)
    events = load_events(args.traces)
    timelines = build_timelines(events, debug_id=args.debug_id)
    if args.reads:
        timelines = read_timelines(timelines)
    if not timelines:
        print("no debug-id-tagged transactions found "
              "(set transaction.debug_id to trace one)")
        return 1
    conflicts = conflict_details(events)
    for did in sorted(timelines):
        print(render_waterfall(did, timelines[did]))
        detail = conflicts.get(did)
        if detail is not None:
            mode = "exact" if detail["exact"] else "conservative"
            print(f"  ABORTED on conflict ({mode} attribution): "
                  f"{detail['ranges']}")
        required = REQUIRED_READ_STAGES if args.reads else REQUIRED_STAGES
        missing = [r for r in required
                   if not any(r in loc for _t, loc in timelines[did])]
        if missing:
            print(f"  (incomplete: missing {', '.join(missing)})")
        print()
    print(render_summary(stage_summary(timelines)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
