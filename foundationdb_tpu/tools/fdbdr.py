"""fdbdr: cluster-to-cluster disaster-recovery replication CLI.

Reference: fdbbackup/backup.actor.cpp (the fdbdr program alias) +
fdbclient/DatabaseBackupAgent.actor.cpp — continuous replication of a
source cluster into a target cluster, with drained switchover.  This CLI
combines the reference's `fdbdr start` and the dr_agent daemon in one
process: `run` submits the relationship (snapshot copy + live mutation
stream) and keeps applying until interrupted; `--switchover` instead
drains and hands over once in sync, then exits — the migration workflow.

    python -m foundationdb_tpu.tools.fdbdr run \
        -s 127.0.0.1:4770 -d 127.0.0.1:4780 [--switchover]

Both clusters are spoken to from this one process: the source also via
its cluster controller (get_server_db_info long-poll) so the agent can
peek the BACKUP_TAG mutation stream off the live TLogs.
"""

from __future__ import annotations

import argparse
import sys
from types import SimpleNamespace


def _second_database(coords_spec: str):
    """Another cluster's Database on the ALREADY-RUNNING loop/network
    (open_cluster installs process-globals; only the first cluster may
    create them)."""
    from ..client.database import ClusterConnection, Database
    from ..server.coordination import CoordinationClientInterface
    from ..server.fdbserver import parse_coordinators
    coords = [CoordinationClientInterface.at_address(a)
              for a in parse_coordinators(coords_spec)]
    return Database(ClusterConnection(coords))


def _make_info_fn(cluster_connection, loop):
    """Live ServerDBInfo off the source's CC (the worker subscription
    path), reusing the ClusterConnection's existing leader monitor —
    no second monitor_leader against the same coordinators."""
    from ..rpc.endpoint import RequestStream
    from ..server.cluster_controller import GetServerDBInfoRequest
    leader_var = cluster_connection.leader
    # known_version resets whenever the CC identity changes: a fresh
    # CC's db_info_version restarts at 0, and long-polling it with the
    # OLD counter would block until it catches up — forever, in steady
    # state (the worker's _register_loop resets the same way).
    state = {"version": -1, "info": None, "ts": -1e9, "cc": None}

    async def info_fn():
        from ..core.error import FdbError
        from ..core.scheduler import delay
        # The apply loop asks once per peek; cache briefly so the CC
        # isn't polled at the peek cadence.
        if state["info"] is not None and loop.now() - state["ts"] < 2.0:
            return state["info"]
        leader = leader_var.get()
        cc = leader.serialized_info if leader else None
        if cc is None or getattr(leader, "forward", False):
            await delay(0.2)
            return state["info"]
        if cc is not state["cc"]:
            state["cc"] = cc
            state["version"] = -1
        try:
            version, info = await RequestStream.at(
                cc.get_server_db_info.endpoint).get_reply(
                GetServerDBInfoRequest(known_version=state["version"] - 1))
            state["version"], state["info"] = version, info
            state["ts"] = loop.now()
        except FdbError:
            await delay(0.2)
        return state["info"]

    return info_fn


def cmd_run(args) -> int:
    from ..client.database import open_cluster
    from ..client.dr_agent import DatabaseBackupAgent
    from ..core.scheduler import delay
    loop, src_db = open_cluster(args.source)
    dst_db = _second_database(args.destination)
    agent = DatabaseBackupAgent(
        SimpleNamespace(loop=loop, config=None), src_db, dst_db,
        info_fn=_make_info_fn(src_db.cluster, loop))

    async def go():
        await agent.submit()
        print(f"DR active: snapshot copied through version "
              f"{agent.applied_through}; streaming mutations.",
              flush=True)
        if args.switchover:
            v = await agent.switchover()
            print(f"Switchover complete: target is an exact copy through "
                  f"version {v}. Point clients at the target cluster.")
            return 0
        while True:
            await delay(5.0)
            print(f"DR applied through version {agent.applied_through}",
                  flush=True)

    from ..core.error import FdbError
    try:
        return loop.run_until(loop.spawn(go()), timeout=args.timeout) or 0
    except (KeyboardInterrupt, FdbError) as e:
        agent.abort()
        reason = "interrupted" if isinstance(e, KeyboardInterrupt) \
            else f"stopped ({getattr(e, 'name', 'error')})"
        print(f"DR {reason} (source capture flag left ON; rerun to "
              "resume or finish with run --switchover).")
        return 0 if isinstance(e, KeyboardInterrupt) else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="fdbdr")
    sub = p.add_subparsers(dest="cmd", required=True)
    sp = sub.add_parser("run", help="replicate source -> target "
                                    "continuously (Ctrl-C stops)")
    sp.add_argument("-s", "--source", required=True,
                    help="source coordinators host:port[,...]")
    sp.add_argument("-d", "--destination", required=True,
                    help="target coordinators host:port[,...]")
    sp.add_argument("--switchover", action="store_true",
                    help="drain and hand over once in sync, then exit")
    sp.add_argument("--timeout", type=float, default=86400.0)
    sp.set_defaults(fn=cmd_run)
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
