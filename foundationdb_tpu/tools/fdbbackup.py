"""fdbbackup / fdbrestore: backup and restore command-line tools.

Reference: fdbbackup/backup.actor.cpp — one program surfacing backup
(`fdbbackup start|status|discontinue|abort`) and restore (`fdbrestore
start`) against a cluster, with container URLs (here file:///dir/name;
the reference adds blobstore://).  Connects as an ordinary client
(client/database.open_cluster); the server-side backup worker role does
the log capture, the CLI's agent loop executes snapshot/restore chunk
tasks from the shared TaskBucket.

    python -m foundationdb_tpu.tools.fdbbackup start \
        -C 127.0.0.1:4770 -d file:///tmp/backups/b1
    python -m foundationdb_tpu.tools.fdbbackup status -d file:///tmp/backups/b1
    python -m foundationdb_tpu.tools.fdbbackup discontinue -C 127.0.0.1:4770 \
        -d file:///tmp/backups/b1
    python -m foundationdb_tpu.tools.fdbrestore start \
        -C 127.0.0.1:4770 -r file:///tmp/backups/b1
"""

from __future__ import annotations

import argparse
import sys
from types import SimpleNamespace


def _open(coords: str):
    from ..client.database import open_cluster
    loop, db = open_cluster(coords)
    return loop, db


def _container(url: str):
    from ..client.backup import open_container
    return open_container(url)


def cmd_start(args) -> int:
    from ..client.backup import FileBackupAgent
    loop, db = _open(args.cluster)
    agent = FileBackupAgent(SimpleNamespace(loop=loop), db, url=args.destcontainer)

    async def go():
        await agent.submit()
        return agent.snapshot_version

    snap_v = loop.run_until(loop.spawn(go()), timeout=args.timeout)
    print(f"Backup started; snapshot complete at version {snap_v}. "
          "The log stream continues until `discontinue` or `abort`.")
    return 0


def cmd_status(args) -> int:
    loop, db = (None, None)
    c = _container(args.destcontainer)
    from ..core.scheduler import EventLoop, set_event_loop
    loop = EventLoop(sim=False)
    set_event_loop(loop)

    async def go():
        from ..core.error import FdbError
        try:
            # Meta lands at discontinue/stop; an ACTIVE backup has none.
            start, snap, end = await c.read_meta()
        except FdbError:
            start = snap = end = None
        complete = await c.snapshot_complete()
        frontier = await c.read_frontier()
        return start, snap, end, complete, frontier

    start, snap, end, complete, frontier = loop.run_until(
        loop.spawn(go()), timeout=args.timeout)
    print(f"Container:          {args.destcontainer}")
    print(f"State:              "
          f"{'stopped (meta sealed)' if end is not None else 'active'}")
    print(f"Snapshot:           "
          f"{'complete' if complete else 'IN PROGRESS'}")
    print(f"Log frontier:       {frontier}")
    if end is not None:
        restorable = complete and frontier >= snap
        print(f"Restorable:         {'yes' if restorable else 'no'}"
              + (f" (snapshot {snap}, end {end})" if restorable else ""))
    else:
        print("Restorable:         after discontinue (meta not sealed yet)")
    return 0


def cmd_discontinue(args) -> int:
    from ..client.backup import FileBackupAgent
    loop, db = _open(args.cluster)
    agent = FileBackupAgent(SimpleNamespace(loop=loop), db, url=args.destcontainer)
    end_v = loop.run_until(loop.spawn(agent.stop()), timeout=args.timeout)
    print(f"Backup discontinued; restorable through version {end_v}.")
    return 0


def cmd_abort(args) -> int:
    from ..client.backup import FileBackupAgent
    loop, db = _open(args.cluster)
    agent = FileBackupAgent(SimpleNamespace(loop=loop), db, url=args.destcontainer)
    loop.run_until(loop.spawn(agent._set_backup_flag(False)),
                   timeout=args.timeout)
    print("Backup aborted (capture stopped immediately; the container may "
          "not be restorable past its snapshot).")
    return 0


def cmd_restore(args) -> int:
    from ..client.backup import restore
    loop, db = _open(args.cluster)
    c = _container(args.sourcecontainer)
    applied = loop.run_until(loop.spawn(restore(db, c.fs, c.name)),
                             timeout=args.timeout)
    print(f"Restore complete: {applied} mutations applied.")
    return 0


def _parser(restore_mode: bool) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="fdbrestore" if restore_mode else "fdbbackup")
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp, container_flag, container_dest, need_cluster=True):
        if need_cluster:
            sp.add_argument("-C", "--cluster", required=True,
                            help="coordinator list host:port[,host:port...]")
        sp.add_argument(container_flag, dest=container_dest, required=True,
                        help="container URL (file:///dir/name)")
        sp.add_argument("--timeout", type=float, default=300.0)

    if restore_mode:
        sp = sub.add_parser("start", help="restore a container into the cluster")
        common(sp, "-r", "sourcecontainer")
        sp.set_defaults(fn=cmd_restore)
    else:
        sp = sub.add_parser("start", help="submit a backup (snapshot + log stream)")
        common(sp, "-d", "destcontainer")
        sp.set_defaults(fn=cmd_start)
        sp = sub.add_parser("status", help="describe a backup container")
        common(sp, "-d", "destcontainer", need_cluster=False)
        sp.set_defaults(fn=cmd_status)
        sp = sub.add_parser("discontinue",
                            help="stop capture after making the backup restorable")
        common(sp, "-d", "destcontainer")
        sp.set_defaults(fn=cmd_discontinue)
        sp = sub.add_parser("abort", help="stop capture immediately")
        common(sp, "-d", "destcontainer")
        sp.set_defaults(fn=cmd_abort)
    return p


def main(argv=None, restore_mode: bool = False) -> int:
    args = _parser(restore_mode).parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
