"""fdbmonitor: the process supervisor (reference fdbmonitor/fdbmonitor.cpp).

Reads a foundationdb.conf-style INI, spawns one fdbserver OS process per
[fdbserver.<port>] section, restarts crashed children with exponential
backoff (reset after a stable run), reloads the conf on SIGHUP or when
its mtime changes (starting added sections, stopping removed ones), and
tears everything down on SIGTERM/SIGINT — the piece that makes a real
deployment self-healing at the process level.

Conf format (a practical subset of the reference's):

    [general]
    cluster-file = /var/fdb/fdb.cluster   ; seeds --coordinators
    restart-delay = 1                     ; seconds, doubles per crash
    restart-backoff-max = 30

    [fdbserver]                            ; defaults for all servers
    class = stateless
    datadir = /var/fdb/data/$PORT          ; $PORT substituted

    [fdbserver.4500]
    class = storage
    coordination = true                    ; pass --coordination

Run: python -m foundationdb_tpu.tools.fdbmonitor --conf foundationdb.conf
"""

from __future__ import annotations

import configparser
import os
import signal
import subprocess
import sys
import time
from typing import Dict, Optional


class _Child:
    def __init__(self, port: int, cmd: list) -> None:
        self.port = port
        self.cmd = cmd
        self.proc: Optional[subprocess.Popen] = None
        self.backoff = 0.0
        self.next_start = 0.0
        self.started_at = 0.0
        self.restarts = 0


class FdbMonitor:
    def __init__(self, conf_path: str, log=print) -> None:
        self.conf_path = conf_path
        self.log = log
        self.children: Dict[int, _Child] = {}
        self.restart_delay = 1.0
        self.backoff_max = 30.0
        self.cluster_file = ""
        self._conf_mtime = 0.0
        self._stop = False

    # -- conf ---------------------------------------------------------------
    def _build_cmd(self, port: int, section: dict) -> list:
        datadir = section.get("datadir", f"./data/{port}")
        datadir = datadir.replace("$PORT", str(port))
        coordinators = section.get("coordinators", "")
        if not coordinators and self.cluster_file and \
                os.path.exists(self.cluster_file):
            with open(self.cluster_file) as f:
                coordinators = f.read().strip()
        cmd = [sys.executable, "-m", "foundationdb_tpu.server.fdbserver",
               "--port", str(port),
               "--coordinators", coordinators or f"127.0.0.1:{port}",
               "--datadir", datadir,
               "--class", section.get("class", "stateless"),
               "--name", section.get("name", f"fdbserver.{port}")]
        if section.get("config"):
            cmd += ["--config", section["config"]]
        if section.get("coordination", "").lower() in ("1", "true", "on"):
            cmd.append("--coordination")
        return cmd

    def load_conf(self) -> None:
        cp = configparser.ConfigParser(inline_comment_prefixes=(";", "#"))
        cp.read(self.conf_path)
        self._conf_mtime = os.path.getmtime(self.conf_path)
        general = dict(cp["general"]) if "general" in cp else {}
        self.restart_delay = float(general.get("restart-delay", 1.0))
        self.backoff_max = float(general.get("restart-backoff-max", 30.0))
        self.cluster_file = general.get("cluster-file", "")
        defaults = dict(cp["fdbserver"]) if "fdbserver" in cp else {}
        wanted: Dict[int, dict] = {}
        for section in cp.sections():
            if not section.startswith("fdbserver."):
                continue
            port = int(section.split(".", 1)[1])
            merged = dict(defaults)
            merged.update(dict(cp[section]))
            wanted[port] = merged
        # Stop removed children; (re)configure the rest.
        for port in list(self.children):
            if port not in wanted:
                self.log(f"fdbmonitor: section removed, stopping {port}")
                self._stop_child(self.children.pop(port))
        for port, section in wanted.items():
            cmd = self._build_cmd(port, section)
            cur = self.children.get(port)
            if cur is None:
                self.children[port] = _Child(port, cmd)
            elif cur.cmd != cmd:
                self.log(f"fdbmonitor: conf changed, restarting {port}")
                self._stop_child(cur)
                self.children[port] = _Child(port, cmd)

    # -- children -----------------------------------------------------------
    def _start_child(self, c: _Child) -> None:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        c.proc = subprocess.Popen(
            c.cmd, env=env, start_new_session=True,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        c.started_at = time.monotonic()
        self.log(f"fdbmonitor: started fdbserver.{c.port} "
                 f"pid={c.proc.pid} (restart #{c.restarts})")

    def _stop_child(self, c: _Child) -> None:
        if c.proc is not None and c.proc.poll() is None:
            c.proc.terminate()
            try:
                c.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                c.proc.kill()
                c.proc.wait()
        c.proc = None

    def poll_once(self) -> None:
        """One supervision pass: reap, backoff, (re)start, conf reload."""
        try:
            if os.path.getmtime(self.conf_path) != self._conf_mtime:
                self.log("fdbmonitor: conf changed on disk, reloading")
                self.load_conf()
        except OSError:
            pass
        except Exception as e:  # noqa: BLE001 — a malformed conf edit
            # must never kill the supervisor (children would be orphaned);
            # keep running the LAST good configuration.
            self.log(f"fdbmonitor: conf reload failed, keeping previous: "
                     f"{e!r}")
            try:
                self._conf_mtime = os.path.getmtime(self.conf_path)
            except OSError:
                pass
        now = time.monotonic()
        for c in self.children.values():
            if c.proc is not None:
                rc = c.proc.poll()
                if rc is None:
                    # Stable for a while: forgive past crashes.
                    if c.backoff and now - c.started_at > 10.0:
                        c.backoff = 0.0
                    continue
                self.log(f"fdbmonitor: fdbserver.{c.port} exited rc={rc}")
                c.proc = None
                c.restarts += 1
                c.backoff = min(max(c.backoff * 2, self.restart_delay),
                                self.backoff_max)
                c.next_start = now + c.backoff
            if c.proc is None and now >= c.next_start:
                self._start_child(c)

    def run(self) -> None:
        self.load_conf()
        signal.signal(signal.SIGTERM, self._on_term)
        signal.signal(signal.SIGINT, self._on_term)
        try:
            signal.signal(signal.SIGHUP, self._on_hup)
        except (AttributeError, ValueError):
            pass
        while not self._stop:
            self.poll_once()
            time.sleep(0.25)
        for c in self.children.values():
            self._stop_child(c)

    def _on_term(self, _sig, _frm) -> None:
        self.log("fdbmonitor: shutting down")
        self._stop = True

    def _on_hup(self, _sig, _frm) -> None:
        self.log("fdbmonitor: SIGHUP, reloading conf")
        self.load_conf()


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="fdbmonitor")
    ap.add_argument("--conf", required=True)
    args = ap.parse_args(argv)
    FdbMonitor(args.conf).run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
