"""The tenant map: entry format, key conventions, mutation parsing.

Reference: fdbclient/Tenant.h (TenantMapEntry, tenantMapPrefix) — each
tenant owns the keyspace [prefix, strinc(prefix)) where prefix is the
tenant id packed big-endian into 8 bytes.  Fixed-width prefixes are what
makes the conflict path cheap: the prefix fills exactly the digest's
tenant-salt column (ops/digest.py SALT_LANES), so a tenant-relative key of
up to 23 bytes digests exactly and tenant traffic never routes through the
supervisor's long-key recheck.

The map itself is ordinary committed data under \\xff/tenant/map/<name>;
commit proxies interpret map mutations into their tenant caches
(parse_tenant_mutation below, the tenant analog of ApplyMetadataMutation),
and the mutations ride TXS_TAG so a recovery replays them onto the
DBCoreState baseline.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

from ..core.wire import Reader, Writer
from ..server.system_data import (TENANT_MAP_END,  # noqa: F401
                                  TENANT_LAST_ID_KEY, TENANT_MAP_PREFIX,
                                  TENANT_METADATA_VERSION_KEY,
                                  TENANT_QUOTA_END, TENANT_QUOTA_PREFIX)
from ..txn.types import Mutation, MutationType

# Every tenant prefix is exactly this long — the digest salt column's width
# (ops/digest.py SALT_BYTES); the two must agree or tenant keys would
# straddle the salt/relative-key lane boundary.
TENANT_PREFIX_LEN = 8


def tenant_prefix(tenant_id: int) -> bytes:
    """The 8-byte keyspace prefix of a tenant id (reference
    TenantMapEntry::idToPrefix: big-endian, so prefix order == id order)."""
    return tenant_id.to_bytes(TENANT_PREFIX_LEN, "big")


def prefix_to_id(prefix: bytes) -> int:
    return int.from_bytes(prefix, "big")


@dataclass(frozen=True)
class TenantMapEntry:
    """One tenant: immutable id (hence immutable prefix) + name."""

    id: int
    name: bytes

    @property
    def prefix(self) -> bytes:
        return tenant_prefix(self.id)

    def encode(self) -> bytes:
        return Writer().i64(self.id).bytes_(self.name).done()

    @classmethod
    def decode(cls, blob: bytes) -> "TenantMapEntry":
        r = Reader(blob)
        return cls(id=r.i64(), name=r.bytes_())


def check_tenant_name(name: bytes) -> None:
    """Validity rules (reference TenantAPI::checkTenantMode + name
    checks): non-empty, no \\xff prefix (reserved), no NUL (it would be
    ambiguous against the map key encoding), bounded length."""
    from ..core.error import err
    if not isinstance(name, bytes) or not name:
        raise err("tenant_name_required", "tenant name must be non-empty")
    if name.startswith(b"\xff") or b"\x00" in name or len(name) > 128:
        raise err("invalid_tenant_name", f"bad tenant name {name!r}")


def tenant_map_key(name: bytes) -> bytes:
    return TENANT_MAP_PREFIX + name


def tenant_quota_key(name: bytes) -> bytes:
    return TENANT_QUOTA_PREFIX + name


def tenant_tag(name: bytes) -> str:
    """The throttle tag tenant transactions carry (GRV + storage reads):
    per-tenant metering and quotas ride the existing tag machinery.

    The byte->str encoding must be LOSSLESS AND INJECTIVE: the old
    backslashreplace decoding mapped e.g. b"a\\xff" and b"a\\\\xff" to the
    same tag, cross-wiring two tenants' quotas and metering (ROADMAP nit
    from PR 3's review).  Printable ASCII passes through unchanged (tags
    stay human-readable in status/fdbcli); backslash and everything
    non-printable escape to \\xNN — backslash itself always escapes, so
    no unescaped name can collide with an escaped one."""
    return "t/" + "".join(
        chr(b) if 0x20 <= b < 0x7F and b != 0x5C else f"\\x{b:02x}"
        for b in name)


def parse_tenant_mutation(
        m: Mutation) -> Optional[List[Tuple[bytes,
                                            Optional[TenantMapEntry]]]]:
    """[(name, entry)] for a tenant-map SetValue, [(name, None), ...] for
    names a ClearRange retires, else None.  For broad clears the caller
    supplies its cache's name list via the returned wildcard: a clear that
    cannot be enumerated yields [(b"*", None)] and the applier drops every
    cached name inside the clear's bounds (it knows them; we don't)."""
    if m.type == MutationType.SetValue and \
            m.param1.startswith(TENANT_MAP_PREFIX):
        name = m.param1[len(TENANT_MAP_PREFIX):]
        return [(name, TenantMapEntry.decode(m.param2))]
    if m.type == MutationType.ClearRange and \
            m.param2 > TENANT_MAP_PREFIX and m.param1 < TENANT_MAP_END:
        lo = max(m.param1, TENANT_MAP_PREFIX)
        hi = min(m.param2, TENANT_MAP_END)
        if hi == lo + b"\x00" and lo.startswith(TENANT_MAP_PREFIX):
            # Point clear (Transaction.clear emits [key, key+\x00)).
            return [(lo[len(TENANT_MAP_PREFIX):], None)]
        return [(b"*", None)]
    return None


def apply_tenant_mutation(tenants: dict, m: Mutation) -> bool:
    """Fold one committed mutation into a {id: name} tenant cache (the
    shared core used by commit proxies and the master's recovery replay).
    Returns True iff the mutation touched the tenant map."""
    parsed = parse_tenant_mutation(m)
    if parsed is None:
        return False
    for name, entry in parsed:
        if entry is not None:
            tenants[entry.id] = name
        elif name == b"*":
            lo = max(m.param1, TENANT_MAP_PREFIX)
            hi = min(m.param2, TENANT_MAP_END)
            for tid, tname in list(tenants.items()):
                if lo <= tenant_map_key(tname) < hi:
                    del tenants[tid]
        else:
            for tid, tname in list(tenants.items()):
                if tname == name:
                    del tenants[tid]
    return True
