"""Client-side tenant handles: prefixed transactions.

Reference: fdbclient/Tenant.h Tenant + NativeAPI's tenant-aware
Transaction — a TenantTransaction is an ordinary Transaction whose keys
are transparently rebased into [prefix, strinc(prefix)): applied on every
get/set/clear/range/watch/atomic op/conflict range going in, stripped from
every key coming out.  Raw cross-prefix access is impossible through the
handle: relative keys are validated BEFORE prefixing, and results are
asserted to carry the prefix before stripping.

The prefix is immutable per tenant id, so the handle caches its
TenantMapEntry forever; a deleted tenant is fenced authoritatively by the
commit proxies (tenant_not_found at commit — never retryable), at which
point the handle is dead and the caller re-opens by name.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..core.error import err
from ..txn.types import MutationType, Version, key_after, strinc
from .map import TENANT_PREFIX_LEN, TenantMapEntry, tenant_tag


async def open_tenant(db, name: bytes) -> "Tenant":
    """Open a handle to an existing tenant (reference fdb_database_open_
    tenant; raises tenant_not_found rather than creating implicitly)."""
    from .management import get_tenant
    entry = await get_tenant(db, name)
    if entry is None:
        raise err("tenant_not_found", f"no tenant {name!r}")
    return Tenant(db, entry)


class Tenant:
    """A database handle scoped to one tenant's keyspace."""

    def __init__(self, db, entry: TenantMapEntry) -> None:
        self.db = db
        self.entry = entry
        self.name = entry.name
        self.prefix = entry.prefix
        assert len(self.prefix) == TENANT_PREFIX_LEN

    def create_transaction(self) -> "TenantTransaction":
        return TenantTransaction(self.db.create_transaction(), self)

    async def run(self, fn):
        """Retry-loop helper mirroring Transaction.run: `await fn(txn)`
        against a TenantTransaction, committed, retried on retryables."""
        txn = self.create_transaction()
        while True:
            try:
                result = await fn(txn)
                await txn.commit()
                return result
            except BaseException as e:  # noqa: BLE001
                await txn.on_error(e)


class TenantTransaction:
    """One transaction attempt chain confined to a tenant's prefix.

    Wraps (rather than subclasses) Transaction so every key crosses
    exactly one audited boundary: _pack going in, _strip coming out."""

    def __init__(self, inner, tenant: Tenant) -> None:
        self._inner = inner
        self.tenant = tenant
        self._prefix = tenant.prefix
        # Tenant identity rides the commit for proxy-side validation, and
        # the tenant's throttle tag rides GRVs + storage reads so the
        # per-tenant metering/quota machinery sees this traffic.
        inner.tenant_id = tenant.entry.id
        inner.tag = tenant_tag(tenant.name)

    # -- key translation ----------------------------------------------------
    def _pack(self, key: bytes) -> bytes:
        if not isinstance(key, (bytes, bytearray, memoryview)):
            raise err("client_invalid_operation",
                      f"tenant key must be bytes, not {type(key).__name__}")
        key = bytes(key)
        if key >= b"\xff":
            # The tenant-relative keyspace is [b"", b"\xff"), exactly like
            # the raw user keyspace; \xff-and-above is rejected so a
            # tenant can never address another tenant or system keys.
            raise err("key_outside_legal_range",
                      "tenant-relative key outside [\"\", \\xff)")
        return self._prefix + key

    def _pack_end(self, end: bytes) -> bytes:
        """Range ends may be b"\xff" (the whole tenant): clamp to the
        prefix's upper bound.  Same bytes-type audit as _pack: a str end
        must raise here, not coerce into a wrong (usually empty) range
        (ROADMAP nit from PR 3's review)."""
        if not isinstance(end, (bytes, bytearray, memoryview)):
            raise err("client_invalid_operation",
                      f"tenant range end must be bytes, "
                      f"not {type(end).__name__}")
        end = bytes(end)
        if end > b"\xff":
            raise err("key_outside_legal_range")
        if end == b"\xff":
            return strinc(self._prefix)
        return self._prefix + end

    def _strip(self, key: bytes) -> bytes:
        assert key.startswith(self._prefix), \
            f"cross-tenant key {key!r} leaked through tenant handle"
        return key[TENANT_PREFIX_LEN:]

    # -- reads ----------------------------------------------------------------
    async def get(self, key: bytes, snapshot: bool = False
                  ) -> Optional[bytes]:
        return await self._inner.get(self._pack(key), snapshot=snapshot)

    async def get_range(self, begin: bytes, end: bytes, limit: int = 1000,
                        reverse: bool = False, snapshot: bool = False
                        ) -> List[Tuple[bytes, bytes]]:
        rows = await self._inner.get_range(
            self._pack(begin), self._pack_end(end), limit=limit,
            reverse=reverse, snapshot=snapshot)
        return [(self._strip(k), v) for k, v in rows]

    async def watch(self, key: bytes):
        return await self._inner.watch(self._pack(key))

    # -- writes ---------------------------------------------------------------
    def set(self, key: bytes, value: bytes) -> None:
        self._inner.set(self._pack(key), value)

    def clear(self, key: bytes, end: Optional[bytes] = None) -> None:
        packed = self._pack(key)
        self._inner.clear(packed, self._pack_end(end) if end is not None
                          else key_after(packed))

    def atomic_op(self, op: MutationType, key: bytes,
                  operand: bytes) -> None:
        self._inner.atomic_op(op, self._pack(key), operand)

    def set_versionstamped_key(self, key_template: bytes, offset: int,
                               value: bytes) -> None:
        # The stamp slot shifts by the prefix the template gains.
        self._inner.set_versionstamped_key(
            self._pack(key_template), offset + TENANT_PREFIX_LEN, value)

    def set_versionstamped_value(self, key: bytes, value_template: bytes,
                                 offset: int = 0) -> None:
        self._inner.set_versionstamped_value(self._pack(key),
                                             value_template, offset)

    def add_read_conflict_range(self, begin: bytes, end: bytes) -> None:
        self._inner.add_read_conflict_range(self._pack(begin),
                                            self._pack_end(end))

    def add_write_conflict_range(self, begin: bytes, end: bytes) -> None:
        self._inner.add_write_conflict_range(self._pack(begin),
                                             self._pack_end(end))

    # -- lifecycle ------------------------------------------------------------
    async def commit(self) -> Version:
        return await self._inner.commit()

    async def on_error(self, e: BaseException) -> None:
        await self._inner.on_error(e)

    def reset(self) -> None:
        self._inner.reset()
        self._inner.tenant_id = self.tenant.entry.id
        self._inner.tag = tenant_tag(self.tenant.name)

    def get_versionstamp(self):
        return self._inner.get_versionstamp()

    def get_read_version(self):
        return self._inner.get_read_version()

    @property
    def committed_version(self) -> Version:
        return self._inner.committed_version

    @property
    def priority(self):
        return self._inner.priority

    @priority.setter
    def priority(self, value) -> None:
        self._inner.priority = value
