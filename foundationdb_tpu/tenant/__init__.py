"""Multi-tenant keyspace (reference fdbclient/Tenant.h + TenantManagement).

A tenant is a named, isolated slice of the user keyspace: every key a
tenant writes is transparently prefixed with the tenant's fixed 8-byte id,
and stripped again on the way out.  The pieces:

  * map.py        — the transactional tenant map under \\xff/tenant/map/
                    (TenantMapEntry, key conventions, mutation parsing
                    shared by the commit proxies and recovery replay)
  * management.py — create/delete/list/get + per-tenant quota knobs, all
                    ordinary serializable transactions
  * handle.py     — the client-side Tenant handle / TenantTransaction
                    wrapper that applies and strips the prefix

Isolation is enforced twice: the handle never emits a key outside its
prefix, and the commit proxies validate every tenant-tagged commit against
their (metadata-versioned) tenant cache — a deleted tenant's writes can
never commit, and a mutation outside the claimed prefix is rejected with
illegal_tenant_access.  Per-tenant admission control rides the existing
tag-throttle machinery: tenant transactions carry the tenant's throttle
tag, storage meters reads per tag, and the ratekeeper turns committed
quotas (\\xff/tenant/quota/) into GRV-proxy tag throttles.
"""

from .handle import Tenant, TenantTransaction, open_tenant  # noqa: F401
from .management import (create_tenant, delete_tenant,  # noqa: F401
                         get_tenant, get_tenant_quotas, list_tenants,
                         set_tenant_quota)
from .map import (TENANT_PREFIX_LEN, TenantMapEntry,  # noqa: F401
                  parse_tenant_mutation, tenant_map_key, tenant_prefix,
                  tenant_quota_key, tenant_tag)
