"""Tenant management: create/delete/list/get + quotas, as transactions.

Reference: fdbclient/TenantManagement.actor.h — tenant operations are
ordinary serializable transactions against the \\xff/tenant/ keyspace, so
they inherit the database's own consistency and durability and need no
private channel into the cluster (the same "configuration as data" stance
as client/management.py).

Every create/delete bumps \\xff/tenant/metadataVersion so caches key their
entries by it; both operations are idempotent (a retry after
commit_unknown_result converges).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..core.error import FdbError, err
from .map import (TENANT_LAST_ID_KEY, TENANT_MAP_END, TENANT_MAP_PREFIX,
                  TENANT_METADATA_VERSION_KEY, TENANT_QUOTA_END,
                  TENANT_QUOTA_PREFIX, TenantMapEntry, check_tenant_name,
                  tenant_map_key, tenant_prefix, tenant_quota_key)


async def _retrying(db, fn):
    t = db.create_transaction()
    t.access_system_keys = True
    while True:
        try:
            r = await fn(t)
            await t.commit()
            return r
        except FdbError as e:
            await t.on_error(e)


async def _bump_metadata_version(t) -> int:
    raw = await t.get(TENANT_METADATA_VERSION_KEY)
    version = (int(raw) if raw else 0) + 1
    t.set(TENANT_METADATA_VERSION_KEY, b"%d" % version)
    return version


async def tenant_metadata_version(db) -> int:
    async def go(t):
        raw = await t.get(TENANT_METADATA_VERSION_KEY)
        return int(raw) if raw else 0
    return await _retrying(db, go)


async def create_tenant(db, name: bytes) -> TenantMapEntry:
    """Create `name` (idempotent: an existing tenant is returned as-is —
    the reference's createTenant ignore-existing mode, which is what a
    retry loop needs after commit_unknown_result)."""
    check_tenant_name(name)

    async def go(t):
        raw = await t.get(tenant_map_key(name))
        if raw is not None:
            return TenantMapEntry.decode(raw)
        last_raw = await t.get(TENANT_LAST_ID_KEY)
        tenant_id = (int(last_raw) if last_raw else 0) + 1
        t.set(TENANT_LAST_ID_KEY, b"%d" % tenant_id)
        entry = TenantMapEntry(id=tenant_id, name=name)
        t.set(tenant_map_key(name), entry.encode())
        await _bump_metadata_version(t)
        return entry
    return await _retrying(db, go)


async def delete_tenant(db, name: bytes) -> None:
    """Delete `name` (idempotent; raises tenant_not_empty while the
    tenant's keyspace still holds data, like the reference)."""
    check_tenant_name(name)

    async def go(t):
        raw = await t.get(tenant_map_key(name))
        if raw is None:
            return
        entry = TenantMapEntry.decode(raw)
        p = tenant_prefix(entry.id)
        from ..txn.types import strinc
        rows = await t.get_range(p, strinc(p), limit=1)
        if rows:
            raise err("tenant_not_empty",
                      f"tenant {name!r} still holds keys")
        t.clear(tenant_map_key(name))
        t.clear(tenant_quota_key(name))
        await _bump_metadata_version(t)
    await _retrying(db, go)


async def get_tenant(db, name: bytes) -> Optional[TenantMapEntry]:
    check_tenant_name(name)

    async def go(t):
        raw = await t.get(tenant_map_key(name))
        return TenantMapEntry.decode(raw) if raw is not None else None
    return await _retrying(db, go)


async def list_tenants(db, begin: bytes = b"", end: bytes = b"\xff",
                       limit: int = 1000) -> List[TenantMapEntry]:
    async def go(t):
        rows = await t.get_range(TENANT_MAP_PREFIX + begin,
                                 min(TENANT_MAP_PREFIX + end,
                                     TENANT_MAP_END),
                                 limit=limit)
        return [TenantMapEntry.decode(v) for _k, v in rows]
    return await _retrying(db, go)


async def set_tenant_quota(db, name: bytes, tps: Optional[float]) -> None:
    """Set (or with tps=None clear) a tenant's transaction-rate quota.
    The ratekeeper polls the quota range and enforces it through the
    tag-throttle machinery (server/ratekeeper.py); the tenant must
    exist."""
    check_tenant_name(name)

    async def go(t):
        if await t.get(tenant_map_key(name)) is None:
            raise err("tenant_not_found", f"no tenant {name!r}")
        if tps is None:
            t.clear(tenant_quota_key(name))
        else:
            t.set(tenant_quota_key(name), b"%g" % float(tps))
    await _retrying(db, go)


async def get_tenant_quotas(db) -> Dict[bytes, float]:
    """{tenant name: tps} for every committed quota."""
    async def go(t):
        rows = await t.get_range(TENANT_QUOTA_PREFIX, TENANT_QUOTA_END)
        return {k[len(TENANT_QUOTA_PREFIX):]: float(v) for k, v in rows}
    return await _retrying(db, go)
