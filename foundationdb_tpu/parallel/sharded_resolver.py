"""The REAL resolve step sharded over a device mesh (BASELINE config 5).

parallel/sharded_window.py demonstrates the collective pattern on the plain
window kernels; THIS module shards TpuConflictSet's actual per-batch
program — too-old classification, two-tier base+delta history query,
intra-batch Jacobi fixpoint, clipped insert, verdict codes, sticky
overflow flag — so a resolver can run its entire conflict window across
chips.  Reference semantics: the proxy min-combines per-key-range resolver
verdicts (CommitProxyServer.actor.cpp:800-806); here the combine is ONE
pmax of the per-txn history bits over mesh axis "kr", on ICI, inside the
jitted step (fused.make_resolve_step axis_name).

Sharding layout (leading axis = shard, jax.sharding P("kr")):

    bk    uint32[D, 6, CAP]   shard d's base boundaries, all inside its
                              digest range [splits[d], splits[d+1])
    bv    int32[D, CAP]       versions; table int32[D, LOG+1, CAP]
    dk/dv/dsize               delta tier, same layout
    bounds uint32[D, 6, 2]    each shard's [lo, hi) digest bounds

The batch (digests + meta blocks, tpu_backend._pack layout) is replicated;
each shard clips reads/writes to its bounds, so V_d(k) == V(k) exactly for
owned k and the max-combined verdicts equal the single-device ones
bit-for-bit (tests/test_sharded_resolver.py proves this against both
TpuConflictSet and the oracle).  Merges are shard-local: no collective at
all on the amortized path.

CAP here is PER-SHARD capacity: D shards hold D*CAP boundaries total, the
scaling axis that lets the window hold the reference target's 1M+
in-flight ranges without any single chip holding them all.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..conflict.tpu_backend import TpuConflictSet
from ..ops.digest import KEY_LANES, MAX_DIGEST
from ..ops.rangemax import NEG_INF
from .sharded_window import (digest_splits, jit_sharded,  # noqa: F401
                             make_conflict_mesh, shard_map_compat)


class ShardedTpuConflictSet(TpuConflictSet):
    """TpuConflictSet whose window state is key-range-sharded over "kr".

    Same public API and host-side scheduling (merge cadence, delta bound,
    rebase, overflow surfacing) as the single-device backend; `capacity`
    and `delta_capacity` are PER-SHARD."""

    def __init__(self, mesh: Mesh, oldest_version=0,
                 capacity: Optional[int] = None,
                 delta_capacity: Optional[int] = None,
                 gc_interval_batches: int = 8,
                 splits: Optional[np.ndarray] = None) -> None:
        assert "kr" in mesh.axis_names, "mesh must carry a 'kr' axis"
        self.mesh = mesh
        self.n_shards = int(mesh.shape["kr"])
        self._kr = NamedSharding(mesh, P("kr"))
        self._step_cache: dict = {}
        self._merge_cache: dict = {}
        self._dtable_cache: dict = {}
        # Key-range split points: uint32[n_shards+1, 8] ascending digest
        # cuts (row 0 all-zero, last row MAX_DIGEST).  Default: even
        # lane-0 cuts; workloads with a shared key prefix should pass
        # equi-depth cuts (sharded_window.splits_from_sample) or one
        # shard absorbs the whole window.
        if splits is not None:
            splits = np.asarray(splits, dtype=np.uint32)
            assert splits.shape == (self.n_shards + 1, KEY_LANES), \
                f"splits shape {splits.shape}"
        self._splits = splits
        super().__init__(oldest_version, capacity=capacity,
                         delta_capacity=delta_capacity,
                         gc_interval_batches=gc_interval_batches)

    # -- sharded state ------------------------------------------------------
    def _put(self, arr: np.ndarray):
        import jax
        return jax.device_put(arr, self._kr)

    def _split_points(self) -> np.ndarray:
        return self._splits if self._splits is not None \
            else digest_splits(self.n_shards)

    def _shard_window(self, cap: int, value: int) -> tuple:
        """[D, 6, cap] boundaries + [D, cap] versions: each shard one
        segment covering its whole digest range at `value`."""
        d = self.n_shards
        splits = self._split_points()
        bk = np.broadcast_to(MAX_DIGEST[None, :, None],
                             (d, KEY_LANES, cap)).copy()
        bk[:, :, 0] = splits[:d]
        bv = np.full((d, cap), int(NEG_INF), dtype=np.int32)
        bv[:, 0] = value
        return bk, bv

    def _reset_state(self, version) -> None:
        import jax.numpy as jnp
        from ..ops.rangemax import build_sparse_table
        self.version_base = version
        d = self.n_shards
        splits = self._split_points()
        bk, bv = self._shard_window(self.capacity, 0)
        self.bk = self._put(bk)
        self.bv = self._put(bv)
        self.size = self._put(np.ones((d,), dtype=np.int32))
        import jax
        self.table = jax.jit(
            jax.vmap(build_sparse_table),
            out_shardings=self._kr)(self.bv)
        dk, dv = self._shard_window(self.d_cap, int(NEG_INF))
        self.dk = self._put(dk)
        self.dv = self._put(dv)
        self.dsize = self._put(np.ones((d,), dtype=np.int32))
        self.dtable = self._build_dtable()
        self.flag = self._put(np.zeros((d,), dtype=np.int32))
        bounds = np.empty((d, KEY_LANES, 2), dtype=np.uint32)
        bounds[:, :, 0] = splits[:d]
        bounds[:, :, 1] = splits[1:]
        self.bounds = self._put(bounds)
        self._firsts = self._put(splits[:d].copy())   # [D, 6]
        self._reset_bookkeeping(live_boundaries=d)
        self._jnp = jnp

    def _grow_delta(self, needed: int) -> None:
        from ..conflict.tpu_backend import _bucket
        self.d_cap = min(_bucket(needed), self.capacity)
        dk, dv = self._shard_window(self.d_cap, int(NEG_INF))
        self.dk = self._put(dk)
        self.dv = self._put(dv)
        self.dsize = self._put(np.ones((self.n_shards,), dtype=np.int32))
        self.dtable = self._build_dtable()

    def _build_dtable(self):
        """Hoisted per-shard delta range-max tables [D, LOG+1, DCAP]: the
        vmapped analog of fused.delta_table_step, refreshed after every
        insert/merge so the sharded per-batch step never rebuilds them.
        The jitted builder is cached on self — a fresh jax.jit wrapper
        per call would miss the pjit cache (keyed on fn identity) and
        re-trace on the per-batch hot path."""
        fn = self._dtable_cache.get("fn")
        if fn is None:
            import jax
            from ..ops.rangemax import build_sparse_table
            fn = jax.jit(jax.vmap(build_sparse_table),
                         out_shardings=self._kr)
            self._dtable_cache["fn"] = fn
        return fn(self.dv)

    # -- sharded programs ---------------------------------------------------
    def _sharded_step(self, t_cap: int, r_cap: int, w_cap: int):
        key = (self.capacity, self.d_cap, t_cap, r_cap, w_cap)
        fn = self._step_cache.get(key)
        if fn is not None:
            return fn
        import jax
        raw = self._fused.make_resolve_step(
            self.capacity, self.d_cap, t_cap, r_cap, w_cap,
            axis_name="kr")

        def shard_fn(bk, bv, table, size, dk, dv, dtable, dsize, flag,
                     digests, meta, bounds):
            dk2, dv2, ds2, fl2, out = raw(
                bk[0], bv[0], table[0], size[0], dk[0], dv[0], dtable[0],
                dsize[0], flag[0], digests, meta, bounds[0])
            return dk2[None], dv2[None], ds2[None], fl2[None], out

        spec_state3 = P("kr", None, None)
        spec_state2 = P("kr", None)
        spec_1 = P("kr")
        mapped = shard_map_compat(shard_fn, self.mesh,
            in_specs=(spec_state3, spec_state2, spec_state3, spec_1,
                      spec_state3, spec_state2, spec_state3, spec_1, spec_1,
                      P(None, None), P(None), spec_state3),
            out_specs=(spec_state3, spec_state2, spec_1, spec_1, P(None)))
        fn = jit_sharded(mapped, donate_argnums=(4, 5, 7, 8))
        self._step_cache[key] = fn
        return fn

    def _sharded_step_compact(self, shapes):
        key = (self.capacity, self.d_cap, "compact") + tuple(shapes)
        fn = self._step_cache.get(key)
        if fn is not None:
            return fn
        import jax
        raw = self._fused.make_resolve_step_compact(
            self.capacity, self.d_cap, *shapes, axis_name="kr")

        def shard_fn(bk, bv, table, size, dk, dv, dtable, dsize, flag, buf,
                     bounds):
            dk2, dv2, ds2, fl2, out = raw(
                bk[0], bv[0], table[0], size[0], dk[0], dv[0], dtable[0],
                dsize[0], flag[0], buf, bounds[0])
            return dk2[None], dv2[None], ds2[None], fl2[None], out

        s3 = P("kr", None, None)
        s2 = P("kr", None)
        s1 = P("kr")
        mapped = shard_map_compat(shard_fn, self.mesh,
            in_specs=(s3, s2, s3, s1, s3, s2, s3, s1, s1, P(None), s3),
            out_specs=(s3, s2, s1, s1, P(None)))
        fn = jit_sharded(mapped, donate_argnums=(4, 5, 7, 8))
        self._step_cache[key] = fn
        return fn

    def _sharded_merge(self):
        key = (self.capacity, self.d_cap)
        fn = self._merge_cache.get(key)
        if fn is not None:
            return fn
        import jax
        raw = self._fused.make_merge_step(self.capacity, self.d_cap,
                                          sharded=True)

        def shard_fn(bk, bv, size, dk, dv, dsize, flag, scalars, firsts):
            outs = raw(bk[0], bv[0], size[0], dk[0], dv[0], dsize[0],
                       flag[0], scalars, firsts[0])
            return tuple(o[None] for o in outs)

        s3 = P("kr", None, None)
        s2 = P("kr", None)
        s1 = P("kr")
        mapped = shard_map_compat(shard_fn, self.mesh,
            in_specs=(s3, s2, s1, s3, s2, s1, s1, P(None), s2),
            out_specs=(s3, s2, s3, s1, s3, s2, s1, s1))
        fn = jit_sharded(mapped, donate_argnums=(0, 1, 2, 3, 4, 5, 6))
        self._merge_cache[key] = fn
        return fn

    # -- overridden dispatch/merge -----------------------------------------
    def merge(self) -> None:
        delta_reb = max(self.oldest_version - self.version_base, 0)
        scalars = np.asarray(
            [self._rel(self.oldest_version), delta_reb], dtype=np.int32)
        mstep = self._sharded_merge()
        (self.bk, self.bv, self.table, self.size,
         self.dk, self.dv, self.dsize, self.flag) = mstep(
            self.bk, self.bv, self.size, self.dk, self.dv, self.dsize,
            self.flag, self._jnp.asarray(scalars), self._firsts)
        if self.d_cap != self._d_cap0:
            self._grow_delta(self._d_cap0)  # shrink back to the base bucket
        else:
            self.dtable = self._build_dtable()  # fresh (reset) delta tier
        self.version_base += delta_reb
        # Same lock discipline as TpuConflictSet.merge: the pipeline's
        # fetch lane corrects these under self._lock.
        with self._lock:
            self._batches_since_merge = 0
            self._delta_bound = 1
            self._delta_epoch += 1
            self._needs.clear()

    def _invoke_step(self, enc, meta):
        """Shard-map'd step over the mesh; the shared _dispatch keeps the
        delta budgeting (worst case every write lands on ONE shard, so
        the per-shard budget uses the same global bound — merges at least
        as often as the single-device backend), the _REL_LIMIT guard, and
        merge scheduling."""
        jnp = self._jnp
        if enc["compact"]:
            step = self._sharded_step_compact(enc["shapes"])
            self.dk, self.dv, self.dsize, self.flag, out = step(
                self.bk, self.bv, self.table, self.size,
                self.dk, self.dv, self.dtable, self.dsize, self.flag,
                jnp.asarray(enc["buf"]), self.bounds)
        else:
            t_cap, r_cap, w_cap = enc["caps"]
            step = self._sharded_step(t_cap, r_cap, w_cap)
            self.dk, self.dv, self.dsize, self.flag, out = step(
                self.bk, self.bv, self.table, self.size,
                self.dk, self.dv, self.dtable, self.dsize, self.flag,
                jnp.asarray(enc["digests"]), jnp.asarray(meta), self.bounds)
        # Hoisted per-shard delta tables for the next batch (see
        # fused.delta_table_step): enqueued right after the insert.
        self.dtable = self._build_dtable()
        return out

    # -- introspection ------------------------------------------------------
    def shard_sizes(self) -> List[int]:
        """Live base-boundary count per shard (syncs the device)."""
        return [int(x) for x in np.asarray(self.size)]

    @classmethod
    def supervised(cls, mesh: Mesh, oldest_version=0, monitor=None,
                   **kwargs):
        """The mesh-sharded backend under the supervision layer
        (conflict/supervisor.py): deadline-budgeted dispatch, health
        monitoring, degrade-to-CPU against the exact mirror, re-probe /
        promotion (the promotion replay rebuilds the whole sharded window
        from the mirror history), and the exact long-key recheck.  This is
        the production-shaped entry point for a resolver running its
        window across chips."""
        from ..conflict.supervisor import SupervisedConflictSet

        def make_device(oldest_version=oldest_version):
            return cls(mesh, oldest_version, **kwargs)

        return SupervisedConflictSet(make_device, oldest_version,
                                     monitor=monitor)
