"""Key-range-sharded conflict window across a device mesh.

The reference scales conflict resolution by partitioning the keyspace across
resolvers and min-combining their verdicts at the proxy
(CommitProxyServer.actor.cpp:152-181 request fan-out, :800-806 min-combine;
rebalancing masterserver.actor.cpp:1318).  The TPU formulation shards the
same axis across chips inside ONE resolver:

  * the digest space [0, 2^192) is split into D contiguous sub-ranges, one
    per device along mesh axis "kr";
  * each device holds a full window (conflict/window.py arrays) restricted
    to its sub-range: inserts are CLIPPED to the owned range on-device, so
    V_d(k) == V(k) exactly for k in shard d;
  * a batch query is broadcast, clipped per shard, answered locally, and the
    partial conflict bitmaps are OR-reduced (psum of int32) over "kr" — the
    device-side analog of the proxy's min-combine;
  * the query batch itself is data-parallel over mesh axis "q".

All collectives ride ICI (psum inside shard_map over the mesh); the host
only ships the batch once.  This is BASELINE.json config 5 ("sharded version
window across 4 chips: psum-merged conflict bitmap").
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.digest import KEY_LANES, MAX_DIGEST, lex_less
from ..ops.rangemax import NEG_INF
from .. import conflict  # noqa: F401  (keep package import order stable)
from ..conflict.window import WindowState, window_gc, window_insert, window_query


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """jax.shard_map across jax versions: the public API (with its vma
    checking disabled — our steps mix replicated and sharded operands
    freely) when present, else the identical jax.experimental entry point
    older jax ships (where the same switch is spelled check_rep)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def jit_sharded(mapped, donate_argnums=()):
    """jit for shard_map'd programs.  Buffer donation composes with the
    experimental shard_map of older jax incorrectly — on 0.4.x XLA:CPU it
    produced wrong verdicts and heap corruption (aliased donated state
    read after reuse) — so donation is applied only where the modern
    public jax.shard_map exists."""
    if donate_argnums and hasattr(jax, "shard_map"):
        return jax.jit(mapped, donate_argnums=donate_argnums)
    return jax.jit(mapped)


def default_mesh_axes(n_devices: int) -> Tuple[int, int]:
    """Factor n into (kr, q): prefer up to 4 key-range shards, rest data."""
    kr = 1
    while kr < 4 and (n_devices % (kr * 2)) == 0:
        kr *= 2
    return kr, n_devices // kr


def make_conflict_mesh(devices: Optional[Sequence] = None,
                       n_devices: Optional[int] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        devices = devices[:n_devices]
    kr, q = default_mesh_axes(len(devices))
    dev_array = np.asarray(devices).reshape(kr, q)
    return Mesh(dev_array, ("kr", "q"))


def digest_splits(n_shards: int) -> np.ndarray:
    """uint32[n+1, 6] split points: shard d owns digest range [s[d], s[d+1]).

    Even splits of the first lane; the last split is the MAX_DIGEST sentinel
    (strictly above every real key digest)."""
    splits = np.zeros((n_shards + 1, KEY_LANES), dtype=np.uint32)
    for d in range(1, n_shards):
        splits[d, 0] = np.uint32((d * (1 << 32)) // n_shards)
    splits[n_shards] = MAX_DIGEST
    return splits


def splits_from_sample(sample_digests: np.ndarray,
                       n_shards: int) -> np.ndarray:
    """Equi-depth split points from a planar digest sample (uint32[8, N])
    -> uint32[n+1, 8], the `splits=` input of ShardedTpuConflictSet.

    digest_splits' even lane-0 cuts balance only keyspaces spread across
    the first four key bytes; real workloads share long common prefixes
    (every bench key starts "k0000...", every tenant key its tenant id),
    which lands the WHOLE window on one shard and voids the capacity
    multiplier.  This is the resolver-keyrange analog of the reference's
    load-driven split points (masterserver resolutionBalancing): cut at
    the sample's d/n quantiles over full-width digests."""
    from ..ops.digest import DIGEST_BYTES, planar_to_s24
    s = np.sort(planar_to_s24(sample_digests))
    splits = np.zeros((n_shards + 1, KEY_LANES), dtype=np.uint32)
    for d in range(1, n_shards):
        q = s[min(s.size - 1, (d * s.size) // n_shards)]
        splits[d] = np.frombuffer(q, dtype=">u4").astype(np.uint32)
    splits[n_shards] = MAX_DIGEST
    return splits


from ..ops.digest import lex_max_cols as _lex_max_cols  # noqa: E402
from ..ops.digest import lex_min_cols as _lex_min_cols  # noqa: E402


class ShardedWindow:
    """Host handle for a conflict window sharded over mesh axis "kr".

    State arrays carry a leading shard axis of size D(kr):
        bk:   uint32[D, 6, CAP]   sharded P("kr") (planar, ops/digest.py)
        bv:   int32[D, CAP]       sharded P("kr")
        size: int32[D]            sharded P("kr")
    Queries/writes enter replicated; conflict bits leave sharded over "q".
    """

    def __init__(self, mesh: Mesh, capacity: int = 1 << 14) -> None:
        assert "kr" in mesh.axis_names and "q" in mesh.axis_names
        self.mesh = mesh
        self.capacity = capacity
        self.n_shards = mesh.shape["kr"]
        splits = digest_splits(self.n_shards)
        kr_sharding = NamedSharding(mesh, P("kr"))

        d = self.n_shards
        bk = np.broadcast_to(MAX_DIGEST[None, :, None],
                             (d, KEY_LANES, capacity)).copy()
        bv = np.full((d, capacity), int(NEG_INF), dtype=np.int32)
        # Each shard's base segment starts at its own lower split and carries
        # version 0 (== the window floor at creation).
        bk[:, :, 0] = splits[:d]
        bv[:, 0] = 0
        size = np.ones((d,), dtype=np.int32)
        self.bk = jax.device_put(bk, kr_sharding)
        self.bv = jax.device_put(bv, kr_sharding)
        self.size = jax.device_put(size, kr_sharding)
        self.shard_lo = jax.device_put(splits[:d], kr_sharding)
        self.shard_hi = jax.device_put(splits[1:], kr_sharding)
        self._step = self._build_step()
        self._gc = self._build_gc()

    # -- jitted sharded programs -------------------------------------------
    def _build_step(self):
        mesh = self.mesh

        def shard_fn(lo, hi, bk, bv, size,
                     qb, qe, qsnap, qvalid, wb, we, wvalid, now_rel):
            # block shapes: lo/hi [1,6]; bk [1,6,CAP]; bv [1,CAP]; size [1];
            # queries sharded over "q": qb [6, R/Q]; writes replicated [6, W].
            lo_r, hi_r = lo[0], hi[0]
            bk0, bv0, size0 = bk[0], bv[0], size[0]
            # --- query: clip to shard, answer locally, OR-reduce over kr ---
            cqb = _lex_max_cols(qb, lo_r)
            cqe = _lex_min_cols(qe, hi_r)
            qv = qvalid & lex_less(cqb, cqe)
            local_bits = window_query(bk0, bv0, cqb, cqe, qsnap, qv)
            bits = jax.lax.psum(local_bits.astype(jnp.int32), "kr") > 0
            # --- insert: clip writes to shard, merge locally ---------------
            cwb = _lex_max_cols(wb, lo_r)
            cwe = _lex_min_cols(we, hi_r)
            wv = wvalid & lex_less(cwb, cwe)
            (nbk, nbv, nsize), ovf = window_insert(
                WindowState(bk0, bv0, size0), cwb, cwe, wv, now_rel)
            # All-or-nothing across shards: if ANY shard overflowed, every
            # shard keeps its pre-insert state (window_insert's own
            # unchanged-on-overflow contract, lifted to the mesh).  Otherwise
            # a skewed batch would commit its writes on the non-full shards
            # only, leaving V(k) wrong on part of the keyspace and making a
            # gc()+retry falsely conflict with the batch's own inserts.
            ovf_any = jax.lax.psum(ovf.astype(jnp.int32), ("kr", "q")) > 0
            nbk = jnp.where(ovf_any, bk0, nbk)
            nbv = jnp.where(ovf_any, bv0, nbv)
            nsize = jnp.where(ovf_any, size0, nsize)
            return (bits, nbk[None], nbv[None], nsize[None], ovf_any)

        mapped = shard_map_compat(shard_fn, mesh,
            in_specs=(P("kr"), P("kr"), P("kr"), P("kr"), P("kr"),
                      P(None, "q"), P(None, "q"), P("q"), P("q"),
                      P(), P(), P(), P()),
            out_specs=(P("q"), P("kr"), P("kr"), P("kr"), P()))
        return jax.jit(mapped)

    def _build_gc(self):
        mesh = self.mesh

        def shard_fn(bk, bv, size, oldest_rel, delta):
            st = window_gc(WindowState(bk[0], bv[0], size[0]), oldest_rel, delta)
            return st.bk[None], st.bv[None], st.size[None]

        mapped = shard_map_compat(shard_fn, mesh,
            in_specs=(P("kr"), P("kr"), P("kr"), P(), P()),
            out_specs=(P("kr"), P("kr"), P("kr")))
        return jax.jit(mapped)

    # -- public API ---------------------------------------------------------
    def resolve_step(self, qb, qe, qsnap, qvalid, wb, we, wvalid,
                     now_rel: int):
        """One fused device step: batched history check + insert of writes.

        Array args are host numpy (or device) arrays, query batch padded to a
        multiple of mesh axis "q".  Returns (bits[R] bool, overflow bool).
        On overflow the window is left UNCHANGED on every shard (the insert
        is all-or-nothing across the mesh); the caller may gc() and re-issue
        the identical step."""
        bits, self.bk, self.bv, self.size, ovf = self._step(
            self.shard_lo, self.shard_hi, self.bk, self.bv, self.size,
            jnp.asarray(qb), jnp.asarray(qe),
            jnp.asarray(qsnap), jnp.asarray(qvalid),
            jnp.asarray(wb), jnp.asarray(we), jnp.asarray(wvalid),
            jnp.int32(now_rel))
        return bits, ovf

    def gc(self, oldest_rel: int, rebase_delta: int = 0) -> None:
        self.bk, self.bv, self.size = self._gc(
            self.bk, self.bv, self.size,
            jnp.int32(oldest_rel), jnp.int32(rebase_delta))
