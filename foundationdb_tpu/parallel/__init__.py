"""Multi-chip parallelism: key-range sharded conflict window over a Mesh.

FDB's parallelism axes (SURVEY.md §2.5) map onto mesh axes:
  * "kr"  — key-range sharding of conflict resolution (the resolver axis;
            reference ProxyCommitData::keyResolvers fan-out with min-combine,
            CommitProxyServer.actor.cpp:152-181,800-806).  Here: the conflict
            window is sharded by digest range; per-shard partial conflict
            bitmaps are OR-reduced with psum over ICI.
  * "q"   — data parallelism over the query batch (independent read-range
            checks of one commit batch spread across chips).
"""

from .sharded_window import (ShardedWindow, default_mesh_axes,
                             make_conflict_mesh)

__all__ = ["ShardedWindow", "make_conflict_mesh", "default_mesh_axes"]
