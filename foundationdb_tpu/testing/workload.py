"""TestWorkload: composable test units run by the tester.

Reference: fdbserver/workloads/workloads.actor.h:60-82 — every workload
implements setup (populate), start (drive traffic / inject faults), check
(verify invariants after quiescence), getMetrics; workloads compose in one
test spec (e.g. Cycle + RandomClogging + Attrition) and run concurrently
against the same simulated cluster.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Type

from ..core.error import FdbError


class TestWorkload:
    """Base class. Subclasses register via @register_workload."""

    name = "base"

    def __init__(self, cluster, db, config: Dict[str, Any]) -> None:
        self.cluster = cluster      # SimFdbCluster (fault APIs live here)
        self.db = db
        self.config = config
        self.metrics: Dict[str, float] = {}

    async def setup(self) -> None:          # populate initial data
        return

    async def start(self) -> None:          # drive load / chaos
        return

    async def check(self) -> bool:          # verify invariants
        return True

    def get_metrics(self) -> Dict[str, float]:
        return dict(self.metrics)

    # -- helpers shared by workloads -----------------------------------------
    async def run_transaction(self, fn: Callable) -> Any:
        """Retry loop: `await fn(txn)`, commit, retry on retryable errors."""
        txn = self.db.create_transaction()
        while True:
            try:
                result = await fn(txn)
                await txn.commit()
                return result
            except FdbError as e:
                await txn.on_error(e)


workload_registry: Dict[str, Type[TestWorkload]] = {}


def register_workload(cls: Type[TestWorkload]) -> Type[TestWorkload]:
    workload_registry[cls.name] = cls
    return cls
