"""Deterministic-simulation test harness: workloads, specs, tester.

Reference layer: fdbserver/workloads/ + fdbserver/tester.actor.cpp +
tests/*.toml (SURVEY.md §4)."""

from .workload import TestWorkload, register_workload, workload_registry  # noqa: F401
from .tester import (NondeterminismAudit, SimRunReport,  # noqa: F401
                     effective_hash_seed, load_spec,
                     repro_hash_seed_prefix, run_simulation, run_test,
                     run_test_twice)
