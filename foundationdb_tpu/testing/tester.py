"""The tester: runs a test spec (workload composition) against a simulated
cluster through setup -> start -> quiescence -> check.

Reference: fdbserver/tester.actor.cpp runTests (:1603) / runWorkload
(:755) — reads a TOML spec (tests/*.toml), instantiates registered
workloads, runs their phases (chaos workloads run concurrently with the
invariant workloads' start phase), waits for quiescence (QuietDatabase:
recovery settled, queues drained), then runs every workload's check.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

try:
    import tomllib                      # Python >= 3.11
except ImportError:                     # pragma: no cover - version-dependent
    try:
        import tomli as tomllib         # the pre-3.11 backport, if present
    except ImportError:
        tomllib = None                  # minimal built-in parser below


from ..core.error import FdbError
from ..core.futures import wait_all
from ..core.scheduler import delay, spawn
from ..core.trace import Severity, TraceEvent
from .workload import TestWorkload, workload_registry
from . import workloads as _builtin  # noqa: F401 - populates the registry


def _parse_toml_subset(text: str) -> Dict[str, Any]:
    """Parser for the TOML subset the test specs use (no external deps —
    the container's Python may predate tomllib): comments, [table] /
    [[array.of.tables]] headers with dotted paths, and scalar
    `key = value` pairs (single/double-quoted strings, ints, floats,
    booleans).  Nested inline structures are not needed by any spec."""

    def scalar(raw: str) -> Any:
        raw = raw.strip()
        if len(raw) >= 2 and raw[0] == raw[-1] and raw[0] in "'\"":
            return raw[1:-1]
        if raw in ("true", "false"):
            return raw == "true"
        try:
            return int(raw)
        except ValueError:
            return float(raw)           # raises on junk: better than silent

    root: Dict[str, Any] = {}
    current = root
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("["):
            is_array = line.startswith("[[")
            close = line.find("]]" if is_array else "]")
            if close < 0:
                raise ValueError(f"unclosed table header line {lineno}: "
                                 f"{line!r}")
            path = line[2 if is_array else 1:close].strip().split(".")
            node: Any = root
            for part in path[:-1]:
                nxt = node.setdefault(part, {})
                if isinstance(nxt, list):   # descend into latest entry
                    nxt = nxt[-1]
                node = nxt
            leaf = path[-1]
            if is_array:
                node.setdefault(leaf, []).append({})
                current = node[leaf][-1]
            else:
                current = node.setdefault(leaf, {})
        elif "=" in line:
            key, _, raw = line.partition("=")
            raw = raw.strip()
            if raw and raw[0] in "'\"":
                # Quoted string: everything past the CLOSING quote (e.g.
                # an inline comment) is dropped.
                close = raw.find(raw[0], 1)
                if close < 0:
                    raise ValueError(f"unclosed string line {lineno}: "
                                     f"{line!r}")
                raw = raw[:close + 1]
            elif "#" in raw:
                raw = raw.split("#", 1)[0]
            current[key.strip()] = scalar(raw)
        else:
            raise ValueError(f"unparseable spec line {lineno}: {line!r}")
    return root

def load_spec(path_or_text: str) -> Dict[str, Any]:
    """Parse a TOML test spec (reference tests/fast/*.toml layout):

        [[test]]
        testTitle = 'CycleTest'
          [[test.workload]]
          testName = 'Cycle'
          nodeCount = 16
          [[test.workload]]
          testName = 'RandomClogging'
    """
    if "\n" in path_or_text or "[" in path_or_text.split("\n")[0]:
        text = path_or_text
    else:
        with open(path_or_text, "rb") as f:
            text = f.read().decode()
    if tomllib is not None:
        return tomllib.loads(text)
    return _parse_toml_subset(text)


async def quiet_database(cluster, db, timeout: float = 60.0) -> None:
    """Wait for the cluster to settle (reference QuietDatabase.actor.cpp):
    recovery complete and a probe transaction commits."""
    from ..core.scheduler import now
    deadline = now() + timeout
    while now() < deadline:
        cc = cluster.current_cc()
        if cc is not None and cc.db_info.recovery_state in (
                "accepting_commits", "fully_recovered"):
            try:
                t = db.create_transaction()
                t.set(b"\x02quiet_probe", b"1")
                await t.commit()
                return
            except FdbError:
                pass
        await delay(1.0)
    raise FdbError(1004, "timed_out", "quiet_database timed out")


def effective_hash_seed() -> Optional[str]:
    """The PYTHONHASHSEED this process effectively runs under, or None
    when str hashing is randomized.  SAME-process double runs
    (run_test_twice) never need it, but CROSS-process unseed
    reproduction does: str-set iteration orders depend on the per-process
    hash salt, so an unpinned replay of a failing seed can diverge for a
    reason that has nothing to do with the bug being chased (ROADMAP
    chaos follow-up; regression-tested with the HashOrderCanary
    workload)."""
    import os
    import sys
    if not sys.flags.hash_randomization:
        # -R off entirely (e.g. PYTHONHASHSEED=0): hashing is the
        # documented fixed function — any process reproduces it.
        return "0"
    seed = os.environ.get("PYTHONHASHSEED", "")
    if seed and seed != "random":
        return seed
    return None


def repro_hash_seed_prefix() -> str:
    """Env prefix every cross-process repro command must carry.  When the
    current process is itself randomized the prefix pins "0" — the repro
    then reproduces the BUG CLASS deterministically even though it cannot
    replay this exact process's str orders."""
    return f"PYTHONHASHSEED={effective_hash_seed() or '0'} "


class NondeterminismAudit:
    """Runtime detector of nondeterminism sources under simulation
    (reference: the simulator's whole contract is that NOTHING reads the
    outside world).  While installed, wall-clock and OS-entropy entry
    points are wrapped to record any caller that lives inside THIS
    package (third-party/test callers are someone else's business).
    Findings are (function, file, line) tuples.

    Allowlisted modules hold the framework's sanctioned escape hatches:
    core/rng.py seeds the nondeterministic id generator from os.urandom
    by design; core/scheduler.py reads the monotonic clock for its
    real-mode epoch; threadpool/profiler/real_* are real-mode only."""

    PATCHES = (("time", "time"), ("time", "time_ns"),
               ("time", "monotonic"), ("time", "perf_counter"),
               ("os", "urandom"), ("random", "random"),
               ("random", "randrange"), ("random", "getrandbits"))
    ALLOWED_FILES = ("core/rng.py", "core/scheduler.py",
                     "core/threadpool.py", "core/profiler.py",
                     "rpc/real_network.py", "server/real_fs.py")

    def __init__(self) -> None:
        import os as _os
        self.findings: List[tuple] = []
        self._saved: List[tuple] = []
        pkg_dir = _os.path.dirname(_os.path.dirname(
            _os.path.abspath(__file__)))
        self._pkg_prefix = pkg_dir + _os.sep

    def _record(self, func_name: str) -> None:
        import sys
        frame = sys._getframe(2)
        fn = frame.f_code.co_filename
        if not fn.startswith(self._pkg_prefix):
            return
        rel = fn[len(self._pkg_prefix):].replace("\\", "/")
        if rel.endswith(self.ALLOWED_FILES):
            return
        entry = (func_name, rel, frame.f_lineno)
        if entry not in self.findings:
            self.findings.append(entry)

    def __enter__(self) -> "NondeterminismAudit":
        import importlib
        for mod_name, attr in self.PATCHES:
            mod = importlib.import_module(mod_name)
            orig = getattr(mod, attr)

            def make(orig=orig, label=f"{mod_name}.{attr}"):
                def wrapped(*a, **kw):
                    self._record(label)
                    return orig(*a, **kw)
                return wrapped
            self._saved.append((mod, attr, orig))
            setattr(mod, attr, make())
        return self

    def __exit__(self, *exc) -> None:
        for mod, attr, orig in self._saved:
            setattr(mod, attr, orig)
        self._saved.clear()


class SimRunReport:
    """Everything one deterministic simulation run leaves behind."""

    def __init__(self, seed: int, metrics, unseed: int, digest: int,
                 folds: int, checkpoints, nondeterminism) -> None:
        self.seed = seed
        self.metrics = metrics
        self.unseed = unseed
        self.digest = digest
        self.folds = folds
        self.checkpoints = list(checkpoints)
        self.nondeterminism = nondeterminism

    def __repr__(self) -> str:  # pragma: no cover
        return (f"SimRunReport(seed={self.seed}, unseed={self.unseed:#010x},"
                f" digest={self.digest:#010x}, folds={self.folds})")


def run_simulation(spec, seed: int, *, buggify: bool = False,
                   config=None, n_workers: int = 7,
                   n_storage_workers: int = 2, timeout: float = 1800.0,
                   audit: bool = True) -> SimRunReport:
    """One fully-seeded simulation run of a test spec on a fresh world:
    fresh deterministic RNG, fresh run digest, fresh event loop +
    SimFdbCluster — and a SimRunReport carrying the run's unseed.

    Cyclic GC is disabled for the run's duration (after a full collect):
    gc timing depends on allocation counters carried over from PREVIOUS
    work in this process, so a gc pass firing __del__-driven broken-
    promise delivery mid-run would make two otherwise identical runs
    diverge.  Plain refcount-driven finalization is deterministic and
    stays on."""
    import gc
    from ..core.buggify import enable_buggify
    from ..core.rng import (DeterministicRandom, reset_run_digest,
                            run_unseed, set_deterministic_random)
    from ..core.scheduler import set_event_loop
    from ..rpc.sim import set_simulator
    from ..server.cluster import SimFdbCluster
    from ..server.interfaces import DatabaseConfiguration

    spec = load_spec(spec) if isinstance(spec, str) else spec
    # Spec-driven SIM topology: a top-level [sim] table sizes the worker
    # pool (the [cluster] table only shapes the recruited database).  A
    # chaos spec that needs spare storage capacity — e.g. fatal-disk
    # attrition under storage_replication=2 needs a third storage worker
    # for the policy guard to ever allow a kill — carries it itself
    # instead of relying on every runner's defaults.
    sim_conf = dict(spec.get("sim") or {})
    n_workers = int(sim_conf.pop("n_workers", n_workers))
    n_storage_workers = int(sim_conf.pop("n_storage_workers",
                                         n_storage_workers))
    if sim_conf:
        raise KeyError(f"unknown [sim] fields {sorted(sim_conf)} in spec")
    if config is None:
        # Spec-driven cluster shape: a top-level [cluster] table overrides
        # the default DatabaseConfiguration field-by-field (e.g.
        # `n_resolvers = 2` boots the partitioned resolution plane for a
        # chaos spec).  Unknown keys are rejected loudly — a typo'd field
        # silently running the default topology would void the spec.
        fields = dict(n_tlogs=2, log_replication=2, n_storage=2,
                      storage_replication=2)
        for k, v in (spec.get("cluster") or {}).items():
            if k not in DatabaseConfiguration._INT_FIELDS and \
                    k not in DatabaseConfiguration._STR_FIELDS:
                raise KeyError(f"unknown [cluster] field {k!r} in spec")
            fields[k] = v
        config = DatabaseConfiguration(**fields)
    # Spec-driven knob overrides: a top-level [knobs] table sets server
    # knobs for the run's duration (e.g. the SchedChaosTest spec turns
    # every SCHED_* stage on) and restores them afterwards — the spec
    # carries its own posture instead of relying on runner defaults.
    # Unknown names are rejected loudly, like [cluster]/[sim] fields.
    from ..core.knobs import client_knobs, server_knobs
    sknobs = server_knobs()
    cknobs = client_knobs()
    knob_overrides = dict(spec.get("knobs") or {})
    # Validate EVERY name before setting ANY value: a KeyError raised
    # mid-application would leak the earlier overrides into the rest of
    # the process (the finally below only restores what was saved).
    # Names resolve against the server registry first, then the client
    # one (e.g. GRV_LEASE_S for the e2e-throughput chaos spec) —
    # unambiguous because the registries share no names.
    def _knob_target(k: str):
        if k.startswith("_"):
            raise KeyError(f"unknown [knobs] field {k!r} in spec")
        if hasattr(sknobs, k):
            return sknobs
        if hasattr(cknobs, k):
            return cknobs
        raise KeyError(f"unknown [knobs] field {k!r} in spec")

    for k in knob_overrides:
        _knob_target(k)
    saved_knobs: Dict[str, Any] = {}
    for k, v in knob_overrides.items():
        tgt = _knob_target(k)
        saved_knobs[k] = (tgt, getattr(tgt, k))
        setattr(tgt, k, v)
    gc_was_enabled = gc.isenabled()
    gc.collect()
    gc.disable()
    set_deterministic_random(DeterministicRandom(seed))
    digest = reset_run_digest()
    enable_buggify(buggify)
    auditor = NondeterminismAudit() if audit else None
    try:
        if auditor is not None:
            auditor.__enter__()
        try:
            cluster = SimFdbCluster(
                config=config,
                n_workers=n_workers, n_storage_workers=n_storage_workers)

            async def go():
                return await run_test(cluster, spec)

            metrics = cluster.run_until(cluster.loop.spawn(go()),
                                        timeout=timeout)
        finally:
            if auditor is not None:
                auditor.__exit__()
        return SimRunReport(
            seed=seed, metrics=metrics, unseed=run_unseed(),
            digest=digest.value, folds=digest.folds,
            checkpoints=digest.checkpoints,
            nondeterminism=auditor.findings if auditor else [])
    finally:
        enable_buggify(False)
        set_simulator(None)
        set_event_loop(None)
        for k, (tgt, v) in saved_knobs.items():
            setattr(tgt, k, v)
        if gc_was_enabled:
            gc.enable()


def _divergence_report(r1: SimRunReport, r2: SimRunReport,
                       tail: int = 8) -> str:
    """First-divergence triage between two same-seed runs: align the
    periodic digest checkpoints, find the first disagreeing one, and
    show the last `tail` checkpoints around it from both runs."""
    lines = [
        f"unseed mismatch for seed {r1.seed}: "
        f"{r1.unseed:#010x} != {r2.unseed:#010x} "
        f"(digest {r1.digest:#010x} vs {r2.digest:#010x}, "
        f"folds {r1.folds} vs {r2.folds})"]
    c1, c2 = r1.checkpoints, r2.checkpoints
    first = None
    for i in range(min(len(c1), len(c2))):
        if c1[i] != c2[i]:
            first = i
            break
    if first is None and len(c1) != len(c2):
        first = min(len(c1), len(c2))
    if first is None:
        lines.append("checkpoints identical — divergence after the last "
                     "checkpoint (tail of the run)")
    else:
        lines.append(f"first divergent checkpoint: #{first} "
                     f"(~fold {(first + 1) * 1024})")
        lo = max(0, first - tail // 2)
        for run_name, cps in (("run1", c1), ("run2", c2)):
            lines.append(f"  {run_name} checkpoints "
                         f"[{lo}..{min(first + tail // 2, len(cps) - 1)}]:")
            for j in range(lo, min(first + tail // 2 + 1, len(cps))):
                folds, value, last_event, t = cps[j]
                marker = " <-- FIRST DIVERGENCE" if j == first else ""
                lines.append(f"    #{j} folds={folds} "
                             f"digest={value:#010x} t={t:.6f} "
                             f"last_event={last_event!r}{marker}")
    for run_name, r in (("run1", r1), ("run2", r2)):
        if r.nondeterminism:
            lines.append(f"  {run_name} nondeterminism sources flagged:")
            for func, file, lineno in r.nondeterminism:
                lines.append(f"    {func} called from {file}:{lineno}")
    if effective_hash_seed() is None:
        lines.append(
            "note: str hashing is RANDOMIZED in this process — set-order "
            "divergence cannot be reproduced elsewhere; re-run repros "
            "with " + repro_hash_seed_prefix().strip())
    return "\n".join(lines)


def run_test_twice(spec, seed: int, **kw):
    """Replay the identical (spec, seed) twice and assert unseed
    equality (reference TestHarness unseed check: same seed, same run —
    bit for bit).  On divergence, raises AssertionError carrying a
    first-divergence report over the digest checkpoint trail plus any
    nondeterminism sources the audit flagged.  Returns both reports."""
    r1 = run_simulation(spec, seed, **kw)
    r2 = run_simulation(spec, seed, **kw)
    if r1.unseed != r2.unseed or r1.digest != r2.digest or \
            r1.folds != r2.folds:
        raise AssertionError(_divergence_report(r1, r2))
    return r1, r2


async def run_test(cluster, spec: Dict[str, Any],
                   db=None) -> Dict[str, Dict[str, float]]:
    """Run one [[test]] entry; returns {workload: metrics}.  Raises
    AssertionError if any workload's check fails."""
    db = db or cluster.database()
    all_metrics: Dict[str, Dict[str, float]] = {}
    for test in spec.get("test", []):
        title = test.get("testTitle", "unnamed")
        TraceEvent("TestStart").detail("Title", title).log()
        instances: List[TestWorkload] = []
        for wconf in test.get("workload", []):
            name = wconf["testName"]
            cls = workload_registry.get(name)
            if cls is None:
                raise KeyError(f"unknown workload {name!r} "
                               f"(registered: {sorted(workload_registry)})")
            instances.append(cls(cluster, db, dict(wconf)))

        # Phase 1: setup, sequentially (reference runs setup before start).
        for w in instances:
            await w.setup()
        # Phase 2: start — ALL workloads concurrently (chaos + load mix).
        await wait_all([spawn(w.start(), f"workload.{w.name}.start")
                        for w in instances])
        # Phase 3: quiescence.
        await quiet_database(cluster, db)
        # Phase 4: check.
        for w in instances:
            ok = await w.check()
            TraceEvent("TestCheck",
                       Severity.Info if ok else Severity.Error).detail(
                "Workload", w.name).detail("Ok", ok).log()
            assert ok, f"workload {w.name} check FAILED in test {title!r}"
            all_metrics[w.name] = w.get_metrics()
        TraceEvent("TestComplete").detail("Title", title).log()
    return all_metrics
