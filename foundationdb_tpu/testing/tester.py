"""The tester: runs a test spec (workload composition) against a simulated
cluster through setup -> start -> quiescence -> check.

Reference: fdbserver/tester.actor.cpp runTests (:1603) / runWorkload
(:755) — reads a TOML spec (tests/*.toml), instantiates registered
workloads, runs their phases (chaos workloads run concurrently with the
invariant workloads' start phase), waits for quiescence (QuietDatabase:
recovery settled, queues drained), then runs every workload's check.
"""

from __future__ import annotations

import tomllib
from typing import Any, Dict, List, Optional

from ..core.error import FdbError
from ..core.futures import wait_all
from ..core.scheduler import delay, spawn
from ..core.trace import Severity, TraceEvent
from .workload import TestWorkload, workload_registry
from . import workloads as _builtin  # noqa: F401 - populates the registry


def load_spec(path_or_text: str) -> Dict[str, Any]:
    """Parse a TOML test spec (reference tests/fast/*.toml layout):

        [[test]]
        testTitle = 'CycleTest'
          [[test.workload]]
          testName = 'Cycle'
          nodeCount = 16
          [[test.workload]]
          testName = 'RandomClogging'
    """
    if "\n" in path_or_text or "[" in path_or_text.split("\n")[0]:
        return tomllib.loads(path_or_text)
    with open(path_or_text, "rb") as f:
        return tomllib.load(f)


async def quiet_database(cluster, db, timeout: float = 60.0) -> None:
    """Wait for the cluster to settle (reference QuietDatabase.actor.cpp):
    recovery complete and a probe transaction commits."""
    from ..core.scheduler import now
    deadline = now() + timeout
    while now() < deadline:
        cc = cluster.current_cc()
        if cc is not None and cc.db_info.recovery_state in (
                "accepting_commits", "fully_recovered"):
            try:
                t = db.create_transaction()
                t.set(b"\x02quiet_probe", b"1")
                await t.commit()
                return
            except FdbError:
                pass
        await delay(1.0)
    raise FdbError(1004, "timed_out", "quiet_database timed out")


async def run_test(cluster, spec: Dict[str, Any],
                   db=None) -> Dict[str, Dict[str, float]]:
    """Run one [[test]] entry; returns {workload: metrics}.  Raises
    AssertionError if any workload's check fails."""
    db = db or cluster.database()
    all_metrics: Dict[str, Dict[str, float]] = {}
    for test in spec.get("test", []):
        title = test.get("testTitle", "unnamed")
        TraceEvent("TestStart").detail("Title", title).log()
        instances: List[TestWorkload] = []
        for wconf in test.get("workload", []):
            name = wconf["testName"]
            cls = workload_registry.get(name)
            if cls is None:
                raise KeyError(f"unknown workload {name!r} "
                               f"(registered: {sorted(workload_registry)})")
            instances.append(cls(cluster, db, dict(wconf)))

        # Phase 1: setup, sequentially (reference runs setup before start).
        for w in instances:
            await w.setup()
        # Phase 2: start — ALL workloads concurrently (chaos + load mix).
        await wait_all([spawn(w.start(), f"workload.{w.name}.start")
                        for w in instances])
        # Phase 3: quiescence.
        await quiet_database(cluster, db)
        # Phase 4: check.
        for w in instances:
            ok = await w.check()
            TraceEvent("TestCheck",
                       Severity.Info if ok else Severity.Error).detail(
                "Workload", w.name).detail("Ok", ok).log()
            assert ok, f"workload {w.name} check FAILED in test {title!r}"
            all_metrics[w.name] = w.get_metrics()
        TraceEvent("TestComplete").detail("Title", title).log()
    return all_metrics
