"""The tester: runs a test spec (workload composition) against a simulated
cluster through setup -> start -> quiescence -> check.

Reference: fdbserver/tester.actor.cpp runTests (:1603) / runWorkload
(:755) — reads a TOML spec (tests/*.toml), instantiates registered
workloads, runs their phases (chaos workloads run concurrently with the
invariant workloads' start phase), waits for quiescence (QuietDatabase:
recovery settled, queues drained), then runs every workload's check.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

try:
    import tomllib                      # Python >= 3.11
except ImportError:                     # pragma: no cover - version-dependent
    try:
        import tomli as tomllib         # the pre-3.11 backport, if present
    except ImportError:
        tomllib = None                  # minimal built-in parser below


from ..core.error import FdbError
from ..core.futures import wait_all
from ..core.scheduler import delay, spawn
from ..core.trace import Severity, TraceEvent
from .workload import TestWorkload, workload_registry
from . import workloads as _builtin  # noqa: F401 - populates the registry


def _parse_toml_subset(text: str) -> Dict[str, Any]:
    """Parser for the TOML subset the test specs use (no external deps —
    the container's Python may predate tomllib): comments, [table] /
    [[array.of.tables]] headers with dotted paths, and scalar
    `key = value` pairs (single/double-quoted strings, ints, floats,
    booleans).  Nested inline structures are not needed by any spec."""

    def scalar(raw: str) -> Any:
        raw = raw.strip()
        if len(raw) >= 2 and raw[0] == raw[-1] and raw[0] in "'\"":
            return raw[1:-1]
        if raw in ("true", "false"):
            return raw == "true"
        try:
            return int(raw)
        except ValueError:
            return float(raw)           # raises on junk: better than silent

    root: Dict[str, Any] = {}
    current = root
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("["):
            is_array = line.startswith("[[")
            close = line.find("]]" if is_array else "]")
            if close < 0:
                raise ValueError(f"unclosed table header line {lineno}: "
                                 f"{line!r}")
            path = line[2 if is_array else 1:close].strip().split(".")
            node: Any = root
            for part in path[:-1]:
                nxt = node.setdefault(part, {})
                if isinstance(nxt, list):   # descend into latest entry
                    nxt = nxt[-1]
                node = nxt
            leaf = path[-1]
            if is_array:
                node.setdefault(leaf, []).append({})
                current = node[leaf][-1]
            else:
                current = node.setdefault(leaf, {})
        elif "=" in line:
            key, _, raw = line.partition("=")
            raw = raw.strip()
            if raw and raw[0] in "'\"":
                # Quoted string: everything past the CLOSING quote (e.g.
                # an inline comment) is dropped.
                close = raw.find(raw[0], 1)
                if close < 0:
                    raise ValueError(f"unclosed string line {lineno}: "
                                     f"{line!r}")
                raw = raw[:close + 1]
            elif "#" in raw:
                raw = raw.split("#", 1)[0]
            current[key.strip()] = scalar(raw)
        else:
            raise ValueError(f"unparseable spec line {lineno}: {line!r}")
    return root

def load_spec(path_or_text: str) -> Dict[str, Any]:
    """Parse a TOML test spec (reference tests/fast/*.toml layout):

        [[test]]
        testTitle = 'CycleTest'
          [[test.workload]]
          testName = 'Cycle'
          nodeCount = 16
          [[test.workload]]
          testName = 'RandomClogging'
    """
    if "\n" in path_or_text or "[" in path_or_text.split("\n")[0]:
        text = path_or_text
    else:
        with open(path_or_text, "rb") as f:
            text = f.read().decode()
    if tomllib is not None:
        return tomllib.loads(text)
    return _parse_toml_subset(text)


async def quiet_database(cluster, db, timeout: float = 60.0) -> None:
    """Wait for the cluster to settle (reference QuietDatabase.actor.cpp):
    recovery complete and a probe transaction commits."""
    from ..core.scheduler import now
    deadline = now() + timeout
    while now() < deadline:
        cc = cluster.current_cc()
        if cc is not None and cc.db_info.recovery_state in (
                "accepting_commits", "fully_recovered"):
            try:
                t = db.create_transaction()
                t.set(b"\x02quiet_probe", b"1")
                await t.commit()
                return
            except FdbError:
                pass
        await delay(1.0)
    raise FdbError(1004, "timed_out", "quiet_database timed out")


async def run_test(cluster, spec: Dict[str, Any],
                   db=None) -> Dict[str, Dict[str, float]]:
    """Run one [[test]] entry; returns {workload: metrics}.  Raises
    AssertionError if any workload's check fails."""
    db = db or cluster.database()
    all_metrics: Dict[str, Dict[str, float]] = {}
    for test in spec.get("test", []):
        title = test.get("testTitle", "unnamed")
        TraceEvent("TestStart").detail("Title", title).log()
        instances: List[TestWorkload] = []
        for wconf in test.get("workload", []):
            name = wconf["testName"]
            cls = workload_registry.get(name)
            if cls is None:
                raise KeyError(f"unknown workload {name!r} "
                               f"(registered: {sorted(workload_registry)})")
            instances.append(cls(cluster, db, dict(wconf)))

        # Phase 1: setup, sequentially (reference runs setup before start).
        for w in instances:
            await w.setup()
        # Phase 2: start — ALL workloads concurrently (chaos + load mix).
        await wait_all([spawn(w.start(), f"workload.{w.name}.start")
                        for w in instances])
        # Phase 3: quiescence.
        await quiet_database(cluster, db)
        # Phase 4: check.
        for w in instances:
            ok = await w.check()
            TraceEvent("TestCheck",
                       Severity.Info if ok else Severity.Error).detail(
                "Workload", w.name).detail("Ok", ok).log()
            assert ok, f"workload {w.name} check FAILED in test {title!r}"
            all_metrics[w.name] = w.get_metrics()
        TraceEvent("TestComplete").detail("Title", title).log()
    return all_metrics
