"""Built-in workloads: invariant checkers, load generators, chaos injectors.

Reference models:
- Cycle         (fdbserver/workloads/Cycle.actor.cpp): a ring of keys;
  transactions swap pointers; the ring must remain a single cycle under
  any interleaving/chaos — THE serializability canary.
- ReadWrite     (fdbserver/workloads/ReadWrite.actor.cpp): configurable
  read/write load, reports ops/s.
- Attrition     (fdbserver/workloads/MachineAttrition.actor.cpp): kills
  random processes on an interval.
- RandomClogging (fdbserver/workloads/RandomClogging.actor.cpp): clogs
  random network pairs.
- ConflictRange (fdbserver/workloads/ConflictRange.actor.cpp, simplified):
  randomized cross-check of conflict behavior against an in-memory model.
"""

from __future__ import annotations

import random
from typing import Dict, List

from ..core.error import FdbError
from ..core.scheduler import delay, now, spawn
from ..core.futures import wait_all
from .workload import TestWorkload, register_workload


async def dr_poll_until(predicate, timeout_s: float, what: str,
                        required: bool = True):
    """Poll `predicate` at the shared DR pacing (DR_POLL_INTERVAL_S
    doubling to DR_POLL_MAX_INTERVAL_S) until it returns truthy; that
    value is returned.  Past `timeout_s`: AssertionError(`what`), or
    None when not `required` (best-effort waits like failback).  The
    one shape behind every region-plane / drain / failover wait in the
    DR workloads, so their timeout+backoff semantics cannot drift."""
    from ..core.knobs import server_knobs
    from ..core.scheduler import PollBackoff
    knobs = server_knobs()
    pb = PollBackoff(knobs.DR_POLL_INTERVAL_S,
                     knobs.DR_POLL_MAX_INTERVAL_S)
    deadline = now() + timeout_s
    while True:
        value = predicate()
        if value:
            return value
        if now() >= deadline:
            if required:
                raise AssertionError(what)
            return None
        await delay(pb.next())


def remote_plane_up(cluster):
    """dr_poll_until predicate: the current generation's async remote
    plane is recruited — returns its ServerDBInfo, else None."""
    cc = cluster.current_cc()
    info = cc.db_info if cc is not None else None
    if info is not None and getattr(info, "remote_tlogs", None) \
            and getattr(info, "remote_storage", None):
        return info
    return None


async def commit_marker(db, key: bytes, timeout_s: float, what: str):
    """Commit `key = b"1"` with retries, failing LOUDLY past the
    deadline (a dead commit pipeline must not masquerade as a later
    drain/failover timeout).  Returns the acked commit version."""
    t = db.create_transaction()
    deadline = now() + timeout_s
    while True:
        if now() >= deadline:
            raise AssertionError(what)
        try:
            t.set(key, b"1")
            return await t.commit()
        except FdbError as e:
            await t.on_error(e)


@register_workload
class CycleWorkload(TestWorkload):
    name = "Cycle"

    async def setup(self) -> None:
        n = int(self.config.get("nodeCount", 16))
        prefix = self.config.get("prefix", "cycle/").encode()

        async def populate(t):
            for i in range(n):
                t.set(prefix + b"%06d" % i, prefix + b"%06d" % ((i + 1) % n))
        await self.run_transaction(populate)

    async def start(self) -> None:
        n = int(self.config.get("nodeCount", 16))
        actors = int(self.config.get("actorCount", 4))
        duration = float(self.config.get("testDuration", 10.0))
        prefix = self.config.get("prefix", "cycle/").encode()
        # Progress floor: keep swapping past the deadline until at least
        # this many swaps landed (0 = pure duration semantics, the sim
        # default).  Real-cluster runs measure `duration` in WALL time,
        # and on a loaded machine every commit of the window can exceed
        # it — asserting swaps>0 off a pure time window is a flake
        # (tier-1 deflake, ISSUE 2 satellite).  A hard cap keeps a truly
        # dead cluster from hanging the workload forever.
        min_swaps = int(self.config.get("minSwaps", 0))
        hard_deadline = now() + max(duration * 10, duration + 60.0)
        rng = random.Random(int(self.config.get("seed", 1)))
        deadline = now() + duration
        swaps = [0]

        async def swapper(seed: int) -> None:
            r = random.Random(seed)
            while now() < deadline or (swaps[0] < min_swaps and
                                       now() < hard_deadline):
                async def swap(t):
                    a = prefix + b"%06d" % r.randrange(n)
                    b = await t.get(a)
                    cv = await t.get(b)
                    d = await t.get(cv)
                    t.set(a, cv)
                    t.set(b, d)
                    t.set(cv, b)
                await self.run_transaction(swap)
                swaps[0] += 1
        await wait_all([spawn(swapper(rng.randrange(1 << 30)))
                        for _ in range(actors)])
        self.metrics["swaps"] = swaps[0]

    async def check(self) -> bool:
        n = int(self.config.get("nodeCount", 16))
        prefix = self.config.get("prefix", "cycle/").encode()

        async def walk(t):
            seen, k = set(), prefix + b"%06d" % 0
            for _ in range(n):
                if k in seen:
                    return False
                seen.add(k)
                k = await t.get(k)
                if k is None:
                    return False
            return k == prefix + b"%06d" % 0 and len(seen) == n
        return await self.run_transaction(walk)


@register_workload
class ReadWriteWorkload(TestWorkload):
    name = "ReadWrite"

    async def setup(self) -> None:
        n = int(self.config.get("nodeCount", 100))

        async def populate(t):
            for i in range(n):
                t.set(b"rw/%08d" % i, b"v%08d" % i)
        await self.run_transaction(populate)

    async def start(self) -> None:
        n = int(self.config.get("nodeCount", 100))
        actors = int(self.config.get("actorCount", 4))
        reads = int(self.config.get("readsPerTransaction", 4))
        writes = int(self.config.get("writesPerTransaction", 2))
        duration = float(self.config.get("testDuration", 10.0))
        rng = random.Random(int(self.config.get("seed", 2)))
        deadline = now() + duration
        ops = [0]

        async def worker(seed: int) -> None:
            r = random.Random(seed)
            while now() < deadline:
                async def txn_fn(t):
                    for _ in range(reads):
                        await t.get(b"rw/%08d" % r.randrange(n))
                    for _ in range(writes):
                        t.set(b"rw/%08d" % r.randrange(n),
                              b"u%010d" % r.randrange(1 << 30))
                await self.run_transaction(txn_fn)
                ops[0] += reads + writes
        t0 = now()
        await wait_all([spawn(worker(rng.randrange(1 << 30)))
                        for _ in range(actors)])
        elapsed = max(now() - t0, 1e-9)
        self.metrics["operations"] = ops[0]
        self.metrics["ops_per_sec"] = ops[0] / elapsed

    async def check(self) -> bool:
        async def count(t):
            data = await t.get_range(b"rw/", b"rw0", limit=100000)
            return len(data)
        n = int(self.config.get("nodeCount", 100))
        return await self.run_transaction(count) == n


@register_workload
class AttritionWorkload(TestWorkload):
    """Kills random stateless-worker processes (reference MachineAttrition;
    storage-class workers are spared until data distribution can re-
    replicate lost shards)."""

    name = "Attrition"

    async def start(self) -> None:
        interval = float(self.config.get("testDuration", 10.0)) / max(
            int(self.config.get("machinesToKill", 2)), 1)
        rng = random.Random(int(self.config.get("seed", 3)))
        kills = 0
        for _ in range(int(self.config.get("machinesToKill", 2))):
            await delay(interval * (0.5 + rng.random()))
            victims = [p for _p, w, _cc, _lv in self.cluster.workers
                       if (p := _p).alive and w.process_class == "stateless"]
            # Keep at least two stateless workers alive so recovery can
            # always place a master + its transaction system.
            if len(victims) <= 2:
                continue
            victim = victims[rng.randrange(len(victims))]
            self.cluster.sim.kill_process(victim)
            kills += 1
        self.metrics["kills"] = kills


@register_workload
class RandomCloggingWorkload(TestWorkload):
    """Clogs random process pairs (reference RandomClogging)."""

    name = "RandomClogging"

    async def start(self) -> None:
        duration = float(self.config.get("testDuration", 10.0))
        rng = random.Random(int(self.config.get("seed", 4)))
        deadline = now() + duration
        clogs = 0
        while now() < deadline:
            await delay(duration / 10 * (0.5 + rng.random()))
            procs = self.cluster.sim.alive_processes()
            if len(procs) >= 2:
                a, b = rng.sample(procs, 2)
                self.cluster.sim.clog_pair(a, b,
                                           seconds=rng.random() * 2.0)
                clogs += 1
        self.metrics["clogs"] = clogs


@register_workload
class ChaosNemesisWorkload(TestWorkload):
    """Continuous deterministic nemesis (reference sim2 swizzle clogging +
    MachineAttrition + network partitions, run as one composable
    workload): three concurrent fault loops driven ENTIRELY by the
    deterministic RNG, so a failing (spec, seed) replays its exact fault
    schedule.

    - swizzle: clog a random subset of worker interfaces one at a time,
      then unclog them in REVERSE order (the reference's swizzle —
      staggered recovery stresses different quorum subsets than a single
      clog/unclog would);
    - attrition: rolling reboot / machine power-fail / kill+restart, one
      victim at a time, guarded by the replication policy
      (server/policy.py) so a fault never leaves the survivors unable to
      satisfy log or storage replication ("never break quorum");
    - partition: random worker pair partitions that always heal.

    Disaster-recovery battery (ISSUE 10), each off by default:

    - regionFailover: provision a remote dc (setup), then hard-kill the
      ENTIRE primary dc mid-traffic — UNDRAINED, no convergence wait —
      verify recovery adopts the remote plane at the surfaced
      failover_version with the acked-commit survival invariant intact,
      then re-provision the dead dc (wiped machines) and optionally
      fail the async plane back onto it;
    - coordinatorAttrition: reboot/hard-restart coordination servers one
      at a time under a quorum guard (all peers up), exercising
      well-known-token CoordinationClientInterface re-pointing;
    - diskFaults: inject a FATAL disk fault (io_error on fsync) into one
      storage worker's machine, wait for the process-death detection
      path, then clear the fault and RESTART the worker — the topology
      heals instead of permanently shrinking.

    start() ends by healing the network and restarting every downed
    worker, so quiescence and the invariant workloads' checks (Cycle,
    ConsistencyCheck) run against a whole cluster."""

    name = "ChaosNemesis"

    async def setup(self) -> None:
        if not self.config.get("regionFailover", False):
            return
        # Provision the remote dc the failover will adopt (same shape as
        # KillRegionWorkload.setup): replica hosts, a stateless worker
        # for the async plane's routers/TLogs, and a CC candidate so the
        # dc can elect a controller once the primary dies.
        c = self.cluster
        self._remote_dc = str(self.config.get("remoteDc", "dcR"))
        for i in range(int(self.config.get("remoteStorage", 2))):
            c.add_worker("storage", name=f"nrf{i}", dcid=self._remote_dc)
        c.add_worker("stateless", name="nrfstate", dcid=self._remote_dc)
        c.add_worker("stateless", name="nrfcc", dcid=self._remote_dc,
                     campaign=True)
        from ..client.management import change_configuration
        await change_configuration(self.db, usable_regions=2,
                                   remote_dc=self._remote_dc)

    async def start(self) -> None:
        duration = float(self.config.get("testDuration", 10.0))
        self._deadline = now() + duration
        loops = []
        if self.config.get("swizzle", True):
            loops.append(spawn(self._swizzle_loop(), "nemesis.swizzle"))
        if self.config.get("attrition", True):
            loops.append(spawn(self._attrition_loop(), "nemesis.attrition"))
        if self.config.get("partitions", True):
            loops.append(spawn(self._partition_loop(), "nemesis.partition"))
        if self.config.get("grayClog", False):
            loops.append(spawn(self._gray_clog_loop(), "nemesis.grayClog"))
        if self.config.get("resolverAttrition", False):
            loops.append(spawn(self._resolver_attrition_loop(),
                               "nemesis.resolverAttrition"))
        if self.config.get("coordinatorAttrition", False):
            loops.append(spawn(self._coordinator_attrition_loop(),
                               "nemesis.coordinatorAttrition"))
        if self.config.get("diskFaults", False):
            loops.append(spawn(self._disk_fault_loop(),
                               "nemesis.diskFaults"))
        if self.config.get("regionFailover", False):
            loops.append(spawn(self._region_failover(),
                               "nemesis.regionFailover"))
        await wait_all(loops)
        # Leave the cluster whole: heal every network fault and bring
        # back every downed worker before quiescence.
        self.cluster.sim.heal()
        for i, entry in enumerate(self.cluster.workers):
            if not entry[0].alive:
                self.cluster.restart_worker(i)

    # -- fault loops ---------------------------------------------------------
    def _alive_workers(self):
        return [e[0] for e in self.cluster.workers if e[0].alive]

    async def _swizzle_loop(self) -> None:
        from ..core.coverage import test_coverage
        from ..core.rng import deterministic_random
        rng = deterministic_random()
        sim = self.cluster.sim
        swizzles = 0
        while now() < self._deadline:
            await delay(0.5 + rng.random01() * 2.0)
            procs = self._alive_workers()
            if len(procs) < 2:
                continue
            k = rng.random_int(1, max(2, len(procs) // 2 + 1))
            rng.shuffle(procs)
            victims = procs[:k]
            clogged = []
            for p in victims:
                sim.clog_process(p, seconds=30.0)   # manually unclogged
                clogged.append(p)
                await delay(rng.random01() * 0.3)
            for p in reversed(clogged):
                await delay(rng.random01() * 0.3)
                sim.unclog_process(p)
            swizzles += 1
            test_coverage("ChaosNemesisSwizzle")
        self.metrics["swizzles"] = swizzles

    async def _partition_loop(self) -> None:
        from ..core.coverage import test_coverage
        from ..core.rng import deterministic_random
        rng = deterministic_random()
        sim = self.cluster.sim
        cycles = 0
        while now() < self._deadline:
            await delay(1.0 + rng.random01() * 2.0)
            procs = self._alive_workers()
            if len(procs) < 2:
                continue
            i = rng.random_int(0, len(procs))
            j = rng.random_int(0, len(procs) - 1)
            if j >= i:
                j += 1
            a, b = procs[i], procs[j]
            sim.partition(a, b)
            test_coverage("ChaosNemesisPartition")
            await delay(0.2 + rng.random01() * 1.5)
            sim.heal_pair(a, b)
            cycles += 1
        self.metrics["partitions"] = cycles

    async def _gray_clog_loop(self) -> None:
        """Gray failure (ISSUE 18): latency-inflate one LIVE link between
        two random workers — no drop, no disconnect, so failure
        monitoring never fires and only the peer-health plane
        (server/health.py ping RTT verdicts) can see it.  Inflation is
        held past the verdict hysteresis window, then healed."""
        from ..core.coverage import test_coverage
        from ..core.knobs import server_knobs
        from ..core.rng import deterministic_random
        rng = deterministic_random()
        sim = self.cluster.sim
        knobs = server_knobs()
        cycles = 0
        while now() < self._deadline:
            await delay(1.0 + rng.random01() * 2.0)
            procs = self._alive_workers()
            if len(procs) < 2:
                continue
            i = rng.random_int(0, len(procs))
            j = rng.random_int(0, len(procs) - 1)
            if j >= i:
                j += 1
            a, b = procs[i], procs[j]
            # Inflation comfortably past the degraded-latency bar; hold
            # long enough for hysteresis to convict, then heal.
            extra = knobs.PEER_DEGRADED_LATENCY_S * (
                4.0 + rng.random01() * 4.0)
            hold = knobs.PEER_PING_INTERVAL_S * (
                knobs.PEER_VERDICT_HYSTERESIS + 2 + rng.random_int(0, 3))
            sim.gray_clog_pair(a, b, extra, hold + 60.0)
            test_coverage("ChaosNemesisGrayClog")
            await delay(hold)
            sim.ungray_pair(a, b)
            cycles += 1
        self.metrics["gray_clogs"] = cycles

    def _safe_to_fail(self, victim) -> bool:
        """Would the survivors still satisfy replication + leave a viable
        control plane?  Consults the replication policy engine
        (server/policy.py) rather than ad-hoc counts."""
        from ..server.policy import policy_from_config
        c = self.cluster
        alive = [e[0] for e in c.workers
                 if e[0].alive and e[0] is not victim]
        stateless = [p for p in alive if p.process_class == "stateless"]
        storage = [p for p in alive if p.process_class == "storage"]
        # Master + transaction system need somewhere to live.
        if len(stateless) < 2:
            return False

        def cands(procs):
            return [(p.name, {"dcid": p.locality.dcid,
                              "zoneid": p.locality.zoneid,
                              "machineid": p.locality.machineid})
                    for p in procs]
        log_pol = policy_from_config(
            getattr(c.config, "log_replication", 1))
        if log_pol.select(cands(stateless)) is None:
            return False
        st_pol = policy_from_config(
            getattr(c.config, "storage_replication", 1))
        if st_pol.select(cands(storage)) is None:
            return False
        return True

    async def _attrition_loop(self) -> None:
        from ..core.coverage import test_coverage
        from ..core.rng import deterministic_random
        rng = deterministic_random()
        sim = self.cluster.sim
        restart_delay = float(self.config.get("restartDelay", 1.5))
        reboots = power_fails = kills = 0
        while now() < self._deadline:
            await delay(1.0 + rng.random01() * 2.5)
            entries = [(i, e[0]) for i, e in enumerate(self.cluster.workers)
                       if e[0].alive]
            if not entries:
                continue
            idx, victim = entries[rng.random_int(0, len(entries))]
            if not self._safe_to_fail(victim):
                continue
            test_coverage("ChaosNemesisAttrition")
            roll = rng.random01()
            if roll < 0.5:
                sim.reboot_process(victim)      # roles respawn via hook
                reboots += 1
            elif roll < 0.8:
                sim.power_fail_machine(victim.locality.machineid)
                power_fails += 1
                await delay(restart_delay)
                self.cluster.restart_worker(idx)
            else:
                sim.kill_process(victim)
                kills += 1
                await delay(restart_delay)
                self.cluster.restart_worker(idx)
            await delay(restart_delay)          # one victim at a time
        self.metrics["reboots"] = reboots
        self.metrics["power_fails"] = power_fails
        self.metrics["kills"] = kills

    async def _resolver_attrition_loop(self) -> None:
        """Targeted resolution-plane attrition (ISSUE 7): kill the worker
        hosting a RESOLVER of the current generation — the epoch ends,
        recovery recruits a fresh plane (persisted boundaries adopted,
        empty conflict windows behind the recovery_version MVCC floor) —
        then restart the worker.  The Cycle + ConsistencyCheck workloads
        running alongside prove verdict continuity across the plane
        change; generic attrition only hits resolvers by luck."""
        from ..core.coverage import test_coverage
        from ..core.rng import deterministic_random
        rng = deterministic_random()
        sim = self.cluster.sim
        restart_delay = float(self.config.get("restartDelay", 1.5))
        kills = 0
        while now() < self._deadline:
            await delay(2.0 + rng.random01() * 3.0)
            cc = self.cluster.current_cc()
            if cc is None or cc.db_info.recovery_state not in (
                    "accepting_commits", "fully_recovered"):
                continue
            resolvers = list(cc.db_info.resolvers)
            if not resolvers:
                continue
            iface = resolvers[rng.random_int(0, len(resolvers))]
            victim = self.cluster.process_of(iface)
            if victim is None or not victim.alive:
                continue
            idx = next((i for i, e in enumerate(self.cluster.workers)
                        if e[0] is victim), None)
            if idx is None or not self._safe_to_fail(victim):
                continue
            test_coverage("ChaosNemesisResolverKill")
            sim.kill_process(victim)
            kills += 1
            await delay(restart_delay)
            self.cluster.restart_worker(idx)
            await delay(restart_delay)      # one victim at a time
        self.metrics["resolver_kills"] = kills

    async def _coordinator_attrition_loop(self) -> None:
        """Rolling coordination-server restarts (the PR-4 gap named in
        ROADMAP): one coordinator at a time — clean reboot or hard
        kill+replace on the same address — under a quorum guard (every
        peer must be up before a new victim is taken).  The durable
        generation registers recover from the machine's files, leader
        election re-runs through the survivors, and every client's
        CoordinationClientInterface re-points via the well-known-token
        endpoints without a stuck GRV pipeline."""
        from ..core.coverage import test_coverage
        from ..core.rng import deterministic_random
        rng = deterministic_random()
        c = self.cluster
        restart_delay = float(self.config.get("restartDelay", 1.5))
        restarts = 0
        while now() < self._deadline:
            await delay(2.0 + rng.random01() * 3.0)
            coords = getattr(c, "coordinators", None)
            if not coords:
                return              # static harness: nothing to restart
            # Quorum guard: restart only when ALL coordinators SERVE, so
            # at most one is ever down and the majority always answers.
            # Serving means the register-recovery startup finished
            # (server._ready), not merely process.alive — a hard restart
            # flips alive back on synchronously while the replacement is
            # still recovering its durable registers.
            if not all(p.alive and s._ready.is_set() for p, s in coords):
                continue
            i = rng.random_int(0, len(coords))
            c.restart_coordinator(i, hard=rng.random01() < 0.5)
            test_coverage("ChaosCoordinatorRestart")
            restarts += 1
            await delay(restart_delay)
        self.metrics["coordinator_restarts"] = restarts

    async def _disk_fault_loop(self) -> None:
        """Restart-capable fatal disk faults (the PR-4 ensemble gap):
        arm an io_error-on-fsync profile on one storage worker's
        machine, wait for the detection path to kill the process
        (StorageIoErrorDeath / TLogIoErrorDeath), then DISARM the fault
        and restart the worker on the same machine — the harness heals
        instead of permanently shrinking, so a long chaos run keeps its
        full topology."""
        from ..core.coverage import test_coverage
        from ..core.rng import deterministic_random
        from ..server.sim_fs import DiskFaultProfile
        rng = deterministic_random()
        sim = self.cluster.sim
        restart_delay = float(self.config.get("restartDelay", 1.5))
        faults = 0
        while now() < self._deadline:
            await delay(1.0 + rng.random01() * 2.0)
            entries = [(i, e[0]) for i, e in enumerate(self.cluster.workers)
                       if e[0].alive and e[0].process_class == "storage"]
            if not entries:
                continue
            idx, victim = entries[rng.random_int(0, len(entries))]
            if not self._safe_to_fail(victim):
                continue
            fs = sim.fs_for(victim)
            fs.set_fault_profile("", DiskFaultProfile(io_error_sync_p=1.0))
            # Bounded wait for the io_error death; a machine that never
            # fsyncs inside the window just gets the fault disarmed.
            for _ in range(40):
                if not victim.alive:
                    break
                await delay(0.25)
            fs.clear_fault_profiles()
            if not victim.alive:
                faults += 1
                test_coverage("ChaosFatalDiskRestart")
                await delay(restart_delay)
                self.cluster.restart_worker(idx)
            await delay(restart_delay)      # one victim at a time
        self.metrics["disk_fault_restarts"] = faults

    async def _region_failover(self) -> None:
        """UNDRAINED region failover (the tentpole scenario): once the
        async plane is up, commit a marker mid-traffic and hard-kill the
        whole primary dc with NO convergence wait.  Recovery must adopt
        the remote plane at the surfaced failover_version; the marker —
        an acked commit — must survive whenever its commit version is at
        or below it (the acked-commit survival invariant; above it, the
        surfaced lost tail makes the loss explicit).  Afterwards the
        dead dc is re-provisioned (machines WIPED: replacement boxes,
        not resurrected pre-failover disks) and, with failback enabled,
        the async plane is re-established pointing at it.

        Pair with Cycle: its ring invariant across the lost-tail
        truncation proves the adopted state is a version-consistent
        snapshot, not a torn mix of tags."""
        from ..core.coverage import test_coverage
        from ..core.error import FdbError
        from ..server.log_router import is_remote_tag
        c = self.cluster
        info = await dr_poll_until(
            lambda: remote_plane_up(c),
            float(self.config.get("planeTimeout", 120)),
            "regionFailover: remote plane never recruited")
        # Optionally FORCE a real undrained window (reference KillRegion
        # with min_delay_before_kill): freeze the async plane's pull
        # path, keep committing on the primary, and only then kill —
        # everything acked during the window is tail the failover MUST
        # lose, so the loss path gets exercised instead of draining by
        # luck on fast seeds.
        lag = float(self.config.get("replicationLagBeforeKill", 0.0))
        clogged = []
        if lag > 0:
            for iface in (list(getattr(info, "log_routers", []) or []) +
                          list(getattr(info, "remote_tlogs", []) or [])):
                p = c.process_of(iface)
                if p is not None and p.alive:
                    c.sim.clog_process(p, seconds=600.0)
                    clogged.append(p)
            await delay(lag)
        # An ACKED commit to hold against the surfaced failover_version.
        marker_v = await commit_marker(
            self.db, b"nemesis/failover_marker",
            float(self.config.get("markerTimeout", 60)),
            "regionFailover: marker commit never landed")
        # UNDRAINED: kill the primary dc NOW — in-flight commits above
        # what the routers shipped become the lost tail.  Deliberately a
        # PRE-KILL snapshot: the same dc set is what failback later
        # re-points the async plane at.
        primary_dcs = {p.locality.dcid  # flowlint: state -- pre-kill snapshot reused for failback
                       for p, _w, _cc2, _lv in c.workers
                       if p.alive} - {self._remote_dc}
        killed_idx = [i for i, e in enumerate(c.workers)
                      if e[0].alive and e[0].locality.dcid in primary_dcs]
        for i in killed_idx:
            c.sim.kill_process(c.workers[i][0])
        # The remote plane must be reachable again for recovery to lock
        # it — only the PRIMARY was supposed to die.
        for p in clogged:
            c.sim.unclog_process(p)
        # Recovery onto the remote plane: serving tags become the twins
        # and the failover record surfaces in db_info.regions.
        def failed_over():
            cc = c.current_cc()
            info2 = cc.db_info if cc is not None else None
            if info2 is not None and info2.recovery_state in (
                    "accepting_commits", "fully_recovered") and \
                    info2.storage_servers and \
                    all(is_remote_tag(tag) for tag in info2.storage_servers):
                return (getattr(info2, "regions", None) or {}).get(
                    "failover")
            return None
        fo = await dr_poll_until(
            failed_over, float(self.config.get("failoverTimeout", 240)),
            "regionFailover: cluster never recovered onto the "
            "remote plane")
        self.metrics["failover_version"] = float(fo["failover_version"])
        self.metrics["lost_tail_versions"] = float(
            fo["lost_tail_versions"])
        self.metrics["marker_version"] = float(marker_v)
        # The survival invariant, checked against the SURFACED version:
        # acked at or below failover_version => readable after adoption;
        # acked ABOVE it => the undrained lost tail (with a forced
        # replication-lag window the marker is GUARANTEED above — the
        # clog started before it committed — and must be gone).
        t = self.db.create_transaction()
        while True:
            try:
                got = await t.get(b"nemesis/failover_marker")
                break
            except FdbError as e:
                await t.on_error(e)
        if marker_v <= fo["failover_version"]:
            assert got == b"1", (
                f"acked marker at {marker_v} <= failover_version "
                f"{fo['failover_version']} was LOST")
            self.metrics["marker_survived"] = 1.0
        else:
            self.metrics["marker_lost"] = 0.0 if got == b"1" else 1.0
            if lag > 0:
                assert got is None, (
                    "marker acked inside the forced replication-lag "
                    "window survived an undrained failover — the clog "
                    "did not isolate the async plane")
        test_coverage("ChaosRegionFailover")
        self.metrics["region_failovers"] = 1.0
        # Heal: re-provision the dead dc on WIPED machines (replacement
        # hardware — pre-failover engines must not come back as
        # same-tag impostors), then optionally re-point the async plane
        # at it (failback) through a committed configuration change.
        for i in killed_idx:
            c.sim.wipe_machine(c.workers[i][0].locality.machineid)
            c.restart_worker(i)
        if self.config.get("failback", True) and primary_dcs:
            from ..client.management import change_configuration
            new_remote = sorted(primary_dcs)[0]
            await change_configuration(self.db, remote_dc=new_remote)

            def failback_plane_up():
                cc = c.current_cc()
                info2 = cc.db_info if cc is not None else None
                return info2 is not None and \
                    bool(getattr(info2, "remote_tlogs", None))
            if await dr_poll_until(
                    failback_plane_up,
                    float(self.config.get("planeTimeout", 120)),
                    "failback plane", required=False):
                self.metrics["failback_plane"] = 1.0

    async def check(self) -> bool:
        # The nemesis's own invariant: it put the cluster back together.
        return all(e[0].alive for e in self.cluster.workers)


@register_workload
class NondeterminismCanaryWorkload(TestWorkload):
    """DELIBERATELY nondeterministic workload (negative control for the
    unseed verifier, ISSUE 4): reads the WALL CLOCK and lets it perturb
    both the deterministic RNG's draw count and the transaction schedule.
    run_test_twice on any spec containing this workload MUST fail its
    unseed check, and the NondeterminismAudit must flag the time.time_ns
    call — a verifier that rubber-stamps this workload is broken.  Never
    include it in a real correctness spec."""

    name = "NondeterminismCanary"

    async def start(self) -> None:
        import time as _time
        from ..core.rng import deterministic_random
        # Two independent wall-clock residues: the chance of BOTH
        # colliding across two runs is ~1e-6, so the negative test is
        # solid without being flaky.
        # The wall-clock read IS this workload's entire purpose (negative
        # control): the verifier must catch it, flowlint must not.
        t = _time.time_ns()  # flowlint: disable=FTL001
        n1 = t % 997
        n2 = (t // 997) % 991
        rng = deterministic_random()
        for _ in range(n1 + n2):
            rng.random01()          # draw count differs => unseed differs
        writes = t % 5 + 1          # schedule differs => digest differs

        async def put(txn):
            for i in range(writes):
                txn.set(b"canary/%02d" % i, b"x")
        await self.run_transaction(put)
        self.metrics["writes"] = writes


@register_workload
class HashOrderCanaryWorkload(TestWorkload):
    """DELIBERATELY PYTHONHASHSEED-sensitive workload (negative control
    for CROSS-process unseed reproduction, ISSUE 5): iterates a str SET
    and folds the iteration order into both the deterministic RNG's draw
    count and the transaction schedule.  Two runs in processes sharing a
    pinned PYTHONHASHSEED replay bit-identically; different hash seeds
    almost surely (collision ~1e-8: two independent ~1e4 residues) yield
    different unseeds — the divergence scripts/run_chaos.py's pinned
    repro commands exist to rule out.  Never include it in a real
    correctness spec."""

    name = "HashOrderCanary"

    async def start(self) -> None:
        from ..core.rng import deterministic_random
        from ..core.scheduler import delay as sim_delay
        n = int(self.config.get("nodeCount", 32))
        sig = 0
        # The set iteration below is this workload's entire purpose
        # (order-sensitivity canary): flowlint must not flag it, the
        # cross-process verifier must catch it when hash seeds differ.
        for name in set("canary-%03d" % i for i in range(n)):  # flowlint: disable=FTL005
            # Polynomial fold: permutation-sensitive, unlike sum/xor.
            sig = (sig * 1000003 + int(name[-3:])) & 0xFFFFFFFF
        rng = deterministic_random()
        for _ in range(sig % 9973 + 1):
            rng.random01()                  # draw count => unseed differs
        await sim_delay((sig // 9973 % 9973) * 1e-6)   # schedule => digest

        async def put(txn):
            txn.set(b"hash_canary", b"%08x" % sig)
        await self.run_transaction(put)
        self.metrics["order_sig"] = float(sig)


@register_workload
class ConflictRangeWorkload(TestWorkload):
    """Randomized serializability cross-check vs. an in-memory model
    (reference ConflictRange.actor.cpp:31, simplified): one actor applies
    random sets/clears through transactions AND to a local dict; after
    quiescence the database must equal the model exactly."""

    name = "ConflictRange"

    async def start(self) -> None:
        duration = float(self.config.get("testDuration", 5.0))
        rng = random.Random(int(self.config.get("seed", 5)))
        n = int(self.config.get("nodeCount", 50))
        self.model: Dict[bytes, bytes] = {}
        deadline = now() + duration
        while now() < deadline:
            op = rng.random()
            if op < 0.6:
                k = b"cr/%04d" % rng.randrange(n)
                v = b"%08d" % rng.randrange(1 << 20)

                async def do_set(t, k=k, v=v):
                    t.set(k, v)
                await self.run_transaction(do_set)
                self.model[k] = v
            else:
                lo = rng.randrange(n)
                hi = min(n, lo + rng.randrange(1, 8))
                b, e = b"cr/%04d" % lo, b"cr/%04d" % hi

                async def do_clear(t, b=b, e=e):
                    t.clear(b, e)
                await self.run_transaction(do_clear)
                for k in [k for k in self.model if b <= k < e]:
                    del self.model[k]

    async def check(self) -> bool:
        async def read_all(t):
            return dict(await t.get_range(b"cr/", b"cr0", limit=100000))
        actual = await self.run_transaction(read_all)
        return actual == self.model


@register_workload
class ConsistencyCheckWorkload(TestWorkload):
    """Replica audit (reference fdbserver/workloads/ConsistencyCheck
    .actor.cpp:31, core check): for every shard, read the full range at one
    read version from EVERY team replica and require byte-identical
    results.  Retries wrong_shard_server/future_version (a replica may
    still be fetching after a move)."""

    name = "ConsistencyCheck"

    async def check(self) -> bool:
        from ..rpc.endpoint import RequestStream
        from ..server.interfaces import GetKeyValuesRequest
        shards_audited = 0
        cursor = b""
        while cursor < b"\xff":
            b, e, ssis = await self.db.get_shard_location(cursor)
            if not ssis:
                cursor = e
                continue
            while True:
                t = self.db.create_transaction()
                try:
                    version = await t._ensure_read_version()
                    replies = []
                    for ssi in ssis:
                        replies.append(await RequestStream.at(
                            ssi.get_key_values.endpoint).get_reply(
                            GetKeyValuesRequest(
                                begin=max(b, cursor), end=min(e, b"\xff"),
                                version=version, limit=1 << 30,
                                limit_bytes=1 << 40)))
                    first = replies[0].data
                    for i, r in enumerate(replies[1:], 1):
                        if r.data != first:
                            raise AssertionError(
                                f"replica divergence in [{b!r},{e!r}): "
                                f"replica 0 has {len(first)} kvs, "
                                f"replica {i} has {len(r.data)}")
                    shards_audited += 1
                    break
                except FdbError as ex:
                    if ex.name not in ("wrong_shard_server", "future_version",
                                       "broken_promise", "transaction_too_old",
                                       "request_maybe_delivered"):
                        raise
                    await delay(0.1)
                    self.db.invalidate_cache(max(b, cursor))
                    b, e, ssis = await self.db.get_shard_location(cursor)
            cursor = e
        self.metrics["shards_audited"] = shards_audited
        return True


@register_workload
class ApiCorrectnessWorkload(TestWorkload):
    """Randomized API exerciser vs an in-memory model (reference
    ApiCorrectness.actor.cpp, simplified): sets, clears, clear-ranges,
    atomic adds and range reads through real transactions, mirrored into a
    dict; RYW is spot-checked inside each transaction and the database
    must equal the model at the end.

    Every transaction also writes a unique txn-id key, so a
    commit_unknown_result is resolved by re-reading it — the reference
    pattern for idempotent retries under chaos."""

    name = "ApiCorrectness"

    TXID_KEY = b"api\x00txid"

    async def start(self) -> None:
        from ..txn.types import MutationType
        duration = float(self.config.get("testDuration", 5.0))
        rng = random.Random(int(self.config.get("seed", 6)))
        n = int(self.config.get("nodeCount", 40))
        self.model: Dict[bytes, bytes] = {}
        deadline = now() + duration
        ops = 0
        while now() < deadline:
            ops += 1
            txid = b"%020d" % ops
            result: Dict[str, Dict[bytes, bytes]] = {}
            t = self.db.create_transaction()
            while True:
                # Staged state is rebuilt PER ATTEMPT: a failed attempt's
                # ops must not leak into the model.
                staged = dict(self.model)
                try:
                    t.set(self.TXID_KEY, txid)
                    for _ in range(rng.randrange(1, 6)):
                        r = rng.random()
                        k = b"api/%04d" % rng.randrange(n)
                        if r < 0.4:
                            v = b"%010d" % rng.randrange(1 << 30)
                            t.set(k, v)
                            staged[k] = v
                        elif r < 0.55:
                            t.clear(k)
                            staged.pop(k, None)
                        elif r < 0.7:
                            lo = rng.randrange(n)
                            hi = min(n, lo + rng.randrange(1, 6))
                            b, e = b"api/%04d" % lo, b"api/%04d" % hi
                            t.clear(b, e)
                            for kk in [kk for kk in staged if b <= kk < e]:
                                del staged[kk]
                        elif r < 0.85:
                            t.atomic_op(MutationType.AddValue, k,
                                        (1).to_bytes(8, "little"))
                            old = int.from_bytes(staged.get(k, b""),
                                                 "little")
                            staged[k] = ((old + 1) & ((1 << 64) - 1)
                                         ).to_bytes(8, "little")
                        else:
                            got = await t.get(k)
                            assert got == staged.get(k), \
                                f"RYW mismatch on {k!r}: {got!r}"
                    await t.commit()
                    result["staged"] = staged
                    break
                except FdbError as e:
                    if e.name == "commit_unknown_result":
                        # Resolve the ambiguity via the txn-id marker.
                        check = self.db.create_transaction()
                        while True:
                            try:
                                seen = await check.get(self.TXID_KEY)
                                break
                            except FdbError as e2:
                                await check.on_error(e2)
                        if seen == txid:
                            result["staged"] = staged
                            break
                        t.reset()
                        continue
                    await t.on_error(e)
            self.model = result["staged"]
        self.metrics["transactions"] = ops

    async def check(self) -> bool:
        async def read_all(t):
            return dict(await t.get_range(b"api/", b"api0", limit=100000))
        actual = await self.run_transaction(read_all)
        return actual == self.model


@register_workload
class RollbackWorkload(TestWorkload):
    """Forces epoch changes mid-load by killing the current master's
    process (reference Rollback.actor.cpp forces recoveries; our analog
    exercises the same rollback/epoch paths in storage and resolvers)."""

    name = "Rollback"

    async def start(self) -> None:
        duration = float(self.config.get("testDuration", 8.0))
        n_recoveries = int(self.config.get("recoveries", 2))
        rng = random.Random(int(self.config.get("seed", 7)))
        deadline = now() + duration
        forced = 0
        for _ in range(n_recoveries):
            await delay(duration / (n_recoveries + 1) *
                        (0.7 + 0.6 * rng.random()))
            if now() >= deadline:
                break
            cc = self.cluster.current_cc()
            if cc is None or cc.db_info.master is None:
                continue
            proc = self.cluster.process_of(cc.db_info.master)
            if proc is not None and proc.alive:
                self.cluster.sim.kill_process(proc)
                forced += 1
        self.metrics["recoveries_forced"] = forced


@register_workload
class ChangeConfigWorkload(TestWorkload):
    """Changes the database configuration mid-run and forces a recovery to
    adopt it (reference ChangeConfig.actor.cpp): flips resolver and commit
    proxy counts, then verifies the new epoch recruited the new counts."""

    name = "ChangeConfig"

    async def start(self) -> None:
        await delay(float(self.config.get("delayBefore", 2.0)))
        cfg = self.cluster.config
        self.want_resolvers = 3 - cfg.n_resolvers if cfg.n_resolvers in (1, 2) \
            else 2
        self.want_proxies = 3 - cfg.n_commit_proxies \
            if cfg.n_commit_proxies in (1, 2) else 2
        # A configuration change is a DATABASE TRANSACTION (reference
        # ChangeConfig.actor.cpp -> ManagementAPI changeConfig): commit
        # the \xff/conf/ keys; the proxies nudge the master, the epoch
        # ends, and the next recovery recruits at the new counts.
        from ..client.management import change_configuration
        await change_configuration(self.db,
                                   n_resolvers=self.want_resolvers,
                                   n_commit_proxies=self.want_proxies)
        self.metrics["changed"] = 1

    async def check(self) -> bool:
        from ..core.scheduler import now as _now
        deadline = _now() + 30.0
        while _now() < deadline:
            cc = self.cluster.current_cc()
            if cc is not None and cc.db_info.recovery_state in (
                    "accepting_commits", "fully_recovered"):
                info = cc.db_info
                if (len(info.resolvers) == self.want_resolvers and
                        len(info.commit_proxies) == self.want_proxies):
                    return True
            await delay(0.5)
        return False


@register_workload
class RandomMoveKeysWorkload(TestWorkload):
    """Random live shard relocations through the DataDistributor under
    load (reference RandomMoveKeys.actor.cpp)."""

    name = "RandomMoveKeys"

    def _dd(self):
        cc = self.cluster.current_cc()
        if cc is None or cc.db_info.data_distributor is None:
            return None
        dd = getattr(cc.db_info.data_distributor, "role", None)
        if dd is not None and not getattr(dd, "halted", False):
            return dd
        return None

    async def start(self) -> None:
        duration = float(self.config.get("testDuration", 8.0))
        moves = int(self.config.get("moves", 4))
        rng = random.Random(int(self.config.get("seed", 8)))
        deadline = now() + duration
        done = 0
        for _ in range(moves):
            await delay(duration / (moves + 1) * (0.5 + rng.random()))
            if now() >= deadline:
                break
            dd = self._dd()
            if dd is None or not dd.healthy:
                continue
            shards = [(b, e, t) for b, e, t in dd.map.ranges() if t]
            if not shards:
                continue
            b, e, team = shards[rng.randrange(len(shards))]
            # New team: same size, random healthy members.
            size = min(len(team), len(dd.healthy))
            new_team = rng.sample(sorted(dd.healthy), size)
            try:
                await dd.move_shard(b, e, new_team)
                done += 1
            except FdbError:
                pass
        self.metrics["moves"] = done


@register_workload
class WatchesWorkload(TestWorkload):
    """Watch semantics under load (reference WatchAndWait.actor.cpp):
    one actor watches keys, another mutates them; every watch must fire."""

    name = "Watches"

    async def start(self) -> None:
        n = int(self.config.get("watchCount", 8))
        fired = [0]

        async def waiter(i: int) -> None:
            key = b"watch/%03d" % i

            async def get_watch(t):
                # Under chaos the watch registration can land after the
                # touch: a value already b"touched" counts as fired (the
                # change we were waiting for has been observed).
                if await t.get(key, snapshot=True) == b"touched":
                    return None
                f = await t.watch(key)
                await t.commit()
                return f
            f = await self.run_transaction(get_watch)
            if f is not None:
                await f
            fired[0] += 1

        async def toucher() -> None:
            await delay(0.5)
            for i in range(n):
                async def set_fn(t, i=i):
                    t.set(b"watch/%03d" % i, b"touched")
                await self.run_transaction(set_fn)

        await wait_all([spawn(waiter(i)) for i in range(n)] +
                       [spawn(toucher())])
        self.metrics["watches_fired"] = fired[0]

    async def check(self) -> bool:
        return self.metrics.get("watches_fired", 0) == int(
            self.config.get("watchCount", 8))


@register_workload
class TenantManagementWorkload(TestWorkload):
    """Tenant lifecycle + isolation under chaos (reference
    fdbserver/workloads/TenantManagementWorkload.actor.cpp, simplified):
    actors create/delete tenants and write tenant-keyed data through
    Tenant handles; a local model tracks expected state; check() asserts
    (a) the tenant map equals the model, (b) every live tenant reads back
    ITS OWN marker under its own relative key — two tenants share the
    same relative keys throughout, so any cross-tenant leak or conflict
    shows up immediately, and (c) raw reads confirm the data actually
    lives under the tenant's committed prefix."""

    name = "TenantManagement"

    MARKER = b"marker"          # same relative key in EVERY tenant

    def _names(self):
        n = int(self.config.get("tenantCount", 4))
        return [b"wl-tenant-%02d" % i for i in range(n)]

    async def start(self) -> None:
        from ..tenant import management as tm
        from ..core.error import FdbError
        duration = float(self.config.get("testDuration", 8.0))
        rng = random.Random(int(self.config.get("seed", 11)))
        names = self._names()
        self.model: Dict[bytes, bytes] = {}   # name -> expected marker
        deadline = now() + duration
        ops = 0
        while now() < deadline:
            ops += 1
            name = names[rng.randrange(len(names))]
            r = rng.random()
            if name not in self.model or r < 0.5:
                # Create (idempotent) + write this tenant's marker
                # through its handle.
                entry = await tm.create_tenant(self.db, name)
                tenant = await self.db.open_tenant(name)
                value = b"%s:%08d" % (name, rng.randrange(1 << 26))

                async def put(t, value=value):
                    t.set(self.MARKER, value)
                try:
                    await tenant.run(put)
                except FdbError as e:
                    if e.name != "tenant_not_found":
                        raise
                    continue     # raced a delete; model unchanged
                self.model[name] = value
                assert entry.prefix == tenant.prefix
            elif r < 0.7:
                # Delete: clear the data first (delete requires empty).
                tenant = await self.db.open_tenant(name)

                async def wipe(t):
                    t.clear(b"", b"\xff")
                try:
                    await tenant.run(wipe)
                    await tm.delete_tenant(self.db, name)
                except FdbError as e:
                    if e.name not in ("tenant_not_found",
                                      "tenant_not_empty"):
                        raise
                    continue
                self.model.pop(name, None)
            else:
                # Cross-tenant isolation probe mid-chaos: read one LIVE
                # tenant's marker through its handle; it must be its own.
                live = list(self.model)
                if not live:
                    continue
                probe = live[rng.randrange(len(live))]
                tenant = await self.db.open_tenant(probe)

                async def read(t):
                    return await t.get(self.MARKER)
                try:
                    got = await tenant.run(read)
                except FdbError as e:
                    if e.name == "tenant_not_found":
                        continue
                    raise
                assert got == self.model.get(probe), (
                    f"tenant {probe!r} read {got!r}, "
                    f"expected {self.model.get(probe)!r}")
        self.metrics["tenant_ops"] = ops

    async def check(self) -> bool:
        from ..tenant import management as tm
        from ..tenant.map import tenant_prefix
        entries = {e.name: e for e in await tm.list_tenants(self.db)}
        live = {n: e for n, e in entries.items()
                if n.startswith(b"wl-tenant-")}
        if set(live) != set(self.model):
            self.metrics["map_mismatch"] = 1.0
            return False
        for name, value in self.model.items():  # flowlint: state -- checks the entry-time model
            tenant = await self.db.open_tenant(name)

            async def read(t):
                return await t.get(self.MARKER)
            if await tenant.run(read) != value:
                return False
            # The data must live under THIS tenant's committed prefix in
            # the raw keyspace — prefix isolation, not client smoke.
            t = self.db.create_transaction()
            from ..core.error import FdbError
            while True:
                try:
                    got = await t.get(tenant_prefix(live[name].id) +
                                      self.MARKER)
                    break
                except FdbError as e:
                    await t.on_error(e)
            if got != value:
                return False
        self.metrics["tenants_verified"] = float(len(self.model))
        return True


@register_workload
class KillRegionWorkload(TestWorkload):
    """Region failover chaos (reference workloads/KillRegion.actor.cpp):
    provisions a remote dc mid-run, waits for the async plane to
    converge to a marker commit (the drained switchover point), kills
    the ENTIRE primary dc, and verifies the cluster recovers onto the
    remote replicas with every acked commit intact.

    check() leaves the cluster serving from the remote dc — pair with
    Cycle/ConsistencyCheck workloads whose checks then run post-failover."""

    name = "KillRegion"

    async def setup(self) -> None:
        c = self.cluster
        self._remote_dc = str(self.config.get("remoteDc", "dcR"))
        n_storage = int(self.config.get("remoteStorage", 2))
        for i in range(n_storage):
            c.add_worker("storage", name=f"krw{i}", dcid=self._remote_dc)
        c.add_worker("stateless", name="krwcc", dcid=self._remote_dc,
                     campaign=True)
        from ..client.management import change_configuration
        await change_configuration(self.db, usable_regions=2,
                                   remote_dc=self._remote_dc)

    def _primary_dcs(self, info):
        """The dc ids actually hosting the SERVING storage set of this
        generation — derived from the recruited configuration, never
        assumed: a spec whose primary dc is not "dc0" must still kill
        the real primary (ISSUE 10 satellite)."""
        dcs = set()
        for iface in (info.storage_servers or {}).values():
            p = self.cluster.process_of(iface)
            if p is not None:
                dcs.add(p.locality.dcid)
        dcs.discard(self._remote_dc)
        return dcs

    async def start(self) -> None:
        c = self.cluster
        # Wait for the remote plane (shared DR poll pacing: backoff
        # toward the cap while the plane recruits).
        await dr_poll_until(
            lambda: remote_plane_up(c),
            float(self.config.get("planeTimeout", 120)),
            "remote plane never recruited")
        # Drained switchover point: a marker commit fully replicated.
        v = await commit_marker(
            self.db, b"killregion/marker",
            float(self.config.get("markerTimeout", 60)),
            "killregion marker commit never landed (commit pipeline "
            "dead before the kill)")

        def replicas_converged():
            cc = c.current_cc()
            info = cc.db_info if cc is not None else None
            roles = [getattr(i, "role", None)
                     for i in (info.remote_storage.values()
                               if info is not None else ())]
            if roles and all(r is not None and r.version.get() >= v
                             for r in roles):
                return info
            return None
        info = await dr_poll_until(
            replicas_converged,
            float(self.config.get("drainTimeout", 240)),
            "remote replicas never converged")
        # KillRegion: the whole primary dc (derived, possibly several
        # dcs if storage spans them) dies at once.
        primary_dcs = self._primary_dcs(info)
        if str(self.config.get("primaryDc", "")):
            primary_dcs = {str(self.config.get("primaryDc"))}
        if not primary_dcs:
            raise AssertionError("could not derive a primary dc to kill")
        killed = 0
        for p, _w, _cc, _lv in list(c.workers):
            if p.alive and p.locality.dcid in primary_dcs:
                c.sim.kill_process(p)
                killed += 1
        self.metrics["killed"] = killed

    async def check(self) -> bool:
        from ..core.error import FdbError
        t = self.db.create_transaction()
        while True:
            try:
                ok = (await t.get(b"killregion/marker")) == b"1"
                break
            except FdbError as e:
                await t.on_error(e)
        cc = self.cluster.current_cc()
        self.metrics["post_failover_epoch"] = (
            cc.db_info.epoch if cc is not None else -1)
        # The serving storage set is the adopted twin replicas (non-empty:
        # an all() over an empty dict must not vacuously pass).
        adopted = (cc is not None and
                   len(cc.db_info.storage_servers) > 0 and
                   all(tag >= 1_000_000
                       for tag in cc.db_info.storage_servers))
        self.metrics["adopted_remote"] = float(adopted)
        return ok and adopted


@register_workload
class BackupAndRestoreWorkload(TestWorkload):
    """Online backup + prefix-shifted restore under chaos (ISSUE 10;
    reference fdbserver/workloads/BackupAndRestoreCorrectness.actor.cpp,
    simplified): submit a backup — the snapshot task chain runs through
    TaskBucket agents and the mutation log rides BACKUP_TAG through
    every epoch the nemesis forces — keep mutating the watched prefix
    while capture runs, stop/seal the container, then restore it into
    THIS cluster under a shifted prefix (reference fdbrestore
    --add-prefix) and consistency-check restored-vs-live at the backup's
    end version.

    Every mutation is IDEMPOTENT (unique-value sets and clears, no
    atomic ops), so commit_unknown_result retries under chaos cannot
    skew the model: the tracked model is exactly the definite effect of
    every acked transaction, the live prefix must equal it after the
    mutation phase, and the restored image must equal it shifted —
    proving the capture stream lost nothing across recoveries."""

    name = "BackupAndRestore"

    PREFIX = b"bw/"
    RESTORE_PREFIX = b"bwr/"

    async def setup(self) -> None:
        n = int(self.config.get("nodeCount", 25))

        async def populate(t):
            for i in range(n):
                t.set(self.PREFIX + b"%04d" % i, b"init%04d" % i)
        await self.run_transaction(populate)
        self.model: Dict[bytes, bytes] = {
            self.PREFIX + b"%04d" % i: b"init%04d" % i for i in range(n)}

    async def start(self) -> None:
        from ..client.backup import FileBackupAgent, restore
        from ..core.coverage import test_coverage
        from ..server.sim_fs import SimFileSystem
        n = int(self.config.get("nodeCount", 25))
        duration = float(self.config.get("mutateDuration", 4.0))
        rng = random.Random(int(self.config.get("seed", 12)))
        # A fresh SimFileSystem as this run's shared blob store: the
        # container must survive every process/machine fault the nemesis
        # injects (it models remote object storage).
        fs = SimFileSystem()
        agent = FileBackupAgent(self.cluster, self.db, fs,
                                name="chaos-backup")
        await agent.submit()
        deadline = now() + duration
        writes = 0
        while now() < deadline:
            writes += 1
            if rng.random() < 0.8:
                k = self.PREFIX + b"%04d" % rng.randrange(n)
                v = b"w%08d" % writes

                async def put(t, k=k, v=v):
                    t.set(k, v)
                await self.run_transaction(put)
                self.model[k] = v
            else:
                lo = rng.randrange(n)
                hi = min(n, lo + rng.randrange(1, 4))
                b = self.PREFIX + b"%04d" % lo
                e = self.PREFIX + b"%04d" % hi

                async def clr(t, b=b, e=e):
                    t.clear(b, e)
                await self.run_transaction(clr)
                for k in [k for k in self.model if b <= k < e]:
                    del self.model[k]
        # Seal: every acked mutation above committed strictly before the
        # stop version, so the container covers the whole model.
        end_version = await agent.stop()
        # Restore the sealed container into the LIVE cluster, shifted.
        await restore(self.db, fs, name="chaos-backup",
                      prefix=self.RESTORE_PREFIX)
        test_coverage("BackupRestoreUnderChaos")
        self.metrics["mutations"] = writes
        self.metrics["backup_end_version"] = float(end_version)

    async def check(self) -> bool:
        async def read_both(t):
            live = dict(await t.get_range(
                self.PREFIX, self.PREFIX[:-1] + b"0", limit=100000))
            shifted_begin = self.RESTORE_PREFIX + self.PREFIX
            restored = dict(await t.get_range(
                shifted_begin, shifted_begin[:-1] + b"0", limit=100000))
            return live, restored
        live, restored = await self.run_transaction(read_both)
        expected_restored = {self.RESTORE_PREFIX + k: v
                             for k, v in self.model.items()}
        self.metrics["live_keys"] = float(len(live))
        self.metrics["restored_keys"] = float(len(restored))
        if live != self.model:
            self.metrics["live_mismatch"] = 1.0
            return False
        if restored != expected_restored:
            self.metrics["restored_mismatch"] = 1.0
            return False
        return True


@register_workload
class SchedRepairLoadWorkload(TestWorkload):
    """Repair-eligible blind-write load + exactly-once audit (ISSUE 12).

    Every transaction is a legitimate repair candidate: its mutations
    are atomic ADDs (value-independent — valid under re-read by
    construction), guarded by a read conflict range on one SHARED hot
    key that every transaction also blind-writes.  Under contention the
    read guard goes stale constantly, so with SCHED_REPAIR_ENABLED the
    commit proxy exercises the re-stamp/re-resolve path continuously —
    including across resolver attrition when composed with ChaosNemesis.

    The audit is the duplicate-commit detector the chaos satellite
    demands: each transaction ADDs 1 to its own UNIQUE counter key, so
    after quiescence

      * an acked commit's counter must be EXACTLY 1 (a repair retry
        that double-committed — e.g. onto a freshly recruited resolver
        — would read 2);
      * a commit_unknown_result's counter must be 0 or 1;
      * a definitively-aborted id's counter must be 0;
      * the hot key's total must lie in [acked, acked + unknown].
    """

    name = "SchedRepairLoad"
    HOT = b"sched/hot"

    def __init__(self, cluster, db, config) -> None:
        super().__init__(cluster, db, config)
        self._acked: set = set()
        self._unknown: set = set()
        self._failed: set = set()

    @staticmethod
    def _ctr(i: int) -> bytes:
        return b"sched/ctr/%08d" % i

    async def start(self) -> None:
        from ..txn.types import MutationType
        duration = float(self.config.get("testDuration", 8.0))
        actors = int(self.config.get("actorCount", 3))
        deadline = now() + duration
        bounces = [0]
        one = (1).to_bytes(8, "little")

        async def worker(base: int) -> None:
            i = 0
            while now() < deadline:
                uid = base + i
                i += 1
                t = self.db.create_transaction()
                t.repairable = True
                t.tag = "schedload"
                while True:
                    try:
                        t.atomic_op(MutationType.AddValue,
                                    self._ctr(uid), one)
                        t.atomic_op(MutationType.AddValue, self.HOT, one)
                        t.add_read_conflict_range(
                            self.HOT, self.HOT + b"\x00")
                        await t.commit()
                        self._acked.add(uid)
                        break
                    except FdbError as e:
                        if e.name == "commit_unknown_result":
                            # Ambiguous: retrying the ADD could double-
                            # apply — record and move to a fresh id.
                            self._unknown.add(uid)
                            break
                        if now() >= deadline and e.name == "not_committed":
                            # Definitive abort at the deadline: no
                            # commit of this id can ever land.
                            self._failed.add(uid)
                            break
                        bounces[0] += 1
                        try:
                            await t.on_error(e)
                        except FdbError:
                            self._failed.add(uid)
                            break
                        if now() >= deadline + 120.0:
                            # Hard escape: every retryable error here is
                            # a definitive no-commit (commit() already
                            # maps ambiguous losses to
                            # commit_unknown_result), so abandoning the
                            # retry leaves the counter provably at 0.
                            self._failed.add(uid)
                            break
        await wait_all([spawn(worker(k * 1_000_000), "schedload.worker")
                        for k in range(actors)])
        self.metrics["acked"] = float(len(self._acked))
        self.metrics["unknown"] = float(len(self._unknown))
        self.metrics["failed"] = float(len(self._failed))
        self.metrics["client_bounces"] = float(bounces[0])

    async def check(self) -> bool:
        async def audit(t):
            bad = []
            hot_raw = await t.get(self.HOT)
            for uid in sorted(self._acked):
                v = await t.get(self._ctr(uid))
                n = int.from_bytes(v or b"", "little")
                if n != 1:
                    bad.append(("acked", uid, n))
            for uid in sorted(self._unknown):
                v = await t.get(self._ctr(uid))
                n = int.from_bytes(v or b"", "little")
                if n not in (0, 1):
                    bad.append(("unknown", uid, n))
            for uid in sorted(self._failed):
                v = await t.get(self._ctr(uid))
                n = int.from_bytes(v or b"", "little")
                if n != 0:
                    bad.append(("failed", uid, n))
            return bad, int.from_bytes(hot_raw or b"", "little")
        bad, hot_total = await self.run_transaction(audit)
        self.metrics["hot_total"] = float(hot_total)
        lo, hi = len(self._acked), len(self._acked) + len(self._unknown)
        if bad:
            self.metrics["audit_violations"] = float(len(bad))
            return False
        return lo <= hot_total <= hi


@register_workload
class ZipfianReadStormWorkload(TestWorkload):
    """Zipfian hot-key read storm + range scans under live mutation
    (ISSUE 15; reference ReadWrite.actor.cpp's skewed-access mode):
    readers hammer a log-uniform (Zipf-like) hot set with point reads
    and long get_range scans while writers rewrite values in place.

    Every value is self-describing — b"%06d|" % index + payload — so
    EVERY read is an invariant check, not just load: a point read must
    return its own index prefix (a cross-wired columnar reply or a
    stale-shard read returns some OTHER row's bytes), and every scan
    must come back sorted, gap-free in index space, and prefix-correct
    per row.  This is the read-path mirror of Cycle: any decode/scan
    fast-path bug that swaps, drops or duplicates rows trips it under
    nemesis, not just in quiet parity tests."""

    name = "ZipfianReadStorm"

    PREFIX = b"zipfr/"

    def _key(self, i: int) -> bytes:
        return self.PREFIX + b"%06d" % i

    @staticmethod
    def _check_row(k: bytes, v: bytes) -> bool:
        # zipfr/NNNNNN -> value must start b"NNNNNN|".
        return v.startswith(k[-6:] + b"|")

    async def setup(self) -> None:
        n = int(self.config.get("nodeCount", 120))

        async def populate(t):
            for i in range(n):
                t.set(self._key(i), b"%06d|seed" % i)
        await self.run_transaction(populate)

    async def start(self) -> None:
        import math
        n = int(self.config.get("nodeCount", 120))
        actors = int(self.config.get("actorCount", 4))
        duration = float(self.config.get("testDuration", 8.0))
        point_reads = int(self.config.get("readsPerTransaction", 6))
        scan_limit = int(self.config.get("scanLimit", 40))
        rng = random.Random(int(self.config.get("seed", 15)))
        deadline = now() + duration
        stats = {"points": 0, "scans": 0, "scan_rows": 0, "writes": 0}
        violations: List = []
        log_n = math.log(n)

        def zipf(r) -> int:
            # Log-uniform rank: index 0 is the celebrity object.
            return min(n - 1, int(math.exp(r.random() * log_n)) - 1)

        async def reader(seed: int) -> None:
            r = random.Random(seed)
            while now() < deadline:
                async def txn_fn(t):
                    for _ in range(point_reads):
                        i = zipf(r)
                        v = await t.get(self._key(i), snapshot=True)
                        if v is None or not self._check_row(self._key(i), v):
                            violations.append(("point", i, v))
                        stats["points"] += 1
                    if r.random() < 0.5:
                        lo = r.randrange(n)
                        rev = r.random() < 0.25
                        rows = await t.get_range(
                            self._key(lo), self.PREFIX + b"\xff",
                            limit=scan_limit, snapshot=True, reverse=rev)
                        idx = [int(k[-6:]) for k, _v in rows]
                        count = min(scan_limit, n - lo)
                        # Forward: ascending from lo; reverse: descending
                        # from the top of the keyspace.  Writers only
                        # rewrite values, so the index set is stable and
                        # the expectation exact.
                        expect = (list(range(n - 1, n - 1 - count, -1))
                                  if rev else list(range(lo, lo + count)))
                        if idx != expect:
                            violations.append(("scan-shape", lo, idx[:8]))
                        for k, v in rows:
                            if not self._check_row(k, v):
                                violations.append(("scan-row", k, v))
                        stats["scans"] += 1
                        stats["scan_rows"] += len(rows)
                await self.run_transaction(txn_fn)

        async def writer(seed: int) -> None:
            r = random.Random(seed)
            j = 0
            while now() < deadline:
                async def txn_fn(t):
                    for _ in range(2):
                        i = zipf(r)
                        t.set(self._key(i), b"%06d|w%07d" % (i, j))
                        stats["writes"] += 1
                await self.run_transaction(txn_fn)
                j += 1
                await delay(0.05)

        await wait_all(
            [spawn(reader(rng.randrange(1 << 30)), "zipf.reader")
             for _ in range(actors)] +
            [spawn(writer(rng.randrange(1 << 30)), "zipf.writer")])
        self._violations = violations
        for k, v in stats.items():
            self.metrics[k] = float(v)
        self.metrics["violations"] = float(len(violations))

    async def check(self) -> bool:
        n = int(self.config.get("nodeCount", 120))

        async def audit(t):
            rows = await t.get_range(self.PREFIX, self.PREFIX + b"\xff",
                                     limit=n + 10)
            return rows
        rows = await self.run_transaction(audit)
        ok = (len(rows) == n and
              all(self._check_row(k, v) for k, v in rows) and
              [int(k[-6:]) for k, _ in rows] == list(range(n)))
        return ok and not getattr(self, "_violations", [])


@register_workload
class WatchFanoutWorkload(TestWorkload):
    """Watch fan-out: ONE key, many watchers (ISSUE 15's celebrity-
    object scenario; reference WatchAndWait.actor.cpp at scale): every
    watcher loops get -> watch -> await-fire until it observes the
    writer's FINAL sentinel, re-registering through chaos errors
    (broken_promise from a killed storage, too_old after clogs).  The
    storage server keeps ONE trigger entry per key however many watchers
    park on it, so the fan-out costs O(1) server state per fire.

    check(): every watcher terminated by OBSERVING the sentinel — a
    watch plane that drops fires under nemesis hangs the workload
    (loud timeout) instead of passing silently."""

    name = "WatchFanout"

    KEY = b"fanout/cell"
    FINAL = b"final"

    async def start(self) -> None:
        watchers = int(self.config.get("watchCount", 32))
        bumps = int(self.config.get("bumpCount", 5))
        duration = float(self.config.get("testDuration", 8.0))
        fires = [0]
        done = [0]

        async def setup(t):
            t.set(self.KEY, b"v0")
        await self.run_transaction(setup)

        async def watcher(i: int) -> None:
            while True:
                async def get_watch(t):
                    v = await t.get(self.KEY, snapshot=True)
                    if v == self.FINAL:
                        return None
                    f = await t.watch(self.KEY)
                    await t.commit()
                    return f
                f = await self.run_transaction(get_watch)
                if f is None:
                    break
                try:
                    await f
                    fires[0] += 1
                except FdbError:
                    # Watch holder died / clogged away: re-register off a
                    # fresh read — the loop's get decides liveness.
                    pass
            done[0] += 1

        async def writer() -> None:
            for j in range(bumps):
                await delay(duration / (bumps + 1))

                async def bump(t, j=j):
                    t.set(self.KEY, b"v%d" % (j + 1))
                await self.run_transaction(bump)

            async def fin(t):
                t.set(self.KEY, self.FINAL)
            await self.run_transaction(fin)

        await wait_all([spawn(watcher(i), "fanout.watch")
                        for i in range(watchers)] + [spawn(writer())])
        self.metrics["watchers_done"] = float(done[0])
        self.metrics["watch_fires"] = float(fires[0])

    async def check(self) -> bool:
        async def final(t):
            return await t.get(self.KEY)
        return (await self.run_transaction(final) == self.FINAL and
                self.metrics.get("watchers_done", 0) ==
                int(self.config.get("watchCount", 32)))
