"""Built-in workloads: invariant checkers, load generators, chaos injectors.

Reference models:
- Cycle         (fdbserver/workloads/Cycle.actor.cpp): a ring of keys;
  transactions swap pointers; the ring must remain a single cycle under
  any interleaving/chaos — THE serializability canary.
- ReadWrite     (fdbserver/workloads/ReadWrite.actor.cpp): configurable
  read/write load, reports ops/s.
- Attrition     (fdbserver/workloads/MachineAttrition.actor.cpp): kills
  random processes on an interval.
- RandomClogging (fdbserver/workloads/RandomClogging.actor.cpp): clogs
  random network pairs.
- ConflictRange (fdbserver/workloads/ConflictRange.actor.cpp, simplified):
  randomized cross-check of conflict behavior against an in-memory model.
"""

from __future__ import annotations

import random
from typing import Dict, List

from ..core.error import FdbError
from ..core.scheduler import delay, now, spawn
from ..core.futures import wait_all
from .workload import TestWorkload, register_workload


@register_workload
class CycleWorkload(TestWorkload):
    name = "Cycle"

    async def setup(self) -> None:
        n = int(self.config.get("nodeCount", 16))
        prefix = self.config.get("prefix", "cycle/").encode()

        async def populate(t):
            for i in range(n):
                t.set(prefix + b"%06d" % i, prefix + b"%06d" % ((i + 1) % n))
        await self.run_transaction(populate)

    async def start(self) -> None:
        n = int(self.config.get("nodeCount", 16))
        actors = int(self.config.get("actorCount", 4))
        duration = float(self.config.get("testDuration", 10.0))
        prefix = self.config.get("prefix", "cycle/").encode()
        rng = random.Random(int(self.config.get("seed", 1)))
        deadline = now() + duration
        swaps = [0]

        async def swapper(seed: int) -> None:
            r = random.Random(seed)
            while now() < deadline:
                async def swap(t):
                    a = prefix + b"%06d" % r.randrange(n)
                    b = await t.get(a)
                    cv = await t.get(b)
                    d = await t.get(cv)
                    t.set(a, cv)
                    t.set(b, d)
                    t.set(cv, b)
                await self.run_transaction(swap)
                swaps[0] += 1
        await wait_all([spawn(swapper(rng.randrange(1 << 30)))
                        for _ in range(actors)])
        self.metrics["swaps"] = swaps[0]

    async def check(self) -> bool:
        n = int(self.config.get("nodeCount", 16))
        prefix = self.config.get("prefix", "cycle/").encode()

        async def walk(t):
            seen, k = set(), prefix + b"%06d" % 0
            for _ in range(n):
                if k in seen:
                    return False
                seen.add(k)
                k = await t.get(k)
                if k is None:
                    return False
            return k == prefix + b"%06d" % 0 and len(seen) == n
        return await self.run_transaction(walk)


@register_workload
class ReadWriteWorkload(TestWorkload):
    name = "ReadWrite"

    async def setup(self) -> None:
        n = int(self.config.get("nodeCount", 100))

        async def populate(t):
            for i in range(n):
                t.set(b"rw/%08d" % i, b"v%08d" % i)
        await self.run_transaction(populate)

    async def start(self) -> None:
        n = int(self.config.get("nodeCount", 100))
        actors = int(self.config.get("actorCount", 4))
        reads = int(self.config.get("readsPerTransaction", 4))
        writes = int(self.config.get("writesPerTransaction", 2))
        duration = float(self.config.get("testDuration", 10.0))
        rng = random.Random(int(self.config.get("seed", 2)))
        deadline = now() + duration
        ops = [0]

        async def worker(seed: int) -> None:
            r = random.Random(seed)
            while now() < deadline:
                async def txn_fn(t):
                    for _ in range(reads):
                        await t.get(b"rw/%08d" % r.randrange(n))
                    for _ in range(writes):
                        t.set(b"rw/%08d" % r.randrange(n),
                              b"u%010d" % r.randrange(1 << 30))
                await self.run_transaction(txn_fn)
                ops[0] += reads + writes
        t0 = now()
        await wait_all([spawn(worker(rng.randrange(1 << 30)))
                        for _ in range(actors)])
        elapsed = max(now() - t0, 1e-9)
        self.metrics["operations"] = ops[0]
        self.metrics["ops_per_sec"] = ops[0] / elapsed

    async def check(self) -> bool:
        async def count(t):
            data = await t.get_range(b"rw/", b"rw0", limit=100000)
            return len(data)
        n = int(self.config.get("nodeCount", 100))
        return await self.run_transaction(count) == n


@register_workload
class AttritionWorkload(TestWorkload):
    """Kills random stateless-worker processes (reference MachineAttrition;
    storage-class workers are spared until data distribution can re-
    replicate lost shards)."""

    name = "Attrition"

    async def start(self) -> None:
        interval = float(self.config.get("testDuration", 10.0)) / max(
            int(self.config.get("machinesToKill", 2)), 1)
        rng = random.Random(int(self.config.get("seed", 3)))
        kills = 0
        for _ in range(int(self.config.get("machinesToKill", 2))):
            await delay(interval * (0.5 + rng.random()))
            victims = [p for _p, w, _cc, _lv in self.cluster.workers
                       if (p := _p).alive and w.process_class == "stateless"]
            # Keep at least two stateless workers alive so recovery can
            # always place a master + its transaction system.
            if len(victims) <= 2:
                continue
            victim = victims[rng.randrange(len(victims))]
            self.cluster.sim.kill_process(victim)
            kills += 1
        self.metrics["kills"] = kills


@register_workload
class RandomCloggingWorkload(TestWorkload):
    """Clogs random process pairs (reference RandomClogging)."""

    name = "RandomClogging"

    async def start(self) -> None:
        duration = float(self.config.get("testDuration", 10.0))
        rng = random.Random(int(self.config.get("seed", 4)))
        deadline = now() + duration
        clogs = 0
        while now() < deadline:
            await delay(duration / 10 * (0.5 + rng.random()))
            procs = self.cluster.sim.alive_processes()
            if len(procs) >= 2:
                a, b = rng.sample(procs, 2)
                self.cluster.sim.clog_pair(a, b,
                                           seconds=rng.random() * 2.0)
                clogs += 1
        self.metrics["clogs"] = clogs


@register_workload
class ConflictRangeWorkload(TestWorkload):
    """Randomized serializability cross-check vs. an in-memory model
    (reference ConflictRange.actor.cpp:31, simplified): one actor applies
    random sets/clears through transactions AND to a local dict; after
    quiescence the database must equal the model exactly."""

    name = "ConflictRange"

    async def start(self) -> None:
        duration = float(self.config.get("testDuration", 5.0))
        rng = random.Random(int(self.config.get("seed", 5)))
        n = int(self.config.get("nodeCount", 50))
        self.model: Dict[bytes, bytes] = {}
        deadline = now() + duration
        while now() < deadline:
            op = rng.random()
            if op < 0.6:
                k = b"cr/%04d" % rng.randrange(n)
                v = b"%08d" % rng.randrange(1 << 20)

                async def do_set(t, k=k, v=v):
                    t.set(k, v)
                await self.run_transaction(do_set)
                self.model[k] = v
            else:
                lo = rng.randrange(n)
                hi = min(n, lo + rng.randrange(1, 8))
                b, e = b"cr/%04d" % lo, b"cr/%04d" % hi

                async def do_clear(t, b=b, e=e):
                    t.clear(b, e)
                await self.run_transaction(do_clear)
                for k in [k for k in self.model if b <= k < e]:
                    del self.model[k]

    async def check(self) -> bool:
        async def read_all(t):
            return dict(await t.get_range(b"cr/", b"cr0", limit=100000))
        actual = await self.run_transaction(read_all)
        return actual == self.model


@register_workload
class ConsistencyCheckWorkload(TestWorkload):
    """Replica audit (reference fdbserver/workloads/ConsistencyCheck
    .actor.cpp:31, core check): for every shard, read the full range at one
    read version from EVERY team replica and require byte-identical
    results.  Retries wrong_shard_server/future_version (a replica may
    still be fetching after a move)."""

    name = "ConsistencyCheck"

    async def check(self) -> bool:
        from ..rpc.endpoint import RequestStream
        from ..server.interfaces import GetKeyValuesRequest
        shards_audited = 0
        cursor = b""
        while cursor < b"\xff":
            b, e, ssis = await self.db.get_shard_location(cursor)
            if not ssis:
                cursor = e
                continue
            while True:
                t = self.db.create_transaction()
                try:
                    version = await t._ensure_read_version()
                    replies = []
                    for ssi in ssis:
                        replies.append(await RequestStream.at(
                            ssi.get_key_values.endpoint).get_reply(
                            GetKeyValuesRequest(
                                begin=max(b, cursor), end=min(e, b"\xff"),
                                version=version, limit=1 << 30,
                                limit_bytes=1 << 40)))
                    first = replies[0].data
                    for i, r in enumerate(replies[1:], 1):
                        if r.data != first:
                            raise AssertionError(
                                f"replica divergence in [{b!r},{e!r}): "
                                f"replica 0 has {len(first)} kvs, "
                                f"replica {i} has {len(r.data)}")
                    shards_audited += 1
                    break
                except FdbError as ex:
                    if ex.name not in ("wrong_shard_server", "future_version",
                                       "broken_promise", "transaction_too_old",
                                       "request_maybe_delivered"):
                        raise
                    await delay(0.1)
                    self.db.invalidate_cache(max(b, cursor))
                    b, e, ssis = await self.db.get_shard_location(cursor)
            cursor = e
        self.metrics["shards_audited"] = shards_audited
        return True
