"""Thread offload for blocking work (reference flow/IThreadPool.h).

The reactor (core/scheduler.py EventLoop) is single-threaded; a synchronous
fsync or a big native conflict-resolve on it stalls every connection and
timer of the process (the reference routes such work through IThreadPool /
CoroFlow for the same reason).  `run_blocking(fn, *args)` runs `fn` on a
worker thread and resumes the awaiting actor on the reactor:

- REAL mode: a shared ThreadPoolExecutor per loop; completions post to a
  thread-safe queue and wake the reactor through a self-pipe registered
  with add_reader (the reactor may be parked in selector.select with no
  timers due — a plain call_soon from another thread would not wake it).
- SIM mode: the fn runs INLINE and completion is delivered through a
  zero-delay timer, preserving the simulator's determinism (reference
  CoroFlow adapts threaded interfaces back onto the deterministic net in
  simulation the same way).  Virtual cost can be charged with `sim_cost`.

Thread-safety contract: `fn` must not touch loop-owned state; callers are
responsible for not mutating the objects `fn` reads while it runs (every
current caller awaits the result before issuing dependent work).
"""

from __future__ import annotations

import collections
import os
import threading
from typing import Any, Callable

from .futures import Future, Promise
from .scheduler import EventLoop, get_event_loop

_MAX_WORKERS = 4


class LoopThreadPool:
    """Per-EventLoop offload pool; create via `pool_for(loop)`."""

    def __init__(self, loop: EventLoop) -> None:
        self.loop = loop
        self._executor = None
        self._done: collections.deque = collections.deque()
        self._wake_r = self._wake_w = None

    # -- real-mode plumbing --------------------------------------------------
    def _ensure_real(self) -> None:
        if self._executor is not None:
            return
        from concurrent.futures import ThreadPoolExecutor
        self._executor = ThreadPoolExecutor(
            max_workers=_MAX_WORKERS,
            thread_name_prefix="fdb-threadpool")
        r, w = os.pipe()
        os.set_blocking(r, False)
        self._wake_r, self._wake_w = r, w
        self.loop.add_reader(r, self._drain)

    def _drain(self) -> None:
        try:
            while os.read(self._wake_r, 4096):
                pass
        except BlockingIOError:
            pass
        while self._done:
            promise, ok, value = self._done.popleft()
            if ok:
                promise.send(value)
            else:
                promise.send_error(value)

    def run(self, fn: Callable[..., Any], *args, sim_cost: float = 0.0
            ) -> Future:
        p: Promise = Promise()
        if self.loop.sim:
            # Deterministic: execute inline, deliver via the timer heap.
            try:
                value, ok = fn(*args), True
            except Exception as e:  # noqa: BLE001 — routed to the future
                value, ok = e, False
            def deliver():
                if ok:
                    p.send(value)
                else:
                    p.send_error(value)
            self.loop.call_at(self.loop.now() + sim_cost, deliver)
            return p.get_future()
        self._ensure_real()

        def work():
            try:
                result = (fn(*args), True)
            except Exception as e:  # noqa: BLE001 — routed to the future
                result = (e, False)
            self._done.append((p, result[1], result[0]))
            try:
                os.write(self._wake_w, b"x")
            except OSError:
                pass
        self._executor.submit(work)
        return p.get_future()

    def close(self) -> None:
        if self._executor is not None:
            self._executor.shutdown(wait=False)
            self._executor = None
        if self._wake_r is not None:
            self.loop.remove_reader(self._wake_r)
            os.close(self._wake_r)
            os.close(self._wake_w)
            self._wake_r = self._wake_w = None


def pool_for(loop: EventLoop = None) -> LoopThreadPool:
    loop = loop or get_event_loop()
    pool = getattr(loop, "_thread_pool", None)
    if pool is None:
        pool = loop._thread_pool = LoopThreadPool(loop)
    return pool


async def run_blocking(fn: Callable[..., Any], *args,
                       sim_cost: float = 0.0) -> Any:
    """Run `fn(*args)` off the reactor thread; await its result."""
    return await pool_for().run(fn, *args, sim_cost=sim_cost)


def current_thread_is_reactor() -> bool:
    return threading.current_thread() is threading.main_thread()
