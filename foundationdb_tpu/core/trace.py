"""Structured trace events (reference flow/Trace.h TraceEvent).

TraceEvent("Name").detail("K", v).log() appends a structured record to the
process tracer: an in-memory ring plus optional JSONL file (the reference
writes rolling XML/JSON trace files, flow/FileTraceLogWriter.cpp).  Severity
40 (SevError) events are test failures, as in the reference harness.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Any, Deque, Dict, List, Optional


class Severity:
    Debug = 5
    Info = 10
    Warn = 20
    WarnAlways = 30
    Error = 40


class Tracer:
    def __init__(self, ring_size: int = 20000, path: Optional[str] = None) -> None:
        self.ring: Deque[Dict[str, Any]] = deque(maxlen=ring_size)
        self.path = path
        self._fh = open(path, "a", encoding="utf-8") if path else None
        self.error_count = 0
        self._lock = threading.Lock()

    def emit(self, event: Dict[str, Any]) -> None:
        with self._lock:
            self.ring.append(event)
            if event.get("Severity", 10) >= Severity.Error:
                self.error_count += 1
            if self._fh:
                self._fh.write(json.dumps(event, default=str) + "\n")

    def flush(self) -> None:
        if self._fh:
            self._fh.flush()

    def find(self, type_name: str) -> List[Dict[str, Any]]:
        return [e for e in self.ring if e.get("Type") == type_name]

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None


_tracer = Tracer()


def set_tracer(t: Tracer) -> None:
    global _tracer
    _tracer = t


def get_tracer() -> Tracer:
    return _tracer


# Process-wide "current span context" (reference TraceEvent's implicit
# span association via the actor's SpanContext): set by transports and
# role handlers around request processing, stamped onto every TraceEvent
# emitted inside, so cross-process hops correlate without threading the
# id through every call signature.
_current_span: str = ""


def set_current_span(ctx: str) -> str:
    """Install `ctx` as the ambient span; returns the previous one so
    callers can restore (set/emit/restore, not a context manager, to stay
    cheap on the hot path)."""
    global _current_span
    prev = _current_span
    _current_span = ctx
    return prev


def get_current_span() -> str:
    return _current_span


class TraceEvent:
    """Builder-style structured log record."""

    __slots__ = ("_event", "_logged")

    def __init__(self, type_name: str, severity: int = Severity.Info,
                 id: str = "") -> None:
        from .scheduler import _current
        t = _current.now() if _current is not None else 0.0
        self._event: Dict[str, Any] = {
            "Type": type_name,
            "Severity": severity,
            "Time": round(t, 6),
        }
        if _current_span:
            self._event["SpanContext"] = _current_span
        if id:
            self._event["ID"] = id
        self._logged = False

    def detail(self, key: str, value: Any) -> "TraceEvent":
        self._event[key] = value
        return self

    def error(self, e: BaseException) -> "TraceEvent":
        self._event["Error"] = repr(e)
        return self

    def log(self) -> None:
        if not self._logged:
            self._logged = True
            _tracer.emit(self._event)

    def __del__(self) -> None:  # auto-log on drop, like the reference
        try:
            self.log()
        except Exception:  # pragma: no cover - interpreter shutdown
            pass


class Span:
    """Request-scoped span (reference flow/Tracing.h:36): a named timed
    region with a parent link, emitted as a "Span" trace event on finish
    (the LogfileTracer sink, Tracing.actor.cpp:47).  Contexts travel
    inside requests as strings; a role handling a request opens a child
    span with parent=request.span_context.  Construction with an empty
    parent on an UNSAMPLED path is free-ish: pass sampled=False and
    nothing is emitted (reference: unsampled spans skip the tracer)."""

    __slots__ = ("context", "parent", "name", "_t0", "sampled")

    def __init__(self, name: str, parent: str = "",
                 sampled: bool = True) -> None:
        self.name = name
        self.parent = parent
        self.sampled = sampled
        from .rng import deterministic_random
        self.context = (deterministic_random().random_unique_id()[:16]
                        if self.sampled else "")
        from .scheduler import current_event_loop_or_none
        lp = current_event_loop_or_none()
        self._t0 = lp.now() if lp is not None else 0.0

    def finish(self) -> None:
        if not self.sampled:
            return
        from .scheduler import current_event_loop_or_none
        lp = current_event_loop_or_none()
        t1 = lp.now() if lp is not None else 0.0
        TraceEvent("Span").detail("Name", self.name).detail(
            "SpanID", self.context).detail("ParentID", self.parent).detail(
            "Duration", round(t1 - self._t0, 6)).log()

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.finish()


def trace_batch_event(event_type: str, debug_id: str, location: str) -> None:
    """Transaction debug correlation (reference g_traceBatch.addEvent:
    "TransactionDebug"/"CommitDebug" point events at every hop, keyed by
    the transaction's debug id; post-processed into a cross-process
    timeline by contrib/commit_debug.py).  No-op without a debug id."""
    if debug_id:
        TraceEvent(event_type).detail("DebugID", debug_id).detail(
            "Location", location).log()
