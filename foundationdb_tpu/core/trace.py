"""Structured trace events (reference flow/Trace.h TraceEvent).

TraceEvent("Name").detail("K", v).log() appends a structured record to the
process tracer: an in-memory ring plus optional JSONL file (the reference
writes rolling XML/JSON trace files, flow/FileTraceLogWriter.cpp).  Severity
40 (SevError) events are test failures, as in the reference harness.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Any, Deque, Dict, List, Optional


class Severity:
    Debug = 5
    Info = 10
    Warn = 20
    WarnAlways = 30
    Error = 40


#: Severity value -> status-JSON label (reference Status.actor.cpp's
#: message severities; status cluster.messages rolls counts per label).
SEVERITY_NAMES = {Severity.Debug: "debug", Severity.Info: "info",
                  Severity.Warn: "warning", Severity.WarnAlways:
                  "warning_always", Severity.Error: "error"}


class Tracer:
    """In-memory ring + optional rolling JSONL file.

    File hygiene (reference flow/FileTraceLogWriter.cpp): the active file
    rolls once it exceeds `roll_bytes` (trace.0.jsonl -> trace.1.jsonl,
    older files shifting up, at most `keep_files` rolled files kept), and
    the writer flushes every `flush_every` events so a crashed process
    leaves a usable trace tail.  close() emits a final TraceStats event
    so the error count of the run is never lost."""

    def __init__(self, ring_size: int = 20000, path: Optional[str] = None,
                 roll_bytes: int = 0, keep_files: int = 5,
                 flush_every: int = 64) -> None:
        self.ring: Deque[Dict[str, Any]] = deque(maxlen=ring_size)
        self.path = path
        self._fh = open(path, "a", encoding="utf-8") if path else None
        self.error_count = 0
        self.events_emitted = 0
        # Lifetime event counts per severity value (status
        # cluster.messages): bumped under the emit lock, so per-connection
        # threads can't lose increments.
        self.severity_counts: Dict[int, int] = {}
        self.roll_bytes = roll_bytes
        self.keep_files = max(1, keep_files)
        self.flush_every = max(1, flush_every)
        self._bytes_written = (os.path.getsize(path)
                               if path and os.path.exists(path) else 0)
        self._since_flush = 0
        self._lock = threading.Lock()

    def _rolled_name(self, i: int) -> str:
        """trace.0.jsonl -> trace.<i>.jsonl; trace.jsonl -> trace.<i>.jsonl."""
        root, ext = os.path.splitext(self.path)
        if root.endswith(".0"):
            root = root[:-2]
        return f"{root}.{i}{ext}"

    def _roll(self) -> None:
        """Shift rolled files up one slot and start a fresh active file
        (caller holds the lock — a contract flowlint PROVES
        interprocedurally since ISSUE 11: every callsite of this
        private method sits inside emit()'s ``with self._lock:``, so
        its entry lockset is seeded with the lock and the FTL012
        suppressions this method used to carry are gone)."""
        self._fh.close()
        try:
            last = self._rolled_name(self.keep_files)
            if os.path.exists(last):
                os.remove(last)
            for i in range(self.keep_files - 1, 0, -1):
                src = self._rolled_name(i)
                if os.path.exists(src):
                    os.replace(src, self._rolled_name(i + 1))
            os.replace(self.path, self._rolled_name(1))
        except OSError:  # pragma: no cover - a lost roll keeps appending
            pass
        self._fh = open(self.path, "a", encoding="utf-8")
        self._bytes_written = 0

    def emit(self, event: Dict[str, Any]) -> None:
        # Unseed verification: the (event name, time) stream is part of
        # the run digest — a divergent run that logs one extra event is
        # caught even if it never touched the RNG or the scheduler heap.
        # Details are NOT folded: they may legitimately carry
        # nondeterministic ids (nondeterministic_random unique ids).
        # SIM ONLY, like the scheduler's fold: real-mode events are
        # wall-clock-timed (meaningless to digest) and can arrive from
        # per-connection threads (racy against an unlocked RunDigest).
        from .scheduler import _current
        if _current is not None and _current.sim:
            from .rng import run_digest
            run_digest().fold_event(event.get("Type", ""),
                                    event.get("Time", 0.0))
        with self._lock:
            self.ring.append(event)
            self.events_emitted += 1
            sev = event.get("Severity", 10)
            self.severity_counts[sev] = self.severity_counts.get(sev, 0) + 1
            if sev >= Severity.Error:
                self.error_count += 1
            if self._fh:
                line = json.dumps(event, default=str) + "\n"
                self._fh.write(line)
                self._bytes_written += len(line)
                self._since_flush += 1
                if self._since_flush >= self.flush_every:
                    self._since_flush = 0
                    self._fh.flush()
                if self.roll_bytes and self._bytes_written >= self.roll_bytes:
                    self._roll()

    def flush(self) -> None:
        with self._lock:
            if self._fh:
                self._fh.flush()

    def find(self, type_name: str,
             min_severity: Optional[int] = None) -> List[Dict[str, Any]]:
        # Snapshot under the lock: per-connection threads append to the
        # ring through emit(), and iterating a deque mid-append is
        # undefined (FTL012 catch).
        with self._lock:
            events = list(self.ring)
        return [e for e in events if e.get("Type") == type_name and
                (min_severity is None or
                 e.get("Severity", 10) >= min_severity)]

    def messages(self) -> Dict[str, int]:
        """Per-severity-label lifetime counts (the status
        cluster.messages shape)."""
        with self._lock:
            counts = dict(self.severity_counts)
        out: Dict[str, int] = {}
        for sev, n in counts.items():
            label = SEVERITY_NAMES.get(sev, f"sev{sev}")
            out[label] = out.get(label, 0) + n
        return dict(sorted(out.items()))

    def close(self) -> None:
        # Final accounting (the reference's TraceLog close summary): a
        # run's error count must reach the file even when nothing reads
        # the live ring.  Built by hand — TraceEvent would re-enter emit
        # through the global tracer, which may not be this instance.
        # Events counts the run's events, excluding this summary record.
        # Counters are snapshotted under the lock (emit bumps them from
        # other threads) and the lock is RELEASED before emit() retakes
        # it for the summary record.
        with self._lock:
            if self._fh is None:
                return
            n_events = self.events_emitted
            n_errors = self.error_count
        from .scheduler import _current
        self.emit({"Type": "TraceStats", "Severity": Severity.Info,
                   "Time": round(_current.now() if _current is not None
                                 else 0.0, 6),
                   "Events": n_events,
                   "ErrorCount": n_errors})
        with self._lock:
            if self._fh:
                self._fh.close()
                self._fh = None


_tracer = Tracer()


def set_tracer(t: Tracer) -> None:
    global _tracer
    _tracer = t


def get_tracer() -> Tracer:
    return _tracer


# Ambient "current span context" (reference TraceEvent's implicit span
# association via the actor's SpanContext): set by transports and role
# handlers around request processing, stamped onto every TraceEvent
# emitted inside, so cross-process hops correlate without threading the
# id through every call signature.  THREAD-LOCAL, not a module global:
# TcpTransport handlers run on per-connection threads, and a shared
# global would stamp one connection's events with another's span (and
# restore a stale value on exit) under concurrent requests.
_span_local = threading.local()


def set_current_span(ctx: str) -> str:
    """Install `ctx` as this thread's ambient span; returns the previous
    one so callers can restore (set/emit/restore, not a context manager,
    to stay cheap on the hot path)."""
    prev = getattr(_span_local, "ctx", "")
    _span_local.ctx = ctx
    return prev


def get_current_span() -> str:
    return getattr(_span_local, "ctx", "")


class TraceEvent:
    """Builder-style structured log record."""

    __slots__ = ("_event", "_logged")

    def __init__(self, type_name: str, severity: int = Severity.Info,
                 id: str = "") -> None:
        from .scheduler import _current
        t = _current.now() if _current is not None else 0.0
        self._event: Dict[str, Any] = {
            "Type": type_name,
            "Severity": severity,
            "Time": round(t, 6),
        }
        span = getattr(_span_local, "ctx", "")
        if span:
            self._event["SpanContext"] = span
        if id:
            self._event["ID"] = id
        self._logged = False

    def detail(self, key: str, value: Any) -> "TraceEvent":
        self._event[key] = value
        return self

    def error(self, e: BaseException) -> "TraceEvent":
        self._event["Error"] = repr(e)
        return self

    def log(self) -> None:
        if not self._logged:
            self._logged = True
            _tracer.emit(self._event)

    def __del__(self) -> None:  # auto-log on drop, like the reference
        try:
            self.log()
        except Exception:  # pragma: no cover - interpreter shutdown
            pass


class Span:
    """Request-scoped span (reference flow/Tracing.h:36): a named timed
    region with a parent link, emitted as a "Span" trace event on finish
    (the LogfileTracer sink, Tracing.actor.cpp:47).  Contexts travel
    inside requests as strings; a role handling a request opens a child
    span with parent=request.span_context.  Construction with an empty
    parent on an UNSAMPLED path is free-ish: pass sampled=False and
    nothing is emitted (reference: unsampled spans skip the tracer)."""

    __slots__ = ("context", "parent", "name", "_t0", "sampled")

    def __init__(self, name: str, parent: str = "",
                 sampled: bool = True) -> None:
        self.name = name
        self.parent = parent
        self.sampled = sampled
        from .rng import deterministic_random
        self.context = (deterministic_random().random_unique_id()[:16]
                        if self.sampled else "")
        from .scheduler import current_event_loop_or_none
        lp = current_event_loop_or_none()
        self._t0 = lp.now() if lp is not None else 0.0

    def finish(self) -> None:
        if not self.sampled:
            return
        from .scheduler import current_event_loop_or_none
        lp = current_event_loop_or_none()
        t1 = lp.now() if lp is not None else 0.0
        TraceEvent("Span").detail("Name", self.name).detail(
            "SpanID", self.context).detail("ParentID", self.parent).detail(
            "Duration", round(t1 - self._t0, 6)).log()

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.finish()


def trace_batch_event(event_type: str, debug_id: str, location: str) -> None:
    """Transaction debug correlation (reference g_traceBatch.addEvent:
    "TransactionDebug"/"CommitDebug" point events at every hop, keyed by
    the transaction's debug id; post-processed into a cross-process
    timeline by contrib/commit_debug.py).  No-op without a debug id."""
    if debug_id:
        TraceEvent(event_type).detail("DebugID", debug_id).detail(
            "Location", location).log()
