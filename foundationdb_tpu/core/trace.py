"""Structured trace events (reference flow/Trace.h TraceEvent).

TraceEvent("Name").detail("K", v).log() appends a structured record to the
process tracer: an in-memory ring plus optional JSONL file (the reference
writes rolling XML/JSON trace files, flow/FileTraceLogWriter.cpp).  Severity
40 (SevError) events are test failures, as in the reference harness.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Any, Deque, Dict, List, Optional


class Severity:
    Debug = 5
    Info = 10
    Warn = 20
    WarnAlways = 30
    Error = 40


class Tracer:
    def __init__(self, ring_size: int = 20000, path: Optional[str] = None) -> None:
        self.ring: Deque[Dict[str, Any]] = deque(maxlen=ring_size)
        self.path = path
        self._fh = open(path, "a", encoding="utf-8") if path else None
        self.error_count = 0
        self._lock = threading.Lock()

    def emit(self, event: Dict[str, Any]) -> None:
        with self._lock:
            self.ring.append(event)
            if event.get("Severity", 10) >= Severity.Error:
                self.error_count += 1
            if self._fh:
                self._fh.write(json.dumps(event, default=str) + "\n")

    def flush(self) -> None:
        if self._fh:
            self._fh.flush()

    def find(self, type_name: str) -> List[Dict[str, Any]]:
        return [e for e in self.ring if e.get("Type") == type_name]

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None


_tracer = Tracer()


def set_tracer(t: Tracer) -> None:
    global _tracer
    _tracer = t


def get_tracer() -> Tracer:
    return _tracer


class TraceEvent:
    """Builder-style structured log record."""

    __slots__ = ("_event", "_logged")

    def __init__(self, type_name: str, severity: int = Severity.Info,
                 id: str = "") -> None:
        from .scheduler import _current
        t = _current.now() if _current is not None else 0.0
        self._event: Dict[str, Any] = {
            "Type": type_name,
            "Severity": severity,
            "Time": round(t, 6),
        }
        if id:
            self._event["ID"] = id
        self._logged = False

    def detail(self, key: str, value: Any) -> "TraceEvent":
        self._event[key] = value
        return self

    def error(self, e: BaseException) -> "TraceEvent":
        self._event["Error"] = repr(e)
        return self

    def log(self) -> None:
        if not self._logged:
            self._logged = True
            _tracer.emit(self._event)

    def __del__(self) -> None:  # auto-log on drop, like the reference
        try:
            self.log()
        except Exception:  # pragma: no cover - interpreter shutdown
            pass
