"""Deterministic randomness (reference flow/DeterministicRandom.h).

ALL randomness inside a simulation must come from deterministic_random() so a
run is reproducible from its seed.  nondeterministic_random() exists for IDs
that must not perturb replay (reference flow/IRandom.h g_nondeterministic_random).
"""

from __future__ import annotations

import random
import struct
import zlib
from collections import deque
from typing import Deque, List, Optional, Sequence, Tuple, TypeVar

T = TypeVar("T")


class DeterministicRandom:
    def __init__(self, seed: int) -> None:
        self.seed = seed
        self._r = random.Random(seed)

    def random01(self) -> float:
        return self._r.random()

    def random_int(self, lo: int, hi: int) -> int:
        """Uniform integer in [lo, hi) (reference randomInt semantics)."""
        return self._r.randrange(lo, hi)

    def random_int64(self, lo: int, hi: int) -> int:
        return self._r.randrange(lo, hi)

    def random_unique_id(self) -> str:
        return f"{self._r.getrandbits(64):016x}{self._r.getrandbits(64):016x}"

    def random_alpha_numeric(self, length: int) -> str:
        chars = "abcdefghijklmnopqrstuvwxyz0123456789"
        return "".join(self._r.choice(chars) for _ in range(length))

    def random_bytes(self, length: int) -> bytes:
        return self._r.getrandbits(8 * length).to_bytes(length, "little") if length else b""

    def random_choice(self, seq: Sequence[T]) -> T:
        return seq[self.random_int(0, len(seq))]

    def random_skewed_uint32(self, lo: int, hi: int) -> int:
        """Log-uniform in [lo, hi) (reference randomSkewedUInt32)."""
        import math
        min_l = math.log2(max(lo, 1))
        max_l = math.log2(hi)
        return min(int(2 ** (min_l + self.random01() * (max_l - min_l))), hi - 1)

    def shuffle(self, lst: list) -> None:
        self._r.shuffle(lst)

    def coinflip(self) -> bool:
        return self.random01() < 0.5

    def unseed(self) -> int:
        """Digest of the FINAL generator state (reference
        DeterministicRandom::randomUInt32 drawn at simulation end — the
        'unseed').  Two same-seed runs that made identical draw sequences
        end in identical states; any extra/missing/reordered draw anywhere
        in the run changes this value.  Reading it does NOT perturb the
        state, so it can be sampled mid-run for checkpointing."""
        return zlib.crc32(repr(self._r.getstate()).encode()) & 0xFFFFFFFF


class RunDigest:
    """Rolling hash of a simulation's observable schedule.

    The unseed alone only witnesses RNG draws; a run can diverge without
    touching the RNG (e.g. a wall-clock-dependent branch issuing one more
    transaction).  The scheduler folds every dispatched (virtual time,
    task seq) and the tracer folds every (event name, time) into this
    digest, so ANY difference in what ran, when, or what it logged is
    caught.  Periodic checkpoints (every CHECKPOINT_EVERY folds) keep a
    bounded trail used for first-divergence reports when two same-seed
    runs disagree (reference TestHarness unseed mismatch triage)."""

    CHECKPOINT_EVERY = 1024
    MAX_CHECKPOINTS = 1 << 16

    __slots__ = ("value", "folds", "checkpoints", "last_event")

    def __init__(self) -> None:
        self.value = 0
        self.folds = 0
        # (fold count, digest value, last trace event name, last time)
        self.checkpoints: Deque[Tuple[int, int, str, float]] = deque(
            maxlen=self.MAX_CHECKPOINTS)
        self.last_event = ""

    _TASK = struct.Struct("<dI")

    def fold_task(self, when: float, seq: int) -> None:
        self.value = zlib.crc32(
            self._TASK.pack(when, seq & 0xFFFFFFFF), self.value)
        self.folds += 1
        if self.folds % self.CHECKPOINT_EVERY == 0:
            self.checkpoints.append(
                (self.folds, self.value, self.last_event, when))

    def fold_event(self, name: str, t: float) -> None:
        self.value = zlib.crc32(name.encode(), self.value ^ hash(t) &
                                0xFFFFFFFF)
        self.folds += 1
        self.last_event = name


_run_digest = RunDigest()


def run_digest() -> RunDigest:
    return _run_digest


def reset_run_digest() -> RunDigest:
    """Fresh digest for a new simulation run.  EventLoops bind the digest
    current at THEIR construction, so reset before building the world."""
    global _run_digest
    _run_digest = RunDigest()
    return _run_digest


def run_unseed() -> int:
    """The run's combined unseed: final deterministic-RNG state folded
    with the schedule digest.  Equal across two runs iff both the draw
    sequence and the dispatched schedule/trace stream were identical."""
    return (deterministic_random().unseed() ^
            (_run_digest.value * 0x9E3779B1 & 0xFFFFFFFF))


_det: Optional[DeterministicRandom] = None
# Seeded from OS entropy: IDs from this generator must differ across processes
# and runs (they exist precisely to NOT be replayable).
_nondet = DeterministicRandom(int.from_bytes(__import__("os").urandom(8), "little"))


def set_deterministic_random(rng: DeterministicRandom) -> None:
    global _det
    _det = rng


def deterministic_random() -> DeterministicRandom:
    global _det
    if _det is None:
        _det = DeterministicRandom(1)
    return _det


def nondeterministic_random() -> DeterministicRandom:
    return _nondet
