"""Deterministic randomness (reference flow/DeterministicRandom.h).

ALL randomness inside a simulation must come from deterministic_random() so a
run is reproducible from its seed.  nondeterministic_random() exists for IDs
that must not perturb replay (reference flow/IRandom.h g_nondeterministic_random).
"""

from __future__ import annotations

import random
from typing import Optional, Sequence, TypeVar

T = TypeVar("T")


class DeterministicRandom:
    def __init__(self, seed: int) -> None:
        self.seed = seed
        self._r = random.Random(seed)

    def random01(self) -> float:
        return self._r.random()

    def random_int(self, lo: int, hi: int) -> int:
        """Uniform integer in [lo, hi) (reference randomInt semantics)."""
        return self._r.randrange(lo, hi)

    def random_int64(self, lo: int, hi: int) -> int:
        return self._r.randrange(lo, hi)

    def random_unique_id(self) -> str:
        return f"{self._r.getrandbits(64):016x}{self._r.getrandbits(64):016x}"

    def random_alpha_numeric(self, length: int) -> str:
        chars = "abcdefghijklmnopqrstuvwxyz0123456789"
        return "".join(self._r.choice(chars) for _ in range(length))

    def random_bytes(self, length: int) -> bytes:
        return self._r.getrandbits(8 * length).to_bytes(length, "little") if length else b""

    def random_choice(self, seq: Sequence[T]) -> T:
        return seq[self.random_int(0, len(seq))]

    def random_skewed_uint32(self, lo: int, hi: int) -> int:
        """Log-uniform in [lo, hi) (reference randomSkewedUInt32)."""
        import math
        min_l = math.log2(max(lo, 1))
        max_l = math.log2(hi)
        return min(int(2 ** (min_l + self.random01() * (max_l - min_l))), hi - 1)

    def shuffle(self, lst: list) -> None:
        self._r.shuffle(lst)

    def coinflip(self) -> bool:
        return self.random01() < 0.5


_det: Optional[DeterministicRandom] = None
# Seeded from OS entropy: IDs from this generator must differ across processes
# and runs (they exist precisely to NOT be replayable).
_nondet = DeterministicRandom(int.from_bytes(__import__("os").urandom(8), "little"))


def set_deterministic_random(rng: DeterministicRandom) -> None:
    global _det
    _det = rng


def deterministic_random() -> DeterministicRandom:
    global _det
    if _det is None:
        _det = DeterministicRandom(1)
    return _det


def nondeterministic_random() -> DeterministicRandom:
    return _nondet
