"""Error codes for the framework.

Mirrors the reference's flow/error_definitions.h error-code contract (the
codes themselves follow the reference's public wire protocol so that clients
behave identically on retryable vs fatal errors)."""

from __future__ import annotations


class FdbError(Exception):
    """An error with a FoundationDB-compatible numeric code."""

    def __init__(self, code: int, name: str = "", message: str = ""):
        self.code = code
        self.name = name or _CODE_TO_NAME.get(code, f"error_{code}")
        super().__init__(message or self.name)

    def __repr__(self) -> str:  # pragma: no cover
        return f"FdbError({self.code}, {self.name!r})"

    @property
    def is_retryable(self) -> bool:
        return self.code in _RETRYABLE


# Subset of reference flow/error_definitions.h codes used by this framework.
ERROR_CODES = {
    "success": 0,
    "end_of_stream": 1,
    "operation_failed": 1000,
    "wrong_shard_server": 1001,
    "timed_out": 1004,
    "coordinated_state_conflict": 1005,
    "future_version": 1009,
    "process_behind": 1037,
    "transaction_too_old": 1007,
    "not_committed": 1020,
    "commit_unknown_result": 1021,
    "transaction_cancelled": 1025,
    "accessed_unreadable": 1036,
    "transaction_timed_out": 1031,
    "broken_promise": 1100,
    "operation_cancelled": 1101,
    "future_released": 1102,
    "connection_failed": 1026,
    "request_maybe_delivered": 1034,
    "proxy_memory_limit_exceeded": 1042,
    "cluster_version_changed": 1039,
    "database_locked": 1038,
    "master_recovery_failed": 1201,
    "tlog_stopped": 1206,
    "worker_removed": 1202,
    "coordinators_changed": 1203,
    "please_reboot": 1207,
    "movekeys_conflict": 1208,
    # Disk faults (reference error_definitions.h: io_error 1510 is
    # process-fatal — fdbserver dies and gets re-recruited).
    "io_error": 1510,
    # Tenant errors (reference error_definitions.h 2130-2137).
    "tenant_name_required": 2130,
    "tenant_not_found": 2131,
    "tenant_already_exists": 2132,
    "tenant_not_empty": 2133,
    "invalid_tenant_name": 2134,
    "illegal_tenant_access": 2137,
    "transaction_too_large": 2101,
    "key_too_large": 2102,
    "value_too_large": 2103,
    "used_during_commit": 2017,
    "key_outside_legal_range": 2003,
    "inverted_range": 2005,
    "client_invalid_operation": 2000,
    "unknown_error": 4000,
    "internal_error": 4100,
}

_CODE_TO_NAME = {v: k for k, v in ERROR_CODES.items()}

# Per reference fdbclient/NativeAPI.actor.cpp onError(): these are the errors a
# client transaction retry loop handles by restarting the transaction.
_RETRYABLE = {
    ERROR_CODES["not_committed"],
    ERROR_CODES["transaction_too_old"],
    ERROR_CODES["future_version"],
    ERROR_CODES["commit_unknown_result"],
    ERROR_CODES["process_behind"],
    ERROR_CODES["request_maybe_delivered"],
    ERROR_CODES["cluster_version_changed"],
}


def err(name: str, message: str = "") -> FdbError:
    return FdbError(ERROR_CODES[name], name, message)


class ActorCancelled(BaseException):
    """Raised inside an actor coroutine when its future is cancelled.

    Derives from BaseException (like asyncio.CancelledError) so ordinary
    `except Exception` handlers do not swallow cancellation."""
