"""Deterministic event loop with virtual time (sim) or wall-clock (real).

The reference runs all logic of a process on ONE network thread: a reactor
with a priority-ordered ready queue plus timers (reference flow/Net2.actor.cpp
Net2::run :1400, TaskPriority ordering).  In simulation the same loop runs on
virtual time so whole clusters execute deterministically in-process
(reference fdbrpc/sim2.actor.cpp).

This loop keeps those properties:
  * single-threaded; all actors interleave only at awaits;
  * timers in a heap keyed (time, -priority, seq) -- seq makes ordering total
    and deterministic;
  * sim mode: time jumps to the next timer when the ready queue drains;
  * real mode: sleeps until the next timer.

JAX device dispatch happens inline on this thread (host-blocking); the TPU
conflict backend pipelines device work across commit batches the same way the
reference overlaps commit batches across pipeline stages.
"""

from __future__ import annotations

import heapq
import time as _time
from enum import IntEnum
from typing import Callable, List, Optional

from .error import err
from .futures import ActorTask, Future, Promise


class TaskPriority(IntEnum):
    """Subset of reference flow/network.h TaskPriority (higher runs first)."""

    Max = 1000000
    RunLoop = 30000
    CoordinationReply = 8810
    Coordination = 8800
    FailureMonitor = 8700
    ResolutionMetrics = 8700
    ClusterController = 8650
    MasterTLogRejoin = 8646
    ProxyCommitDispatcher = 8640
    TLogQueuingMetrics = 8620
    TLogPop = 8610
    TLogPeekReply = 8600
    TLogPeek = 8590
    TLogCommitReply = 8580
    TLogCommit = 8570
    ProxyGetRawCommittedVersion = 8565
    ProxyResolverReply = 8560
    ProxyCommit = 8540
    ProxyCommitBatcher = 8530
    TLogConfirmRunningReply = 8520
    TLogConfirmRunning = 8510
    ProxyGRVTimer = 8505
    GetConsistentReadVersion = 8500
    DefaultPromiseEndpoint = 8000
    DefaultOnMainThread = 7500
    DefaultDelay = 7010
    DefaultYield = 7000
    DiskRead = 5010
    DefaultEndpoint = 5000
    UnknownEndpoint = 4000
    FetchKeys = 3910
    MoveKeys = 3550
    DataDistribution = 3500
    StorageServer = 3000
    UpdateStorage = 3000
    DefaultLowPriority = 2000
    Low = 1
    Zero = 0


class EventLoop:
    """One logical process thread; the only scheduler in the framework."""

    def __init__(self, sim: bool = True, start_time: float = 0.0) -> None:
        self.sim = sim
        self._time = start_time
        self._epoch_real = _time.monotonic() - start_time
        self._heap: List = []  # (time, -priority, seq, fn)
        # Tie-break sequence for heap entries.  An itertools.count, not
        # `self._seq += 1`: call_at can be reached from outside the
        # reactor thread (threadpool completions, __del__-driven
        # broken-promise delivery runs on whatever thread GC happens to
        # use), and a racy read-modify-write can mint DUPLICATE seqs —
        # heapq then falls through to comparing the callback functions
        # (TypeError, observed as a once-per-thousand-runs suite crash).
        # count.__next__ is a single C call, atomic under the GIL.
        import itertools
        self._seq_counter = itertools.count(1)
        # Unseed verification (core/rng.py RunDigest): in sim mode every
        # dispatched (virtual time, task seq) folds into the run digest,
        # making the SCHEDULE itself part of the reproducibility witness.
        # Bound at construction: this loop belongs to the digest that was
        # current when its world was built.
        from .rng import run_digest
        self._run_digest = run_digest() if sim else None
        self._tasks: set = set()
        self._stopped = False
        # Real-IO reactor half (reference Net2: boost::asio reactor fused
        # with the task queue, Net2.actor.cpp:1400 Net2::run).  Only used
        # in real mode; sim mode has no file descriptors by construction.
        self._selector = None
        self._io_cbs: dict = {}   # fd -> [reader_cb, writer_cb]
        # Optional instrumentation wrapper around each dispatched callback
        # (core/profiler.py slow-task detection): receives the callable,
        # must invoke it.  Only callback EXECUTION goes through it — idle
        # sleeps and selector waits do not.
        self.callback_hook = None

    def _dispatch(self, fn) -> None:
        if self.callback_hook is None:
            fn()
        else:
            self.callback_hook(fn)

    # -- real-IO reactor (real mode only) ------------------------------------
    def _sel(self):
        if self._selector is None:
            import selectors
            self._selector = selectors.DefaultSelector()
        return self._selector

    def _io_update(self, fileobj) -> None:
        import selectors
        sel = self._sel()
        cbs = self._io_cbs.get(fileobj)
        mask = 0
        if cbs is not None:
            if cbs[0] is not None:
                mask |= selectors.EVENT_READ
            if cbs[1] is not None:
                mask |= selectors.EVENT_WRITE
        try:
            if mask == 0:
                self._io_cbs.pop(fileobj, None)
                sel.unregister(fileobj)
            else:
                sel.modify(fileobj, mask, fileobj)
        except KeyError:
            if mask:
                sel.register(fileobj, mask, fileobj)

    def add_reader(self, fileobj, cb: Callable[[], None]) -> None:
        self._io_cbs.setdefault(fileobj, [None, None])[0] = cb
        self._io_update(fileobj)

    def remove_reader(self, fileobj) -> None:
        if fileobj in self._io_cbs:
            self._io_cbs[fileobj][0] = None
            self._io_update(fileobj)

    def add_writer(self, fileobj, cb: Callable[[], None]) -> None:
        self._io_cbs.setdefault(fileobj, [None, None])[1] = cb
        self._io_update(fileobj)

    def remove_writer(self, fileobj) -> None:
        if fileobj in self._io_cbs:
            self._io_cbs[fileobj][1] = None
            self._io_update(fileobj)

    def _poll_io(self, timeout: Optional[float]) -> bool:
        """Wait up to `timeout` for IO readiness; dispatch callbacks.
        Returns True if any callback ran."""
        import selectors
        events = self._sel().select(timeout)
        ran = False
        for key, mask in events:
            cbs = self._io_cbs.get(key.fileobj)
            if cbs is None:
                continue
            if (mask & selectors.EVENT_READ) and cbs[0] is not None:
                self._dispatch(cbs[0])
                ran = True
            if (mask & selectors.EVENT_WRITE) and cbs[1] is not None:
                self._dispatch(cbs[1])
                ran = True
        return ran

    # -- time ---------------------------------------------------------------
    def now(self) -> float:
        if self.sim:
            return self._time
        return _time.monotonic() - self._epoch_real

    # -- scheduling primitives ---------------------------------------------
    def call_at(self, when: float, fn: Callable[[], None],
                priority: TaskPriority = TaskPriority.DefaultDelay) -> None:
        heapq.heappush(self._heap,
                       (when, -int(priority), next(self._seq_counter), fn))

    def call_soon(self, fn: Callable[[], None],
                  priority: TaskPriority = TaskPriority.DefaultYield) -> None:
        self.call_at(self.now(), fn, priority)

    def delay(self, seconds: float,
              priority: TaskPriority = TaskPriority.DefaultDelay) -> Future:
        p: Promise = Promise()
        self.call_at(self.now() + seconds, lambda: p.send(None), priority)
        return p.get_future()

    def yield_now(self, priority: TaskPriority = TaskPriority.DefaultYield) -> Future:
        return self.delay(0.0, priority)

    # -- actors -------------------------------------------------------------
    def spawn(self, coro, name: str = "") -> Future:
        """Start an actor; returns its Future. Cancelling the Future cancels it."""
        task = ActorTask(coro, self, name)
        self._tasks.add(task)
        self.call_soon(task._initial_step)
        return task.future

    def _task_done(self, task: ActorTask) -> None:
        self._tasks.discard(task)

    # -- running ------------------------------------------------------------
    def run_until(self, future: Future, timeout: Optional[float] = None) -> object:
        """Drive the loop until `future` resolves; returns its value/raises."""
        deadline = None if timeout is None else self.now() + timeout
        while not future.is_ready():
            if not self._step_once(deadline):
                if future.is_ready():
                    break
                if deadline is not None and (not self._heap or self._heap[0][0] > deadline):
                    raise err("timed_out",
                              f"run_until timed out at t={self.now():.3f}")
                # Queue drained with no timeout: this is a deadlock, not a timeout.
                raise err("internal_error",
                          f"event loop drained at t={self.now():.3f} with future "
                          "still pending (deadlocked or orphaned future)")
        return future.get()

    def run_for(self, seconds: float) -> None:
        """Advance simulation by `seconds` of virtual time."""
        end = self.now() + seconds
        while self._heap and self._heap[0][0] <= end:
            self._step_once(None)
        if self.sim and self._time < end:
            self._time = end

    def _step_once(self, deadline: Optional[float]) -> bool:
        """Run one scheduled callback (or a batch of ready IO callbacks in
        real mode); returns False if nothing to run before `deadline`."""
        if self.sim:
            if not self._heap:
                return False
            when, negprio, seq, fn = self._heap[0]
            if deadline is not None and when > deadline:
                self._time = deadline
                return False
            heapq.heappop(self._heap)
            if when > self._time:
                self._time = when
            self._run_digest.fold_task(when, seq)
            self._dispatch(fn)
            return True
        # Real mode: fuse the timer heap with the IO reactor.  Wait for
        # whichever comes first — the next timer, the deadline, or IO
        # readiness — dispatching IO as it arrives (reference Net2::run).
        has_io = bool(self._io_cbs)
        while True:
            now = self.now()
            when = self._heap[0][0] if self._heap else None
            if when is not None and when <= now:
                break                       # a timer is due: run it below
            target = when
            if deadline is not None:
                target = deadline if target is None else min(target, deadline)
            if not has_io:
                if when is None:
                    return False            # no work at all
                if deadline is not None and when > deadline:
                    return False            # nothing before the deadline
                _time.sleep(when - now)
                break
            timeout = None if target is None else max(0.0, target - now)
            if self._poll_io(timeout):
                return True                 # IO callbacks ran (may schedule)
            if deadline is not None and self.now() >= deadline \
                    and (when is None or when > deadline):
                return False
            if when is None:
                continue                    # pure-IO loop: keep waiting
        when, negprio, seq, fn = heapq.heappop(self._heap)
        self._dispatch(fn)
        return True

    def stop(self) -> None:
        self._stopped = True

    def run_forever(self) -> None:
        """Serve until stop(): the real-mode process main loop."""
        self._stopped = False
        while not self._stopped:
            if not self._step_once(None):
                if not self._io_cbs and not self._heap:
                    return   # truly no work left and no IO sources


    def drain(self, max_steps: int = 10_000_000) -> None:
        """Run until no work remains (sim only)."""
        steps = 0
        while self._step_once(None):
            steps += 1
            if steps >= max_steps:
                raise err("internal_error", "EventLoop.drain exceeded max_steps")

    def shutdown(self) -> None:
        """Close actors that were spawned but never stepped.  A discarded
        loop (workload teardown, cluster restart) can hold ActorTasks whose
        _initial_step never ran; their coroutine objects would emit
        "coroutine ... was never awaited" RuntimeWarnings at GC — exactly
        where a dropped-callback liveness bug would hide, so the teardown
        path must be warning-clean by construction.  Started actors are
        left alone: their coroutines have begun and GC handles them
        silently."""
        for task in list(self._tasks):
            if not task._started and not task._finished:
                task._finished = True
                try:
                    task.coro.close()
                except Exception:  # noqa: BLE001 — teardown is best-effort
                    pass
        self._tasks.clear()


# ---------------------------------------------------------------------------
# Global current-loop access (the reference's g_network equivalent)
# ---------------------------------------------------------------------------

_current: Optional[EventLoop] = None


def set_event_loop(loop: Optional[EventLoop]) -> None:
    """Install `loop` as the current reactor.  A DIFFERENT loop being
    replaced is shut down (see EventLoop.shutdown): the old world is dead,
    and its never-started actors must not leak warning-emitting coroutine
    objects into the new one's run."""
    global _current
    old, _current = _current, loop
    if old is not None and old is not loop:
        old.shutdown()


def get_event_loop() -> EventLoop:
    if _current is None:
        raise err("internal_error", "no EventLoop installed (set_event_loop first)")
    return _current


def current_event_loop_or_none() -> Optional[EventLoop]:
    """The installed loop, or None — for callbacks that may fire from the
    garbage collector after their world was torn down."""
    return _current


def now() -> float:
    return get_event_loop().now()


def delay(seconds: float, priority: TaskPriority = TaskPriority.DefaultDelay) -> Future:
    return get_event_loop().delay(seconds, priority)


def yield_now(priority: TaskPriority = TaskPriority.DefaultYield) -> Future:
    return get_event_loop().yield_now(priority)


def spawn(coro, name: str = "") -> Future:
    return get_event_loop().spawn(coro, name)


class PollBackoff:
    """Adaptive poll interval for wait-until-condition loops: starts at
    `base`, doubles after every empty (no-progress) poll up to `cap`, and
    resets to `base` on progress.  The DR surface's shared pacing
    (knobs DR_POLL_INTERVAL_S / DR_POLL_MAX_INTERVAL_S): a converged
    plane is re-checked at the cap, not the hot interval, bounding the
    dispatch volume a long wait adds to a chaos run — the same fix the
    GRV transaction starter got for its starved-queue polling.

        pb = PollBackoff(knobs.DR_POLL_INTERVAL_S,
                         knobs.DR_POLL_MAX_INTERVAL_S)
        while not condition():
            await delay(pb.next())
        ...
        pb.reset()          # on observed progress
    """

    __slots__ = ("base", "cap", "_cur", "polls")

    def __init__(self, base: float, cap: Optional[float] = None) -> None:
        self.base = float(base)
        self.cap = float(cap if cap is not None else base)
        self._cur = self.base
        self.polls = 0          # empty polls so far (observability/tests)

    def next(self) -> float:
        """The interval to sleep before the next poll; doubles the one
        after it (call reset() when a poll observes progress)."""
        cur = self._cur
        self._cur = min(self._cur * 2.0, self.cap)
        self.polls += 1
        return cur

    def reset(self) -> None:
        self._cur = self.base
