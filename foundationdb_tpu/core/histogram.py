"""Log-scale latency histograms + counter collections with periodic
trace emission.

Reference: flow/Histogram.h:59 (32-bucket power-of-two histogram; the
commit path hangs them off every stage, CommitProxyServer.actor.cpp:403-409)
and fdbrpc/Stats.h:70-183 (Counter/CounterCollection + traceCounters'
periodic rate emission).  These feed the status JSON's latency_statistics
and the north-star p50 resolve tracking.
"""

from __future__ import annotations

from typing import Dict, List, Optional

_N_BUCKETS = 40
_BASE = 1e-6          # bucket 0 upper bound: 1us; bucket i: 1us * 2^i


class Histogram:
    """Power-of-two log-scale histogram of seconds (reference Histogram.h).

    Bucket i counts samples in (BASE*2^(i-1), BASE*2^i]; percentiles are
    bucket upper bounds (exact enough for p50/p95/p99 reporting).

    Two tiers: the CURRENT INTERVAL (buckets/count/... below, what the
    periodic LatencyBand emission reports and then roll()s away) and a
    lifetime ACCUMULATOR of rolled intervals — snapshot()/to_status()
    merge both, so status percentiles always reflect the full
    distribution regardless of the emission cadence."""

    def __init__(self, group: str = "", op: str = "") -> None:
        self.group = group
        self.op = op
        self.buckets = [0] * _N_BUCKETS
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max = 0.0
        from .metrics import HistogramSnapshot
        self._accumulated = HistogramSnapshot()

    def record(self, seconds: float) -> None:
        self.count += 1
        self.total += seconds
        self.max = max(self.max, seconds)
        self.min = seconds if self.min is None else min(self.min, seconds)
        i = 0
        bound = _BASE
        while seconds > bound and i < _N_BUCKETS - 1:
            bound *= 2
            i += 1
        self.buckets[i] += 1

    def snapshot(self):
        """Mergeable lifetime snapshot (accumulated intervals + the
        current one) — the aggregation currency of core/metrics.py."""
        from .metrics import HistogramSnapshot
        return HistogramSnapshot(
            self._accumulated.buckets, self._accumulated.count,
            self._accumulated.total, self._accumulated.min,
            self._accumulated.max).merge(HistogramSnapshot(
                self.buckets, self.count, self.total, self.min, self.max))

    def roll(self):
        """Fold the current interval into the lifetime accumulator and
        reset it; returns the interval's snapshot (what one LatencyBand
        emission reports)."""
        from .metrics import HistogramSnapshot
        interval = HistogramSnapshot(self.buckets, self.count, self.total,
                                     self.min, self.max)
        self._accumulated.merge(interval)
        self.buckets = [0] * _N_BUCKETS
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = 0.0
        return interval

    def percentile(self, p: float) -> float:
        """Upper bound of the bucket containing the p-quantile (0..1),
        over the LIFETIME distribution."""
        return self.snapshot().percentile(p)

    @property
    def mean(self) -> float:
        s = self.snapshot()
        return s.mean

    def to_status(self) -> Dict[str, float]:
        """The status-JSON latency_statistics shape (reference
        mr-status latency_statistics docs)."""
        return self.snapshot().to_status()

    def clear(self) -> None:
        from .metrics import HistogramSnapshot
        self.buckets = [0] * _N_BUCKETS
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = 0.0
        self._accumulated = HistogramSnapshot()


class Counter:
    """Monotonic counter with rate-since-last-emission (Stats.h:70)."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.value = 0
        self._last_value = 0

    def add(self, n: int = 1) -> None:
        self.value += n

    def rate_and_roll(self, dt: float) -> float:
        d = self.value - self._last_value
        self._last_value = self.value
        return d / dt if dt > 0 else 0.0


class CounterCollection:
    """Named counters + histograms for one role instance; emit() traces
    rates on a cadence (reference traceCounters, Stats.h:183)."""

    def __init__(self, group: str, role_id: str) -> None:
        self.group = group
        self.role_id = role_id
        self.counters: Dict[str, Counter] = {}
        self.histograms: Dict[str, Histogram] = {}
        # Every collection is visible to the process-wide registry (weakly
        # — it dies with the owning role) so status / fdbcli `metrics` can
        # aggregate without threading references through every layer.
        from .metrics import get_metrics_registry
        get_metrics_registry().register(self)

    def counter(self, name: str) -> Counter:
        c = self.counters.get(name)
        if c is None:
            c = self.counters[name] = Counter(name)
        return c

    def histogram(self, name: str) -> Histogram:
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = Histogram(self.group, name)
        return h

    async def emit_loop(self, interval: Optional[float] = None) -> None:
        """The traceCounters actor: periodic {group}Metrics + LatencyBand
        emission (core/metrics.emit_collection); cadence from the
        METRICS_EMIT_INTERVAL knob unless overridden."""
        from .knobs import server_knobs
        from .metrics import emit_collection
        from .scheduler import delay, now
        last = now()
        while True:
            # Knob re-read per tick (when not explicitly overridden) so a
            # dynamic METRICS_EMIT_INTERVAL change applies to running
            # roles without a restart.
            await delay(interval if interval is not None
                        else float(server_knobs().METRICS_EMIT_INTERVAL))
            t = now()
            dt = t - last
            last = t
            emit_collection(self, dt)

    def to_status(self) -> Dict[str, object]:
        return {
            "counters": {n: c.value for n, c in self.counters.items()},
            "latency_statistics": {n: h.to_status()
                                   for n, h in self.histograms.items()},
        }
