"""Log-scale latency histograms + counter collections with periodic
trace emission.

Reference: flow/Histogram.h:59 (32-bucket power-of-two histogram; the
commit path hangs them off every stage, CommitProxyServer.actor.cpp:403-409)
and fdbrpc/Stats.h:70-183 (Counter/CounterCollection + traceCounters'
periodic rate emission).  These feed the status JSON's latency_statistics
and the north-star p50 resolve tracking.
"""

from __future__ import annotations

from typing import Dict, List, Optional

_N_BUCKETS = 40
_BASE = 1e-6          # bucket 0 upper bound: 1us; bucket i: 1us * 2^i


class Histogram:
    """Power-of-two log-scale histogram of seconds (reference Histogram.h).

    Bucket i counts samples in (BASE*2^(i-1), BASE*2^i]; percentiles are
    bucket upper bounds (exact enough for p50/p95/p99 reporting)."""

    def __init__(self, group: str = "", op: str = "") -> None:
        self.group = group
        self.op = op
        self.buckets = [0] * _N_BUCKETS
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max = 0.0

    def record(self, seconds: float) -> None:
        self.count += 1
        self.total += seconds
        self.max = max(self.max, seconds)
        self.min = seconds if self.min is None else min(self.min, seconds)
        i = 0
        bound = _BASE
        while seconds > bound and i < _N_BUCKETS - 1:
            bound *= 2
            i += 1
        self.buckets[i] += 1

    def percentile(self, p: float) -> float:
        """Upper bound of the bucket containing the p-quantile (0..1)."""
        if self.count == 0:
            return 0.0
        target = max(1, int(self.count * p))
        acc = 0
        bound = _BASE
        for i, c in enumerate(self.buckets):
            acc += c
            if acc >= target:
                return bound
            bound *= 2
        return bound

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def to_status(self) -> Dict[str, float]:
        """The status-JSON latency_statistics shape (reference
        mr-status latency_statistics docs)."""
        return {"count": self.count, "mean": self.mean,
                "min": self.min or 0.0, "max": self.max,
                "p50": self.percentile(0.50),
                "p95": self.percentile(0.95),
                "p99": self.percentile(0.99)}

    def clear(self) -> None:
        self.buckets = [0] * _N_BUCKETS
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = 0.0


class Counter:
    """Monotonic counter with rate-since-last-emission (Stats.h:70)."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.value = 0
        self._last_value = 0

    def add(self, n: int = 1) -> None:
        self.value += n

    def rate_and_roll(self, dt: float) -> float:
        d = self.value - self._last_value
        self._last_value = self.value
        return d / dt if dt > 0 else 0.0


class CounterCollection:
    """Named counters + histograms for one role instance; emit() traces
    rates on a cadence (reference traceCounters, Stats.h:183)."""

    def __init__(self, group: str, role_id: str) -> None:
        self.group = group
        self.role_id = role_id
        self.counters: Dict[str, Counter] = {}
        self.histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        c = self.counters.get(name)
        if c is None:
            c = self.counters[name] = Counter(name)
        return c

    def histogram(self, name: str) -> Histogram:
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = Histogram(self.group, name)
        return h

    async def emit_loop(self, interval: float = 5.0) -> None:
        """Periodic TraceEvent with each counter's rate and histogram p50s
        (the reference's traceCounters actor)."""
        from .scheduler import delay, now
        from .trace import TraceEvent
        last = now()
        while True:
            await delay(interval)
            t = now()
            dt = t - last
            last = t
            ev = TraceEvent(f"{self.group}Metrics").detail(
                "Id", self.role_id).detail("Elapsed", round(dt, 3))
            for name, c in self.counters.items():
                ev.detail(name, c.value).detail(
                    f"{name}PerSec", round(c.rate_and_roll(dt), 2))
            for name, h in self.histograms.items():
                ev.detail(f"{name}P50", h.percentile(0.50)).detail(
                    f"{name}P99", h.percentile(0.99))
                # Reference Histogram::writeToLog clears on emission so
                # each report (and to_status) reflects the current
                # interval, not a lifetime-diluted distribution.
                h.clear()
            ev.log()

    def to_status(self) -> Dict[str, object]:
        return {
            "counters": {n: c.value for n, c in self.counters.items()},
            "latency_statistics": {n: h.to_status()
                                   for n, h in self.histograms.items()},
        }
