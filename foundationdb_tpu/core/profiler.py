"""Sampling profiler + slow-task detection (reference flow/Profiler.actor.cpp
:100 SIGPROF sampler and Net2's slow-task TraceEvents).

Two production observability tools for real deployments:

- SlowTask detection: the reactor times every callback it dispatches
  (install_slow_task_detection hooks EventLoop._dispatch below); one that
  holds the loop beyond the threshold emits a SlowTask TraceEvent with
  the callback's name — the single-threaded reactor means every such
  stall delays every connection of the process (the reason the blocking
  work offload in core/threadpool.py exists; this is the tool that FINDS
  offenders).

- SamplingProfiler: a daemon thread sampling the reactor thread's stack
  at a fixed interval (sys._current_frames, the in-process analog of the
  reference's SIGPROF handler writing profile.bin).  report() aggregates
  samples into (stack, count) hot spots; fdbserver enables it with
  --profile / FDB_PROFILE=1 and dumps the top stacks to the trace log on
  shutdown or on demand.
"""

from __future__ import annotations

import threading
import time
import traceback
from collections import Counter
from typing import List, Optional, Tuple

from .trace import Severity, TraceEvent

SLOW_TASK_THRESHOLD_S = 0.25


def install_slow_task_detection(loop, threshold_s: Optional[float] = None
                                ) -> None:
    """Time each dispatched CALLBACK (via EventLoop.callback_hook — idle
    sleeps and selector waits are not counted) and emit a SlowTask
    TraceEvent when one holds the reactor past the threshold (the
    SLOW_TASK_THRESHOLD_S knob unless overridden).  Installed by default
    at worker startup — sim and real clusters both get SlowTask events
    without a test wiring it."""
    if threshold_s is None:
        from .knobs import get_knobs
        threshold_s = float(getattr(get_knobs().flow, "SLOW_TASK_THRESHOLD_S",
                                    SLOW_TASK_THRESHOLD_S))
    if getattr(loop, "_slow_task_installed", False):
        return
    loop._slow_task_installed = True

    def timing_hook(fn):
        t0 = time.monotonic()
        fn()
        dt = time.monotonic() - t0
        if dt > threshold_s:
            TraceEvent("SlowTask", Severity.Warn).detail(
                "DurationMs", round(dt * 1e3, 1)).detail(
                "ThresholdMs", round(threshold_s * 1e3, 1)).detail(
                "Callback", getattr(fn, "__qualname__", repr(fn))[:80]
            ).log()

    loop.callback_hook = timing_hook


class SamplingProfiler:
    def __init__(self, interval_s: float = 0.01,
                 target_thread: Optional[int] = None) -> None:
        self.interval_s = interval_s
        self.target = target_thread or threading.main_thread().ident
        self.samples: Counter = Counter()
        self.total = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Guards samples: report() on the reactor thread vs inserts on
        # the sampler thread ("dict changed size during iteration").
        self._lock = threading.Lock()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fdb-profiler")
        self._thread.start()

    def _run(self) -> None:
        import sys
        while not self._stop.wait(self.interval_s):
            frame = sys._current_frames().get(self.target)
            if frame is None:
                continue
            stack = tuple(
                f"{fr.f_code.co_filename.rsplit('/', 1)[-1]}:"
                f"{fr.f_code.co_name}:{lineno}"
                for fr, lineno in traceback.walk_stack(frame))
            # Innermost first, capped: deep actor stacks all share the
            # scheduler root frames.
            with self._lock:
                self.samples[stack[:12]] += 1
                self.total += 1

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=2.0)
        self._thread = None

    def report(self, top: int = 10) -> List[Tuple[float, str]]:
        """[(fraction_of_samples, 'inner<-outer stack'), ...]"""
        with self._lock:
            snapshot = Counter(self.samples)
            total = self.total
        out = []
        for stack, n in snapshot.most_common(top):
            out.append((n / max(total, 1), " <- ".join(stack[:5])))
        return out

    def log_report(self, top: int = 10) -> None:
        for frac, stack in self.report(top):
            TraceEvent("ProfilerHotStack").detail(
                "Fraction", round(frac, 4)).detail("Stack", stack).log()


# One profiler per OS process: worker startup calls maybe_start_profiler
# from every hosted role's process, but only the first call (with
# FDB_PROFILE=1) actually starts the sampling thread.
_profiler: Optional[SamplingProfiler] = None


def maybe_start_profiler(spawn=None, dump_interval_s: float = 30.0
                         ) -> Optional[SamplingProfiler]:
    """Start the process-wide SamplingProfiler when FDB_PROFILE=1
    (reference --profile / Profiler.actor.cpp); idempotent.  With `spawn`
    (an actor-spawning callable) a periodic hot-stack dump actor is also
    started so long-running servers trace their profile without being
    asked."""
    import os
    global _profiler
    if os.environ.get("FDB_PROFILE") != "1":
        return None
    if _profiler is not None:
        return _profiler
    _profiler = SamplingProfiler()
    _profiler.start()
    if spawn is not None:
        async def _dump() -> None:
            from .scheduler import delay
            while True:
                await delay(dump_interval_s)
                _profiler.log_report()
        spawn(_dump(), "profiler.dump")
    return _profiler
