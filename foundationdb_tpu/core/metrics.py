"""Process-wide metrics registry: mergeable histogram snapshots and the
periodic Metrics/LatencyBand trace emission shared by every role.

Reference: fdbrpc/Stats.h — traceCounters (:183) is the per-role actor
emitting counter rates on a cadence; LatencyBands (:240) publishes
latency percentiles per request class; Status.actor.cpp aggregates the
per-role histograms into the status document's latency_statistics.

Design here:

* every CounterCollection (core/histogram.py) registers itself into the
  process-wide MetricsRegistry on construction (weakly — a dead role's
  collection vanishes with the role object);
* ``HistogramSnapshot`` is the MERGEABLE value type: bucket counts +
  count/total/min/max, closed under ``merge`` so status can aggregate one
  latency band across all instances of a role (and, in simulation, across
  the whole cluster living in one process);
* ``emit_collection`` is the traceCounters body: one ``{group}Metrics``
  event with counter values + rates, and one ``LatencyBand`` event per
  histogram that saw samples this interval (p50/p95/p99 + rate).  The hot
  path only bumps counters / histogram buckets — TraceEvents happen ONLY
  here, on the periodic cadence (METRICS_EMIT_INTERVAL knob).
"""

from __future__ import annotations

import weakref
from typing import Dict, Iterable, List, Optional

_N_BUCKETS = 40
_BASE = 1e-6          # bucket 0 upper bound: 1us; bucket i: 1us * 2^i


class HistogramSnapshot:
    """Immutable-ish, mergeable view of a log-scale histogram (the wire /
    aggregation shape of core/histogram.Histogram)."""

    __slots__ = ("buckets", "count", "total", "min", "max")

    def __init__(self, buckets: Optional[List[int]] = None, count: int = 0,
                 total: float = 0.0, min_: Optional[float] = None,
                 max_: float = 0.0) -> None:
        self.buckets = list(buckets) if buckets is not None \
            else [0] * _N_BUCKETS
        self.count = count
        self.total = total
        self.min = min_
        self.max = max_

    def merge(self, other: "HistogramSnapshot") -> "HistogramSnapshot":
        """Fold `other` into self (in place; returns self for chaining).
        Exact for everything a log-scale histogram can be exact about:
        bucket counts/total/max add and combine losslessly."""
        for i, c in enumerate(other.buckets):
            self.buckets[i] += c
        self.count += other.count
        self.total += other.total
        self.max = max(self.max, other.max)
        if other.min is not None:
            self.min = other.min if self.min is None \
                else min(self.min, other.min)
        return self

    @classmethod
    def merged(cls, snaps: Iterable["HistogramSnapshot"]
               ) -> "HistogramSnapshot":
        out = cls()
        for s in snaps:
            out.merge(s)
        return out

    def percentile(self, p: float) -> float:
        """Upper bound of the bucket containing the p-quantile (0..1),
        nearest-rank (ceil) so small counts behave: p99 of 2 samples is
        the 2nd, not the 1st.  Merged snapshots report the same value a
        single histogram holding all samples would."""
        if self.count == 0:
            return 0.0
        import math
        target = min(max(1, math.ceil(self.count * p)), self.count)
        acc = 0
        bound = _BASE
        for c in self.buckets:
            acc += c
            if acc >= target:
                return bound
            bound *= 2
        return bound

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def to_status(self) -> Dict[str, float]:
        return {"count": self.count, "mean": self.mean,
                "min": self.min or 0.0, "max": self.max,
                "p50": self.percentile(0.50),
                "p95": self.percentile(0.95),
                "p99": self.percentile(0.99)}

    def to_wire(self) -> Dict[str, object]:
        """Plain-data form (rides RegisterWorkerRequest.metrics_doc so a
        real cluster's status builder can merge remote snapshots)."""
        return {"buckets": list(self.buckets), "count": self.count,
                "total": self.total, "min": self.min, "max": self.max}

    @classmethod
    def from_wire(cls, d: Dict[str, object]) -> "HistogramSnapshot":
        return cls(d.get("buckets"), int(d.get("count", 0)),
                   float(d.get("total", 0.0)), d.get("min"),
                   float(d.get("max", 0.0)))


class MetricsRegistry:
    """All live CounterCollections of this process, weakly held.

    In simulation the whole cluster shares one Python process, so the
    registry sees every role of every simulated machine — which is exactly
    what cluster-wide aggregation wants.  In a real deployment each
    fdbserver process has its own registry and the status builder merges
    role snapshots it can reach (server/status.py)."""

    def __init__(self) -> None:
        self._collections: "weakref.WeakSet" = weakref.WeakSet()

    def register(self, collection) -> None:
        self._collections.add(collection)

    def collections(self, group: Optional[str] = None) -> List:
        out = [c for c in self._collections
               if group is None or c.group == group]
        out.sort(key=lambda c: (c.group, c.role_id))
        return out

    def merged_histogram(self, group: str, name: str) -> HistogramSnapshot:
        """One latency band merged across every live instance of `group`
        (lifetime samples, not just the current emission interval)."""
        return HistogramSnapshot.merged(
            c.histograms[name].snapshot()
            for c in self.collections(group) if name in c.histograms)

    def aggregate_counters(self) -> Dict[str, Dict[str, int]]:
        """{group: {counter: summed value}} across all live collections."""
        out: Dict[str, Dict[str, int]] = {}
        for c in self.collections():
            g = out.setdefault(c.group, {})
            for name, counter in c.counters.items():
                g[name] = g.get(name, 0) + counter.value
        return out

    def export(self) -> Dict[str, object]:
        """Plain-data snapshot of every group (counter sums + lifetime
        histogram wires) — what a real-mode worker attaches to its
        periodic CC registration so the status builder can aggregate
        bands across processes it has no object references into."""
        out: Dict[str, object] = {}
        for c in self.collections():
            g = out.setdefault(c.group, {"counters": {}, "histograms": {}})
            for name, counter in c.counters.items():
                g["counters"][name] = \
                    g["counters"].get(name, 0) + counter.value
            for name, h in c.histograms.items():
                snap = h.snapshot()
                prev = g["histograms"].get(name)
                if prev is not None:
                    snap = HistogramSnapshot.from_wire(prev).merge(snap)
                g["histograms"][name] = snap.to_wire()
        return out

    def to_status(self) -> Dict[str, object]:
        """The cluster.metrics status shape: per-group counter sums plus
        merged latency bands for every histogram name seen in a group."""
        doc: Dict[str, object] = {}
        for c in self.collections():
            g = doc.setdefault(c.group, {"counters": {},
                                         "latency_statistics": {}})
            for name, counter in c.counters.items():
                g["counters"][name] = \
                    g["counters"].get(name, 0) + counter.value
        for group, g in doc.items():
            names = set()
            for c in self.collections(group):
                names.update(c.histograms)
            g["latency_statistics"] = {
                name: self.merged_histogram(group, name).to_status()
                for name in sorted(names)}
        return doc


_registry = MetricsRegistry()


def get_metrics_registry() -> MetricsRegistry:
    return _registry


def set_metrics_registry(r: MetricsRegistry) -> MetricsRegistry:
    """Install a fresh registry (tests); returns the previous one."""
    global _registry
    prev = _registry
    _registry = r
    return prev


def emit_collection(coll, dt: float) -> None:
    """One traceCounters tick for `coll`: a ``{group}Metrics`` event with
    values + rates, then one ``LatencyBand`` event per histogram that saw
    samples this interval.  Rolls each histogram's interval into its
    lifetime accumulator (so to_status()/snapshot() keep the full
    distribution while each LatencyBand reflects only its interval)."""
    from .trace import TraceEvent
    ev = TraceEvent(f"{coll.group}Metrics").detail(
        "Id", coll.role_id).detail("Elapsed", round(dt, 3))
    for name, c in coll.counters.items():
        ev.detail(name, c.value).detail(
            f"{name}PerSec", round(c.rate_and_roll(dt), 2))
    for name, h in coll.histograms.items():
        interval = h.roll()
        if interval.count == 0:
            continue           # idle op: no event (trace hygiene)
        TraceEvent("LatencyBand").detail("Group", coll.group).detail(
            "Id", coll.role_id).detail("Op", name).detail(
            "Count", interval.count).detail(
            "PerSec", round(interval.count / dt, 2) if dt > 0 else 0.0
        ).detail("Mean", round(interval.mean, 6)).detail(
            "P50", interval.percentile(0.50)).detail(
            "P95", interval.percentile(0.95)).detail(
            "P99", interval.percentile(0.99)).detail(
            "Max", round(interval.max, 6)).log()
    ev.log()
