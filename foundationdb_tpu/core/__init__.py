"""Core runtime: futures/actors, deterministic event loop, RNG, knobs, trace.

Equivalent layer to the reference's flow/ (see SURVEY.md L0)."""

from .error import ActorCancelled, ERROR_CODES, FdbError, err
from .futures import (AsyncTrigger, AsyncVar, Future, FutureStream, Promise,
                      PromiseStream, error_future, map_future, quorum,
                      ready_future, wait_all, wait_any)
from .scheduler import (EventLoop, TaskPriority, delay, get_event_loop, now,
                        set_event_loop, spawn, yield_now)
from .rng import (DeterministicRandom, deterministic_random,
                  nondeterministic_random, set_deterministic_random)
from .buggify import buggify, buggify_enabled, enable_buggify
from .trace import Severity, TraceEvent, Tracer, get_tracer, set_tracer
from .knobs import (ClientKnobs, Knobs, ServerKnobs, client_knobs, get_knobs,
                    server_knobs, set_knobs)

__all__ = [
    "ActorCancelled", "ERROR_CODES", "FdbError", "err",
    "AsyncTrigger", "AsyncVar", "Future", "FutureStream", "Promise",
    "PromiseStream", "error_future", "map_future", "quorum", "ready_future",
    "wait_all", "wait_any",
    "EventLoop", "TaskPriority", "delay", "get_event_loop", "now",
    "set_event_loop", "spawn", "yield_now",
    "DeterministicRandom", "deterministic_random", "nondeterministic_random",
    "set_deterministic_random",
    "buggify", "buggify_enabled", "enable_buggify",
    "Severity", "TraceEvent", "Tracer", "get_tracer", "set_tracer",
    "ClientKnobs", "Knobs", "ServerKnobs", "client_knobs", "get_knobs",
    "server_knobs", "set_knobs",
]
