"""Binary wire-format primitives: length-prefixed, little-endian.

Reference: flow/serialize.h — the "classic" serializer writes fields in
declaration order as fixed-width little-endian integers and length-prefixed
byte strings, producing a byte-order-stable format shared by the transport
and every durable file (DiskQueue payloads, coordinated state).  This module
is the Python analog: an explicit Writer/Reader pair (no reflection, no
pickling) used by TLog commit records, DBCoreState, and the RPC wire format.
"""

from __future__ import annotations

import struct

_U8 = struct.Struct("<B")
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_I64 = struct.Struct("<q")


class Writer:
    def __init__(self) -> None:
        self._parts: list = []

    def u8(self, v: int) -> "Writer":
        self._parts.append(_U8.pack(v))
        return self

    def u16(self, v: int) -> "Writer":
        self._parts.append(_U16.pack(v))
        return self

    def u32(self, v: int) -> "Writer":
        self._parts.append(_U32.pack(v))
        return self

    def i64(self, v: int) -> "Writer":
        self._parts.append(_I64.pack(v))
        return self

    def bytes_(self, b: bytes) -> "Writer":
        self._parts.append(_U32.pack(len(b)))
        self._parts.append(bytes(b))
        return self

    def str_(self, s: str) -> "Writer":
        return self.bytes_(s.encode("utf-8"))

    def done(self) -> bytes:
        return b"".join(self._parts)


class Reader:
    def __init__(self, data: bytes) -> None:
        self._d = data
        self._o = 0

    def u8(self) -> int:
        v = _U8.unpack_from(self._d, self._o)[0]
        self._o += 1
        return v

    def u16(self) -> int:
        v = _U16.unpack_from(self._d, self._o)[0]
        self._o += 2
        return v

    def u32(self) -> int:
        v = _U32.unpack_from(self._d, self._o)[0]
        self._o += 4
        return v

    def i64(self) -> int:
        v = _I64.unpack_from(self._d, self._o)[0]
        self._o += 8
        return v

    def bytes_(self) -> bytes:
        n = self.u32()
        b = self._d[self._o:self._o + n]
        self._o += n
        return b

    def str_(self) -> str:
        return self.bytes_().decode("utf-8")

    def at_end(self) -> bool:
        return self._o >= len(self._d)


def longest_common_prefix_len(a: bytes, b: bytes) -> int:
    """Length of the longest common prefix, via binary search over
    C-speed slice compares (no per-byte Python loop).  Shared by the
    columnar wire frames' prefix-truncated key streams (rpc/serde.py)
    and the B-tree's compressed leaf pages (server/kvstore_btree.py)."""
    n = min(len(a), len(b))
    if n == 0 or a[:1] != b[:1]:
        return 0
    if a[:n] == b[:n]:
        return n
    lo, hi = 1, n - 1
    while lo < hi:
        mid = (lo + hi + 1) // 2
        if a[:mid] == b[:mid]:
            lo = mid
        else:
            hi = mid - 1
    return lo
