"""Code-coverage markers (reference flow's TEST() macro + the TestHarness
coverage ledger).

The reference sprinkles `TEST("description")` at interesting code paths
(rare races, recovery branches, spill activations); the test harness
collects which markers fired across an ensemble and FAILS runs whose
expected markers never fired — simulation that stops exercising a path
is a silent coverage regression.  `test_coverage("...")` is the analog:
call it at the path, assert with `covered()` / report with `report()`;
scripts/run_ensemble.py aggregates across seeds and prints never-hit
markers.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, List, Set

_hits: Counter = Counter()
_registered: Set[str] = set()


def test_coverage(name: str) -> None:
    """Mark this code path as exercised (reference TEST(name))."""
    _registered.add(name)
    _hits[name] += 1


def register(name: str) -> None:
    """Pre-register a marker so report() can list it as NEVER hit even
    when the marking line itself never executed."""
    _registered.add(name)


def covered(name: str) -> bool:
    return _hits[name] > 0


def hits(name: str) -> int:
    return _hits[name]


def report() -> Dict[str, int]:
    return {name: _hits[name] for name in sorted(_registered)}


def missing() -> List[str]:
    return [name for name in sorted(_registered) if _hits[name] == 0]


def reset() -> None:
    _hits.clear()


# Markers that exist in the codebase (kept in sync with the
# test_coverage() call sites); ensembles report any that never fire.
for _name in (
    "RecoveryMasterLockedOldGeneration",
    "RecoveryRegionFailover",
    "TLogSpillActivated",
    "TaskBucketReclaim",
    "DDShardMerge",
    "RatekeeperThrottling",
    "RatekeeperTenantQuota",
    "ProxyTenantRejected",
    # Chaos engine (ISSUE 4): disk-fault detection paths.  Every injected
    # corruption/IO fault must be CAUGHT by the layer above — these mark
    # the catch sites, so a suite that injects faults the code silently
    # serves through shows up as a never-hit marker.
    "SimDiskIoErrorInjected",
    "SimDiskBitRotInjected",
    "DiskQueueCrcCaught",
    "BTreeSlotCrcCaught",
    "StorageIoErrorDeath",
    "TLogIoErrorDeath",
    "ChaosNemesisSwizzle",
    "ChaosNemesisAttrition",
    "ChaosNemesisPartition",
    # Resolution-plane attrition (ISSUE 7): a live resolver's worker is
    # killed; recovery must recruit a fresh plane with verdict
    # continuity (Cycle + ConsistencyCheck run alongside).
    "ChaosNemesisResolverKill",
    # Disaster-recovery nemesis battery (ISSUE 10): undrained region
    # failover (primary dc hard-killed mid-traffic, remote plane adopted
    # at min(end_version)), rolling coordinator restart (re-election +
    # CoordinationClientInterface re-pointing), fatal disk fault with
    # worker restart (the topology heals instead of shrinking), and a
    # backup captured + restored while the nemesis runs.
    "ChaosRegionFailover",
    "ChaosCoordinatorRestart",
    "ChaosFatalDiskRestart",
    "BackupRestoreUnderChaos",
    # Conflict-aware scheduling (ISSUE 12): predictor deferral at GRV
    # admission, intra-batch reorder at the commit proxy, and the
    # server-side repair path (attempted + committed) — the SchedChaos
    # spec must keep exercising all three stages.
    "GrvSchedDeferral",
    "ProxyBatchReordered",
    "ProxyTxnRepaired",
    "ProxyTxnRepairCommitted",
    # Gray-failure battery (ISSUE 18): one live link latency-inflated —
    # delivery still succeeds, so only the peer-health plane (ping RTT
    # verdicts, server/health.py) can observe it.
    "ChaosNemesisGrayClog",
    # Shard-disownment fence (system_data.py DISOWN_SHARD_PREFIX): a
    # storage server that missed DD's out-of-band RemoveShardRequest
    # (unreachable during the move) closes the range in-stream instead
    # of serving frozen data — the stale-read hole the ISSUE-12 chaos
    # battery flushed out.
    "SSDisownShardFence",
):
    register(_name)
