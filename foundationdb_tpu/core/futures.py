"""Futures, promises, streams, and actor coroutines.

The reference builds everything on Flow's single-assignment Future/Promise
pairs and an actor compiler turning `ACTOR` functions into state machines
(reference: flow/flow.h, flow/actorcompiler/ActorCompiler.cs).  We need no
codegen: Python native coroutines (`async def`) are our actors, driven by the
deterministic event loop in core/scheduler.py.  Semantics intentionally kept
from the reference:

  * single-assignment: a Future is set exactly once (value or error);
  * broken_promise: if a Promise is dropped unset, waiters get the
    broken_promise error (flow/flow.h SAV semantics);
  * cancellation: cancelling the Future returned by an actor injects
    ActorCancelled into the coroutine at its current suspension point
    (mirrors actor cancellation on Future destruction);
  * streams: PromiseStream/FutureStream with end_of_stream.
"""

from __future__ import annotations

import inspect
from collections import deque
from typing import Any, Callable, Generic, Iterable, List, Optional, TypeVar

from .error import ActorCancelled, FdbError, err

T = TypeVar("T")

_PENDING = 0
_VALUE = 1
_ERROR = 2


class Future(Generic[T]):
    """Single-assignment asynchronous value; awaitable from actor coroutines."""

    __slots__ = ("_state", "_result", "_callbacks", "_source_task")

    def __init__(self) -> None:
        self._state = _PENDING
        self._result: Any = None
        self._callbacks: List[Callable[[Future], None]] = []
        # Actor task that will fulfill this future (for cancellation), if any.
        self._source_task: Optional["ActorTask"] = None

    # -- inspection ---------------------------------------------------------
    def is_ready(self) -> bool:
        return self._state != _PENDING

    def is_error(self) -> bool:
        return self._state == _ERROR

    def get(self) -> T:
        """Value if ready; raises if error or pending."""
        if self._state == _VALUE:
            return self._result
        if self._state == _ERROR:
            raise self._result
        raise err("internal_error", "Future.get() on pending future")

    @property
    def error(self) -> Optional[BaseException]:
        return self._result if self._state == _ERROR else None

    # -- resolution ---------------------------------------------------------
    def _send(self, value: T) -> None:
        if self._state != _PENDING:
            raise err("internal_error", "Future already set")
        self._state = _VALUE
        self._result = value
        self._fire()

    def _send_error(self, e: BaseException) -> None:
        if self._state != _PENDING:
            raise err("internal_error", "Future already set")
        self._state = _ERROR
        self._result = e
        self._fire()

    def _fire(self) -> None:
        cbs, self._callbacks = self._callbacks, []
        for cb in cbs:
            cb(self)

    def on_ready(self, cb: Callable[["Future"], None]) -> None:
        if self._state != _PENDING:
            cb(self)
        else:
            self._callbacks.append(cb)

    def remove_callback(self, cb: Callable[["Future"], None]) -> None:
        try:
            self._callbacks.remove(cb)
        except ValueError:
            pass

    # -- cancellation -------------------------------------------------------
    def cancel(self) -> None:
        """Cancel the actor computing this future (if any and still pending)."""
        if self._state == _PENDING and self._source_task is not None:
            self._source_task.cancel()

    # -- awaitable protocol -------------------------------------------------
    def __await__(self):
        if self._state == _PENDING:
            yield self
        if self._state == _ERROR:
            raise self._result
        if self._state == _PENDING:
            raise err("internal_error", "Future resumed while pending")
        return self._result


def ready_future(value: T = None) -> Future:
    f: Future = Future()
    f._send(value)
    return f


def error_future(e: BaseException) -> Future:
    f: Future = Future()
    f._send_error(e)
    return f


class Promise(Generic[T]):
    """The write end of a Future (single assignment).

    Dropping the last reference to an unset Promise breaks it: waiters get
    broken_promise (reference flow/flow.h SAV destruction semantics)."""

    __slots__ = ("_future", "_sent", "__weakref__")

    def __init__(self) -> None:
        self._future: Future = Future()
        self._sent = False

    def get_future(self) -> Future:
        return self._future

    def send(self, value: T = None) -> None:
        self._sent = True
        self._future._send(value)

    def send_error(self, e: BaseException) -> None:
        self._sent = True
        self._future._send_error(e)

    def is_set(self) -> bool:
        return self._sent

    def break_promise(self) -> None:
        if not self._sent and not self._future.is_ready():
            self._sent = True   # spent: later send/send_error must no-op
            self._future._send_error(err("broken_promise"))

    def __del__(self) -> None:
        try:
            self.break_promise()
        except Exception:  # pragma: no cover - interpreter shutdown
            pass


END_OF_STREAM = FdbError(1, "end_of_stream")


class PromiseStream(Generic[T]):
    """Multi-value FIFO stream (reference flow/flow.h PromiseStream/FutureStream).

    Values are buffered; each pop() returns a Future of the next value.
    send_error()/close() terminates the stream for all future pops."""

    __slots__ = ("_queue", "_waiters", "_closed_error")

    def __init__(self) -> None:
        self._queue: deque = deque()
        self._waiters: deque = deque()
        self._closed_error: Optional[BaseException] = None

    def send(self, value: T = None) -> None:
        if self._closed_error is not None:
            return
        while self._waiters:
            w = self._waiters.popleft()
            # Deliver only to a waiter some actor is actually awaiting
            # (it has a resume callback).  A pending-but-callback-less
            # waiter is ABANDONED: its consumer was cancelled after
            # pop() (ActorTask.cancel detaches the callback) — e.g. a
            # deposed cluster controller's stream servers.  Delivering
            # into it would swallow exactly one message per cancelled
            # consumer; the re-run consumer then waits forever for a
            # request whose sender waits forever for a reply (observed
            # as a wedged recovery after CC re-election, ISSUE 10).
            if not w.is_ready() and w._callbacks:
                w._send(value)
                return
        self._queue.append(value)

    def send_error(self, e: BaseException) -> None:
        if self._closed_error is not None:
            return
        self._closed_error = e
        waiters, self._waiters = self._waiters, []
        for w in waiters:
            if not w.is_ready():
                w._send_error(e)

    def close(self) -> None:
        self.send_error(END_OF_STREAM)

    def pop(self) -> Future:
        """Future of the next stream value.

        Await the returned future DIRECTLY (or via the async-for
        protocol).  Do not hold it across a combinator (e.g.
        `wait_any([pop_f, delay(t)])` and re-await after the timeout):
        send() treats a pending waiter with no attached consumer
        callback as abandoned-by-cancellation and drops it — the value
        is preserved for the NEXT pop(), but a dropped future re-awaited
        later never resolves."""
        f: Future = Future()
        if self._queue:
            f._send(self._queue.popleft())
        elif self._closed_error is not None:
            f._send_error(self._closed_error)
        else:
            self._waiters.append(f)
        return f

    def empty(self) -> bool:
        return not self._queue

    def break_buffered_replies(self) -> None:
        """Break the reply promise of every buffered-but-unserved request
        (the server died before popping them).  An explicit protocol —
        callers must not grope stream internals, or a rename silently
        reverts promise breaks to GC-timing dependence."""
        for req in self._queue:
            reply = getattr(req, "reply", None)
            if reply is not None and hasattr(reply, "send_error") and \
                    not reply.is_set():
                reply.send_error(err("broken_promise"))
        self._queue.clear()

    def __len__(self) -> int:
        return len(self._queue)

    def __aiter__(self):
        return self

    async def __anext__(self):
        try:
            return await self.pop()
        except FdbError as e:
            if e.code == 1:  # end_of_stream
                raise StopAsyncIteration from None
            raise


FutureStream = PromiseStream  # reader alias


class AsyncVar(Generic[T]):
    """A variable you can wait on for changes (reference flow AsyncVar)."""

    __slots__ = ("_value", "_change")

    def __init__(self, value: T = None) -> None:
        self._value = value
        self._change: Future = Future()

    def get(self) -> T:
        return self._value

    def set(self, value: T) -> None:
        if value != self._value:
            self._value = value
            self.trigger()

    def trigger(self) -> None:
        old, self._change = self._change, Future()
        old._send(None)

    def on_change(self) -> Future:
        return self._change


class AsyncTrigger:
    """Edge trigger: on_trigger() futures resolve at each trigger()."""

    __slots__ = ("_inner",)

    def __init__(self) -> None:
        self._inner = AsyncVar(0)

    def trigger(self) -> None:
        self._inner.trigger()

    def on_trigger(self) -> Future:
        return self._inner.on_change()


_current_task: "Optional[ActorTask]" = None


def current_task() -> "Optional[ActorTask]":
    """The ActorTask whose coroutine body is executing right now (None
    between actor steps / in harness code)."""
    return _current_task


class ActorTask:
    """Drives one actor coroutine on the event loop (our ACTOR equivalent)."""

    __slots__ = ("coro", "future", "_loop", "_cancelled", "_waiting_on",
                 "_resume_cb", "name", "_finished", "_started", "process")

    def __init__(self, coro, loop, name: str = "") -> None:
        assert inspect.iscoroutine(coro), f"spawn() needs a coroutine, got {coro!r}"
        self.coro = coro
        self.future: Future = Future()
        self.future._source_task = self
        self._loop = loop
        self._cancelled = False
        self._finished = False
        self._started = False
        self._waiting_on: Optional[Future] = None
        self._resume_cb: Optional[Callable] = None
        self.name = name or getattr(coro, "__name__", "actor")
        # The simulated process this actor runs "on" (set by
        # SimProcess.spawn; inherited by transitively spawned actors) —
        # the network's ambient SOURCE address.  None for harness/client
        # actors that live outside the simulated machine set.
        self.process = current_task().process \
            if current_task() is not None else None

    def _initial_step(self) -> None:
        if self._cancelled or self._finished:
            # Cancelled before first execution: like Flow, the body never runs.
            if not self._finished:
                self.coro.close()
                self._finish_cancel()
            return
        self._started = True
        self._step()

    def _step(self, send_value=None, throw_exc: Optional[BaseException] = None) -> None:
        """Advance the coroutine one suspension; hook its next awaited Future.

        Also drives post-cancellation cleanup: if the coroutine awaits during
        unwind (e.g. in a finally block) we keep re-hooking until it finishes."""
        global _current_task
        if self._finished:
            return
        self._waiting_on = None
        # Ambient actor context while the coroutine body runs: spawned
        # sub-actors inherit this task's process, and the sim network
        # reads it as the SOURCE address of outgoing requests (without
        # it every RPC looked like destination self-traffic and
        # clogs/partitions never applied to request delivery).
        prev_task, _current_task = _current_task, self
        try:
            try:
                if throw_exc is not None:
                    awaited = self.coro.throw(throw_exc)
                else:
                    awaited = self.coro.send(send_value)
            except StopIteration as stop:
                self._finish_value(stop.value)
                return
            except ActorCancelled as e:
                # Drop the traceback NOW: it pins the whole unwound frame
                # chain (and those frames' locals — e.g. held reply
                # promises) until cyclic GC happens to run, making
                # broken_promise delivery wall-clock dependent.  Clearing
                # it restores the reference semantics of Flow's SAV
                # destruction: refcounts free the frames immediately and
                # their promises break deterministically.
                e.__traceback__ = None
                del e
                self._finish_cancel()
                return
            except BaseException as e:  # noqa: BLE001 - actor errors propagate via future
                self._finish_error(e)
                return
        finally:
            _current_task = prev_task

        if not isinstance(awaited, Future):
            self._finish_error(err("internal_error",
                                   f"actor {self.name} awaited non-Future {awaited!r}"))
            return
        self._waiting_on = awaited

        def resume(fut: Future, task=self) -> None:
            # Defer resumption through the loop: deterministic ordering and no
            # reentrant callback stacks.
            task._loop.call_soon(lambda: task._on_future_ready(fut))

        self._resume_cb = resume
        awaited.on_ready(resume)

    def _on_future_ready(self, fut: Future) -> None:
        # Note: a cancelled-but-unfinished actor still resumes here so that
        # `finally:` blocks containing awaits run to completion.
        if self._finished:
            return
        if fut.is_error():
            self._step(throw_exc=fut.error)
        else:
            self._step(send_value=fut._result)

    def _finish_value(self, value) -> None:
        self._finished = True
        if not self.future.is_ready():
            self.future._send(value)
        self._loop._task_done(self)

    def _finish_error(self, e: BaseException) -> None:
        self._finished = True
        if not self.future.is_ready():
            self.future._send_error(e)
        self._loop._task_done(self)

    def _finish_cancel(self) -> None:
        self._finished = True
        if not self.future.is_ready():
            self.future._send_error(err("operation_cancelled"))
        self._loop._task_done(self)

    def cancel(self) -> None:
        """Cancel the actor. Its future resolves operation_cancelled now; the
        coroutine unwinds via ActorCancelled at its suspension point, and any
        awaits in `finally:` cleanup continue to be driven to completion."""
        if self._finished or self._cancelled:
            return
        self._cancelled = True
        waiting, self._waiting_on = self._waiting_on, None
        if waiting is not None and self._resume_cb is not None:
            waiting.remove_callback(self._resume_cb)
        if not self.future.is_ready():
            self.future._send_error(err("operation_cancelled"))
        if self._started:
            # _step handles a coroutine that awaits during unwind by re-hooking.
            self._loop.call_soon(lambda: self._step(throw_exc=ActorCancelled()))
        # else: _initial_step will observe _cancelled and close the coroutine.


# ---------------------------------------------------------------------------
# Combinators (reference flow/genericactors.actor.h)
# ---------------------------------------------------------------------------

def _combinator(futures: List[Future], on_each: Callable) -> Future:
    """Shared plumbing: attach one callback per input; when `out` resolves,
    deregister callbacks from still-pending inputs so long-lived futures
    (e.g. a shutdown signal awaited in a loop) don't accumulate closures."""
    out: Future = Future()
    cbs: List = [None] * len(futures)

    def cleanup() -> None:
        for f, cb in zip(futures, cbs):
            if not f.is_ready() and cb is not None:
                f.remove_callback(cb)

    for i, f in enumerate(futures):
        def cb(fut: Future, i=i) -> None:
            if out.is_ready():
                return
            on_each(out, i, fut)
            if out.is_ready():
                cleanup()
        cbs[i] = cb
    # Attach after all cbs are recorded (a ready future fires immediately);
    # stop as soon as out resolves so no callback lingers on later inputs.
    for f, cb in zip(futures, cbs):
        if out.is_ready():
            break
        f.on_ready(cb)
    return out


def wait_all(futures: Iterable[Future]) -> Future:
    """Resolves with list of values when all are ready; first error wins."""
    futures = list(futures)
    if not futures:
        return ready_future([])
    results: List[Any] = [None] * len(futures)
    remaining = [len(futures)]

    def on_each(out: Future, i: int, f: Future) -> None:
        if f.is_error():
            out._send_error(f.error)
            return
        results[i] = f._result
        remaining[0] -= 1
        if remaining[0] == 0:
            out._send(results)

    return _combinator(futures, on_each)


def wait_any(futures: Iterable[Future]) -> Future:
    """Resolves with (index, value) of the first ready future (choose/when)."""
    futures = list(futures)
    if not futures:
        return error_future(err("internal_error", "wait_any of empty list"))

    def on_each(out: Future, i: int, f: Future) -> None:
        if f.is_error():
            out._send_error(f.error)
        else:
            out._send((i, f._result))

    return _combinator(futures, on_each)


def quorum(futures: Iterable[Future], n: int) -> Future:
    """Resolves (None) when n futures are ready; error if too many fail."""
    futures = list(futures)
    if n <= 0:
        return ready_future(None)
    if n > len(futures):
        return error_future(err("internal_error",
                                f"quorum({n}) of only {len(futures)} futures"))
    state = {"ok": 0, "fail": 0}
    max_fail = len(futures) - n

    def on_each(out: Future, i: int, f: Future) -> None:
        if f.is_error():
            state["fail"] += 1
            if state["fail"] > max_fail:
                out._send_error(f.error)
        else:
            state["ok"] += 1
            if state["ok"] >= n:
                out._send(None)

    return _combinator(futures, on_each)


def swallow(f: Future) -> Future:
    """Resolve (with None) when `f` resolves, success OR error — for racing
    fallible futures inside wait_any/wait_all without error propagation.
    Inspect `f` itself afterwards for the outcome."""
    out: Future = Future()
    f.on_ready(lambda fut: out._send(None) if not out.is_ready() else None)
    return out


def map_future(f: Future, fn: Callable[[Any], Any]) -> Future:
    out: Future = Future()

    def cb(fut: Future) -> None:
        if fut.is_error():
            out._send_error(fut.error)
        else:
            try:
                out._send(fn(fut._result))
            except BaseException as e:  # noqa: BLE001
                out._send_error(e)

    f.on_ready(cb)
    return out
