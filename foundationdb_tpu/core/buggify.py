"""BUGGIFY fault-injection sites (reference flow/flow.h:80-89).

A buggify site is identified by a string name. In simulation, each site is
deterministically enabled with probability P_BUGGIFIED_SECTION_ACTIVATED per
run; an enabled site then fires with P_BUGGIFIED_SECTION_FIRES per evaluation.
Outside simulation buggify() is always False.
"""

from __future__ import annotations

from typing import Dict

from .rng import deterministic_random

P_ACTIVATED = 0.25
P_FIRES = 0.25

_enabled = False
_site_active: Dict[str, bool] = {}
# Deterministic per-site overrides (tests/chaos drivers): True = the site
# fires on EVERY evaluation, False = never, absent = probabilistic.
# Overrides apply even with buggify globally disabled, so a chaos test
# can kill exactly one site without randomizing every other one.
_forced: Dict[str, bool] = {}


def enable_buggify(on: bool = True) -> None:
    global _enabled
    _enabled = on
    _site_active.clear()


def buggify_enabled() -> bool:
    return _enabled


def force_buggify(site: str, fire: bool = True) -> None:
    """Pin a site: buggify(site) returns `fire` until unforce_buggify."""
    _forced[site] = fire


def unforce_buggify(site: str = None) -> None:
    """Drop one forced site (or all of them with no argument)."""
    if site is None:
        _forced.clear()
    else:
        _forced.pop(site, None)


def buggify(site: str) -> bool:
    """True (rarely, deterministically) when fault injection should happen."""
    if site in _forced:
        return _forced[site]
    if not _enabled:
        return False
    rng = deterministic_random()
    active = _site_active.get(site)
    if active is None:
        active = rng.random01() < P_ACTIVATED
        _site_active[site] = active
    return active and rng.random01() < P_FIRES
