"""BUGGIFY fault-injection sites (reference flow/flow.h:80-89).

A buggify site is identified by a string name. In simulation, each site is
deterministically enabled with probability P_BUGGIFIED_SECTION_ACTIVATED per
run; an enabled site then fires with P_BUGGIFIED_SECTION_FIRES per evaluation.
Outside simulation buggify() is always False.
"""

from __future__ import annotations

from typing import Dict

from .rng import deterministic_random

P_ACTIVATED = 0.25
P_FIRES = 0.25

_enabled = False
_site_active: Dict[str, bool] = {}


def enable_buggify(on: bool = True) -> None:
    global _enabled
    _enabled = on
    _site_active.clear()


def buggify_enabled() -> bool:
    return _enabled


def buggify(site: str) -> bool:
    """True (rarely, deterministically) when fault injection should happen."""
    if not _enabled:
        return False
    rng = deterministic_random()
    active = _site_active.get(site)
    if active is None:
        active = rng.random01() < P_ACTIVATED
        _site_active[site] = active
    return active and rng.random01() < P_FIRES
