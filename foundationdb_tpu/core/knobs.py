"""Knob (tuning constant) registry with BUGGIFY randomization.

Reference: flow/Knobs.h/.cpp, fdbclient/ServerKnobs.cpp, ClientKnobs.cpp.
Knobs are typed named constants, overridable at startup, and in simulation a
subset is randomized per-seed (`if (randomize && BUGGIFY) knob = ...`) to
widen test coverage.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from .rng import DeterministicRandom


class KnobBase:
    """Subclass sets attributes in __init__; randomizers registered alongside."""

    def __init__(self) -> None:
        self._randomizers: List[Tuple[str, Callable[[DeterministicRandom], Any]]] = []

    def _rand(self, name: str, fn: Callable[[DeterministicRandom], Any]) -> None:
        self._randomizers.append((name, fn))

    def randomize(self, rng: DeterministicRandom, p: float = 0.5) -> None:
        """Apply each registered randomizer with probability p (sim only)."""
        for name, fn in self._randomizers:
            if rng.random01() < p:
                setattr(self, name, fn(rng))

    def override(self, overrides: Dict[str, Any]) -> None:
        for k, v in overrides.items():
            if not hasattr(self, k):
                raise KeyError(f"unknown knob {k}")
            setattr(self, k, v)

    def apply_dynamic(self, name: str, raw: bytes) -> bool:
        """Apply a committed dynamic-knob override (the config-DB path,
        server/system_data.py KNOBS_PREFIX): the printed value is coerced
        to the current attribute's type.  Unknown names are ignored with
        a warning — a knob removed in this build must not wedge the
        watch.  Returns True when a value actually changed."""
        from .trace import Severity, TraceEvent
        if name.startswith("_") or not hasattr(self, name):
            TraceEvent("DynamicKnobUnknown", Severity.Warn).detail(
                "Name", name).log()
            return False
        cur = getattr(self, name)
        text = raw.decode()
        try:
            if isinstance(cur, bool):
                value: Any = text.lower() in ("1", "true", "on")
            elif isinstance(cur, int):
                value = int(float(text))
            elif isinstance(cur, float):
                value = float(text)
            else:
                value = text
        except ValueError:
            TraceEvent("DynamicKnobBadValue", Severity.Warn).detail(
                "Name", name).detail("Raw", text).log()
            return False
        if value == cur:
            return False
        setattr(self, name, value)
        TraceEvent("DynamicKnobApplied").detail("Name", name).detail(
            "Value", value).log()
        return True


class FlowKnobs(KnobBase):
    def __init__(self) -> None:
        super().__init__()
        self.DELAY_JITTER_OFFSET = 0.9
        self.DELAY_JITTER_RANGE = 0.2
        self.HUGE_ARENA_LOGGING_BYTES = 100e6
        # Trace file hygiene (reference FileTraceLogWriter.cpp +
        # MAX_TRACE_LOG_FILE_SIZE / TRACE_RETAIN_FILES): roll the JSONL
        # output past this size, keep at most KEEP rolled files, and
        # flush every FLUSH_EVERY events so a crash leaves usable traces.
        self.TRACE_ROLL_FILE_BYTES = 10 << 20
        self.TRACE_KEEP_FILES = 5
        self.TRACE_FLUSH_EVERY_EVENTS = 64
        # Reactor slow-task detection threshold (core/profiler.py): a
        # callback holding the loop longer than this emits SlowTask.
        self.SLOW_TASK_THRESHOLD_S = 0.25


class ServerKnobs(KnobBase):
    """Server-side knobs. Values follow the reference's published defaults
    (fdbclient/ServerKnobs.cpp) where the semantics carry over."""

    def __init__(self) -> None:
        super().__init__()
        # Versions (reference ServerKnobs.cpp:32-36)
        self.VERSIONS_PER_SECOND = 1_000_000
        self.MAX_READ_TRANSACTION_LIFE_VERSIONS = 5 * self.VERSIONS_PER_SECOND
        self.MAX_WRITE_TRANSACTION_LIFE_VERSIONS = 5 * self.VERSIONS_PER_SECOND
        self.MAX_VERSIONS_IN_FLIGHT = 100 * self.VERSIONS_PER_SECOND
        self.MAX_COMMIT_BATCH_INTERVAL = 2.0

        # Commit batching (reference ServerKnobs.cpp:376-387)
        self.COMMIT_TRANSACTION_BATCH_INTERVAL_MIN = 0.001
        self.COMMIT_TRANSACTION_BATCH_INTERVAL_MAX = 0.020
        self.COMMIT_TRANSACTION_BATCH_COUNT_MAX = 32768
        self.COMMIT_TRANSACTION_BATCH_BYTES_MAX = 8 << 20
        self.RESOLVER_COALESCE_TIME = 1.0

        # Metrics emission cadence (reference Stats.h traceCounters
        # interval): how often every role's CounterCollection emits its
        # {group}Metrics + LatencyBand trace events (core/metrics.py).
        self.METRICS_EMIT_INTERVAL = 5.0
        # REAL-mode periodic worker re-registration cadence (worker.py
        # _stats_announce_loop): each refresh ships the process's
        # metrics-registry export to the CC, so this bounds the staleness
        # of cluster.latency_statistics / cluster.metrics on a real
        # cluster.  Dynamic (re-read per tick) — `bench.py e2e` lowers it
        # live for per-phase stage attribution.  Sim keeps its own fixed
        # deterministic interval.
        self.WORKER_REGISTER_INTERVAL_S = 30.0

        # Peer-health plane (ISSUE 18; reference the 7.1 worker health
        # monitor: WorkerInterface.actor.cpp UpdateWorkerHealthRequest +
        # ClusterController degradation tracking).  Master switch gates
        # every per-peer sample in both transports AND the ping actor, so
        # the bench overhead gate can measure enabled-vs-disabled.
        self.PEER_HEALTH_ENABLED = True
        # Ping-actor cadence: each worker's health monitor pings every
        # known peer this often (deterministic virtual-time delay in sim).
        self.PEER_PING_INTERVAL_S = 1.0
        # A peer whose ping/request RTT EMA exceeds this is latency-
        # degraded (the gray-failure signal a quorum check can't see).
        self.PEER_DEGRADED_LATENCY_S = 0.050
        # ... or whose timeout fraction (timeouts+disconnects over total
        # attempts in the current window) exceeds this.
        self.PEER_TIMEOUT_FRACTION = 0.25
        # Hysteresis: a peer must stay above/below threshold for this many
        # consecutive health-monitor evaluations before its verdict flips
        # (verdicts must not flap on one bad sample).
        self.PEER_VERDICT_HYSTERESIS = 2
        # CC-side aggregation: a process is cluster-degraded only when at
        # least this many INDEPENDENT workers report it degraded.
        self.CC_DEGRADATION_REPORTERS = 2
        # Health reports older than this are aged out of CC aggregation
        # (a silent reporter must not pin a stale verdict forever).
        self.CC_HEALTH_REPORT_MAX_AGE_S = 30.0
        # Action hook: when ON, a cluster-degraded TLog/resolver triggers
        # a recovery-based eviction.  DEFAULT OFF with bit-identical
        # off-posture (parity gate in tier-1): with the knob off the CC
        # only *reports* — no RNG draw, no scheduling perturbation.
        self.CC_HEALTH_TRIGGERED_RECOVERY = False

        # Resolver (reference ServerKnobs.cpp:439)
        self.RESOLVER_STATE_MEMORY_LIMIT = 1_000_000
        self.KEY_BYTES_PER_SAMPLE = 2e4

        # Conflict-set backend selector -- OUR north-star gate. "cpu" = the
        # Python oracle; "native" = C++ skip-structure; "tpu" = JAX device
        # kernel over the HBM-resident window; "auto" = tpu when an
        # accelerator is attached, else cpu.
        self.CONFLICT_SET_BACKEND = "cpu"
        self.TPU_CONFLICT_CAPACITY = 1 << 17  # max resident history segments

        # Device-backend supervision (conflict/supervisor.py): deadline
        # budget per device call, transient-retry policy, health-trip
        # thresholds, and the degraded-mode re-probe cadence.  Device
        # backends ("tpu"/"sharded") are wrapped in the supervisor by
        # default so a dead/stalling accelerator degrades the Resolver to
        # the exact CPU mirror instead of wedging the commit pipeline.
        self.CONFLICT_BACKEND_SUPERVISED = True
        # Per-call deadline.  Generous: its job is catching INDEFINITE
        # hangs (a dead tunnel), not slow batches — first-use-of-a-shape
        # calls legitimately carry minutes of in-band XLA compile (the
        # axon remote compile service measured 150-400s/shape, PERF.md).
        self.CONFLICT_DEVICE_TIMEOUT_S = 600.0    # 0 disables thread guard
        self.CONFLICT_DEVICE_MAX_RETRIES = 2      # transient-error retries
        self.CONFLICT_DEVICE_RETRY_BACKOFF_S = 0.05   # doubles per retry
        # Health-monitor failure-streak length (BackendHealthMonitor).
        # NOTE: an UNRECOVERED hard failure always degrades the backend
        # immediately — a mid-batch failure leaves device state
        # unknowable, and wrong verdicts are worse than a conservative
        # degrade — so this streak matters for monitors tracking
        # survivable signals, not for hard dispatch/wait errors.
        self.CONFLICT_BACKEND_FAILURE_THRESHOLD = 3
        self.CONFLICT_DEVICE_LATENCY_SLO_S = 0.0  # 0 disables the SLO trip
        self.CONFLICT_DEVICE_SLO_STRIKES = 8      # consecutive slow batches
        self.CONFLICT_BACKEND_REPROBE_S = 5.0     # doubles per failed probe
        # Depth-N dispatch pipeline (conflict/supervisor.py): max batches
        # in flight on the device (dispatched, verdicts not yet folded)
        # before resolve_async folds the oldest first.  While batch k's
        # device step runs, batch k+1 host-packs/h2d-enqueues on the
        # dispatch lane and batch k-1's verdicts d2h-fetch on the fetch
        # lane; verdict DELIVERY stays strictly in submission order at
        # every depth.  1 = fully serialized (the pre-pipeline behavior).
        self.CONFLICT_PIPELINE_DEPTH = 8

        # Cluster heat telemetry (conflict/heat.py, ISSUE 8): the
        # conflict-range / read-hot-spot sampling plane surfaced through
        # status cluster.heat, \xff\xff/metrics/ and `fdbcli top`.  The
        # master switch gates every hot-path sample (resolver conflict
        # attribution feed, storage per-shard read heat, the supervised
        # device path's mirror attribution) so the bench overhead gate
        # can measure enabled-vs-disabled on the same stream.
        self.HEAT_TELEMETRY_ENABLED = True
        # Max aborted txns per device-path batch attributed EXACTLY via
        # the supervisor's mirror (conflict/supervisor.py satellite fix);
        # the remainder keep conservative whole-read-set blame, counted
        # by the ConservativeAttribution counter.
        self.CONFLICT_ATTRIBUTION_SAMPLE = 32
        # Rows per table in HotConflictRange emission, cluster.heat and
        # the \xff\xff/metrics/ mirrors.
        self.CONFLICT_HEAT_TOP_K = 8
        # Unified resolver sample table bound (load + conflict columns,
        # halved when full — the old SAMPLE_TABLE_MAX).
        self.CONFLICT_HEAT_TABLE_MAX = 4096
        # Storage read-heat sampling (server/storage.py): per-shard
        # ops/bytes EMA folded at each queuing-metrics poll.
        self.READ_HOT_EMA_HALF_LIFE_S = 2.0   # EMA memory
        self.READ_HOT_SHARD_MAX_REPORT = 8    # rows per reply/status
        self.READ_HOT_MIN_OPS_PER_S = 10.0    # ReadHotShard trace floor

        # Conflict-aware transaction scheduling (foundationdb_tpu/sched/,
        # ISSUE 12): three independently gated stages.  All DEFAULT OFF —
        # the abort-set parity guard promises bit-identical resolver
        # verdicts and reply bytes with every SCHED_* stage disabled.
        # (a) Predictor: GRV-admission deferral of transactions whose
        # declared tag/tenant maps to a predicted-doomed range (decayed
        # abort-probability EMAs fed from the resolvers' heat trackers
        # via the ratekeeper's rate-info piggyback).
        self.SCHED_PREDICTOR_ENABLED = False
        # Per-deferral delay at the GRV proxy; deterministic sim delay.
        self.SCHED_ADMISSION_DELAY_S = 0.05
        # Starvation proof: a request is deferred at most this many
        # times, then admitted unconditionally.
        self.SCHED_MAX_DEFERRALS = 3
        # EMA fold factor per feed snapshot, and the doom thresholds: a
        # range is predicted-doomed when its abort-probability EMA and
        # decayed conflict count both clear these.
        self.SCHED_PREDICTOR_ALPHA = 0.5
        # Doom threshold on the conflicts/(conflicts+load) EMA.  Load is
        # 1-in-8 subsampled upstream, so the ratio overweights aborts by
        # design; 0.3 means roughly "one attributed abort per ~19 range
        # touches" — well above any low-contention noise floor.
        self.SCHED_PREDICTOR_ABORT_P = 0.3
        self.SCHED_PREDICTOR_MIN_CONFLICTS = 4.0
        self.SCHED_PREDICTOR_TABLE_MAX = 512
        # (b) Intra-batch reorder at commit-proxy batch assembly: greedy
        # topological readers-before-writers pre-pass; above EXACT_MAX
        # transactions it degrades to the one-round in-degree sort.
        self.SCHED_REORDER_ENABLED = False
        self.SCHED_REORDER_EXACT_MAX = 1024
        # (c) Repair: opt-in server-side retry of staleness-only aborts
        # (re-stamp at a fresh read version, re-resolve) — at most this
        # many attempts per transaction before the abort goes back to
        # the client.  Values > 1 climb the repair LADDER
        # (sched/repair.py RepairLadder): each failed re-resolve of a
        # culprit range backs that RANGE off for BACKOFF_VERSIONS
        # doubling per rung, so a range rewritten faster than one batch
        # interval stops burning resolver round trips while cold ranges
        # still repair at full speed.
        self.SCHED_REPAIR_ENABLED = False
        self.TXN_REPAIR_MAX_ATTEMPTS = 1
        # Base per-range backoff after a ladder EXHAUSTS (all attempts
        # spent, still conflicted), in versions — ~a quarter of a commit
        # batch at the reference 1M versions/s cadence, doubling per
        # repeat exhaustion, cleared by the next successful repair of
        # the range.  Small by design: blocking a hot range for whole
        # batches starves repair wholesale (measured in bench.py sched).
        self.TXN_REPAIR_BACKOFF_VERSIONS = 250
        self.TXN_REPAIR_LADDER_TABLE_MAX = 1024

        # End-to-end commit hot path (ISSUE 14).  Both default OFF: the
        # knobs-off pipeline is bit-identical (wire images golden-guarded,
        # `bench.py e2e --smoke` parity gate in tier-1).
        # Columnar wire frames for the two hottest RPCs
        # (ResolveTransactionBatchRequest fragments, the TLog push, and
        # the resolver's verdict reply): batch-level frames packing keys/
        # ranges/versions as contiguous byte columns with shared-prefix
        # truncation instead of per-object tagged dict encoding
        # (rpc/serde.py).  Decoding is format-transparent regardless of
        # this knob — a columnar-off peer still reads columnar frames and
        # vice versa (mixed-format safe within one protocol version).
        self.RPC_COLUMNAR_ENABLED = False
        # Vectorized commit-proxy batch assembly: per-resolver clipped
        # fragments and the TLog mutation stream built in one pass over
        # flattened boundary arrays (bisect lookups, cached eligibility)
        # instead of per-txn RangeMap walks — bit-identical output to the
        # plain path (parity-tested).
        self.PROXY_VECTORIZED_ASSEMBLY = False

        # Read hot path (ISSUE 15) — the read-side mirror of the two
        # knobs above.  Both DEFAULT OFF with bit-identical knobs-off
        # behavior (`bench.py reads --smoke` parity gate in tier-1).
        # Prefix-compressed B-tree LEAF pages (kvstore_btree.py, the
        # reference's Redwood page key compression): leaves encode one
        # shared page prefix + per-entry suffix arrays, so dense
        # same-prefix keyspaces pack several times more records per 4K
        # page.  Decoding is format-transparent regardless of the knob
        # (plain pages and compressed pages both always decode), so the
        # knob can be flipped on a live store: old pages stay readable,
        # COW rewrites migrate them incrementally.
        self.BTREE_PREFIX_COMPRESSION = False
        # Batched/vectorized range scans: the storage server's MVCC
        # range_read walks its sorted key array emitting rows in slices
        # with the per-key version-chain probe inlined, and the B-tree's
        # read_range switches from per-key recursive descent to an
        # iterative leaf walk emitting bisected page slices.  Results
        # are bit-identical to the plain paths (parity-tested).
        self.STORAGE_VECTORIZED_SCAN = False
        # Incremental DD shard-metrics (storage.py _ShardMetricsCache):
        # storage maintains per-shard byte/count estimates updated by
        # write-time deltas, so DD's 0.5s GetShardMetrics poll is O(1)
        # per unchanged shard instead of O(keys in shard) — the fix that
        # lets `bench.py e2e` stop bounding its working set.  Totals are
        # exact (deltas are computed from the replaced value), so this
        # defaults ON; the knob is the emergency revert to full scans.
        self.STORAGE_INCREMENTAL_SHARD_METRICS = True

        # Resolution plane (master recruitment): resolver count override —
        # 0 recruits DatabaseConfiguration.n_resolvers (the committed
        # \xff/conf value); > 0 pins the count regardless of configuration
        # (takes effect at the next recovery, like every recruitment knob).
        self.RESOLVER_COUNT = 0
        # Seed recruitment-time resolver boundaries as equi-depth cuts over
        # the storage shard map (DD keeps shards split by data volume, so
        # shard boundaries sample the committed key distribution — the
        # keyspace analog of sharded_window.splits_from_sample's digest
        # quantiles).  False falls back to static even byte splits.
        self.RESOLVER_BOUNDARY_EQUIDEPTH = True

        # Resolution balancing (reference masterserver.actor.cpp:1318)
        self.RESOLUTION_BALANCING_INTERVAL = 0.5
        self.RESOLUTION_BALANCING_MIN_LOAD = 50   # ranges/poll to bother
        self.RESOLUTION_BALANCING_RATIO = 1.5     # max/min load trigger

        # Data distribution (reference DD_SHARD_SIZE_GRANULARITY etc.)
        self.DD_SHARD_SPLIT_BYTES = 1 << 20   # split a shard above this
        self.DD_METRICS_INTERVAL = 0.5        # shard-size poll cadence
        # Merge adjacent same-team shards whose COMBINED size is below
        # this (reference DataDistributionTracker shardMerger; kept well
        # under the split threshold to avoid split/merge ping-pong).
        self.DD_SHARD_MERGE_BYTES = (1 << 20) // 4

        # Perpetual storage wiggle (reference DataDistribution.actor.cpp
        # storage wiggle / perpetual_storage_wiggle configuration): when
        # non-zero, DD slowly cycles through storage servers, draining
        # one at a time and letting it refill — rewriting every replica
        # in place (the reference uses it for engine migrations and
        # latent-disk-error scrubbing).  Dynamic: `setknob
        # PERPETUAL_STORAGE_WIGGLE 1` turns it on cluster-wide.
        self.PERPETUAL_STORAGE_WIGGLE = 0
        self.STORAGE_WIGGLE_INTERVAL = 5.0

        # GRV / ratekeeper
        self.START_TRANSACTION_BATCH_INTERVAL_MIN = 1e-6
        self.START_TRANSACTION_BATCH_INTERVAL_MAX = 0.010
        self.START_TRANSACTION_MAX_BUDGET_SIZE = 20
        # Ratekeeper smoothing half-life (reference SMOOTHING_AMOUNT /
        # smoothReleasedTransactions in Ratekeeper.actor.cpp).
        self.RK_SMOOTHING_HALF_LIFE = 1.0
        # Per-tag auto-throttle (reference TagThrottle / busy-read
        # detection, Ratekeeper.actor.cpp updateRate + StorageServer
        # busiest-tag sampling): a storage server whose read rate exceeds
        # BUSY fraction of its saturation with one tag responsible for
        # >= MIN_TAG_FRACTION of reads gets that tag throttled.
        self.SS_READ_SATURATION_OPS = 20000.0
        self.AUTO_THROTTLE_BUSY_FRACTION = 0.8
        self.AUTO_THROTTLE_MIN_TAG_FRACTION = 0.5
        self.AUTO_TAG_THROTTLE_DURATION = 5.0

        # Storage
        self.STORAGE_DURABILITY_LAG_SOFT_MAX = 250e6
        self.DESIRED_TOTAL_BYTES = 150000
        self.STORAGE_LIMIT_BYTES = 500000
        # Read-path future_version wait (reference waitForVersion timeout
        # in storageserver.actor.cpp) and updateStorage durability-batch
        # cadence (reference updateStorage :4002).  Promoted from
        # module-level constants by flowlint FTL008.
        self.STORAGE_FUTURE_VERSION_TIMEOUT = 1.0
        self.UPDATE_STORAGE_INTERVAL = 0.05

        # Simulated disk fault injection (server/sim_fs.py, reference
        # AsyncFileNonDurable + BUGGIFY'd diskFailureInjector): when the
        # BUGGIFY site "sim_fs.fault_profile" is active for a run, newly
        # opened sim files get an ambient LATENCY-ONLY profile with these
        # magnitudes (fatal faults — io_error, bit-rot — are injected via
        # explicit DiskFaultProfiles only; see from_knobs).
        self.SIM_DISK_LATENCY_SPIKE_P = 0.01  # per write/sync op
        self.SIM_DISK_LATENCY_SPIKE_S = 0.05  # spike duration
        # Baseline simulated disk-op costs (server/sim_fs.py, tlog
        # fsync): virtual-time latencies every sim write/sync pays even
        # without an injected fault profile.  Promoted from module-level
        # constants by flowlint FTL008.
        self.SIM_DISK_WRITE_LATENCY_S = 0.0002
        self.SIM_DISK_SYNC_LATENCY_S = 0.0005
        self.TLOG_SIM_FSYNC_S = 0.0005

        # TLog
        self.TLOG_SPILL_THRESHOLD = 1500e6
        # Resident TLog bytes target for the ratekeeper spring (reference
        # TARGET_BYTES_PER_TLOG = 2.4GB vs TLOG_SPILL_THRESHOLD = 1.5GB):
        # sits ABOVE the spill threshold — spilling is the first relief
        # valve (a lagging peeker never throttles the cluster); the rate
        # springs down only when memory grows past what spilling can
        # evict (nothing durable yet => fsync-bound overload).
        self.TLOG_LIMIT_BYTES = 2400e6
        # Byte budget per TLogPeekReply (reference DESIRED_TOTAL_BYTES in
        # tLogPeekMessages): a lagging puller's catch-up peek pages through
        # the spilled backlog instead of materializing all of it at once.
        self.TLOG_PEEK_DESIRED_BYTES = 1e6
        # Upper bound on a GRV batch's TLog liveness confirm + master
        # version fetch (reference TLOG_TIMEOUT in getLiveCommittedVersion):
        # expiry means this proxy's log generation is wedged or displaced
        # and the proxy must DIE VISIBLY so recovery replaces the epoch —
        # a confirm that neither replies nor errors (e.g. the request
        # parked behind a superseded generation) would otherwise wedge
        # every future GRV on this proxy.  Sits well above the nemesis's
        # deliberate <=2 s link clogs so healthy epochs ride those out.
        self.TLOG_CONFIRM_TIMEOUT_S = 5.0
        # Region replication (log_router.py): bound on a LogRouter's
        # buffered bytes — past it, pulling pauses and the primary TLogs
        # absorb the remote lag via spill-by-reference.
        self.LOG_ROUTER_BUFFER_BYTES = 100e6
        self.UPDATE_STORAGE_BYTE_LIMIT = 1e6
        self.MAX_COMMIT_UPDATES = 2000

        # Disaster-recovery polling (backup_worker.py _url_watch, the
        # KillRegion/regionFailover plane + drain waits): base interval,
        # doubling after each no-progress poll up to the cap (the PR-4
        # GRV-starter lesson applied to the DR surface — a converged
        # plane must not be re-polled at the hot interval forever, and
        # chaos-suite dispatch volume is bounded by the cap).
        self.DR_POLL_INTERVAL_S = 0.5
        self.DR_POLL_MAX_INTERVAL_S = 4.0

        # Coordination candidacy lease (coordination.py _expiry_loop): a
        # candidate that neither heartbeats (confirmed leader) nor
        # re-sends a candidacy within this window is evicted from the
        # register — the only way a coordinator can tell a dead
        # candidate's parked long-poll from a live one.
        self.COORD_CANDIDACY_LEASE_S = 3.0

        self._rand("COMMIT_TRANSACTION_BATCH_INTERVAL_MAX",
                   lambda r: r.random01() * 0.1 + 0.001)
        self._rand("RESOLVER_STATE_MEMORY_LIMIT", lambda r: 3e6)


class ClientKnobs(KnobBase):
    def __init__(self) -> None:
        super().__init__()
        self.MAX_BATCH_SIZE = 1000
        # Client-side GRV batching window (GRV_BATCH_ENABLED): must stay
        # BELOW the GRV round trip it amortizes — at 5ms (the old value)
        # the added latency outweighed the saved requests on a local
        # cluster (~2ms RTT), measured as a ~5% e2e commits/s LOSS.
        self.GRV_BATCH_TIMEOUT = 0.001
        self.DEFAULT_BACKOFF = 0.01
        self.DEFAULT_MAX_BACKOFF = 1.0
        self.BACKOFF_GROWTH_RATE = 2.0
        self.TRANSACTION_SIZE_LIMIT = 1 << 24
        self.KEY_SIZE_LIMIT = 10000
        self.VALUE_SIZE_LIMIT = 100000
        # Duplicate a storage read to the next replica when the preferred
        # one hasn't answered within this delay (reference LoadBalance
        # second-request hedging).
        self.HEDGE_REQUEST_DELAY = 0.075
        # Fraction of reads against a TSS-paired primary that are also
        # mirrored to the shadow for comparison (1.0 = every read).
        self.TSS_SAMPLE_RATE = 1.0
        # Client-side GRV batching (ISSUE 14; reference readVersionBatcher
        # in NativeAPI.actor.cpp): concurrent transactions of one Database
        # share a single GetReadVersionRequest (transaction_count = N)
        # instead of each serializing on the GRV proxies.  Only "plain"
        # requests batch (DEFAULT priority, no tags/tenant/debug id) so
        # throttling and predictor identities stay per-request.  OFF by
        # default: the knobs-off pipeline issues exactly one GRV per
        # transaction, bit-identical to the pre-ISSUE-14 client.
        self.GRV_BATCH_ENABLED = False
        # Read-version LEASE (causal-read-risky, default off): a read
        # version obtained from any GRV reply is cached and reused for up
        # to this many seconds, so a hot client loop stops paying one GRV
        # round trip per transaction.  CAVEAT: a leased version may be
        # OLDER than the latest commit — the transaction still reads one
        # consistent MVCC snapshot and OCC still aborts stale read-write
        # conflicts, but a read-only transaction can miss writes
        # committed inside the lease window (the reference's
        # CAUSAL_READ_RISKY trade).  0 disables.
        self.GRV_LEASE_S = 0.0


class Knobs:
    """Process-wide knob singleton bundle."""

    def __init__(self) -> None:
        self.flow = FlowKnobs()
        self.server = ServerKnobs()
        self.client = ClientKnobs()

    def randomize(self, rng: DeterministicRandom) -> None:
        self.flow.randomize(rng)
        self.server.randomize(rng)
        self.client.randomize(rng)


_knobs = Knobs()


def get_knobs() -> Knobs:
    return _knobs


def set_knobs(k: Knobs) -> None:
    global _knobs
    _knobs = k


def server_knobs() -> ServerKnobs:
    return _knobs.server


def client_knobs() -> ClientKnobs:
    return _knobs.client
